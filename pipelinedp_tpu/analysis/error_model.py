"""Closed-form DP error model over flat statistic vectors.

This module is the single source of truth for the utility-analysis math.
Capability parity with the reference's per-partition error modeling
(``analysis/per_partition_combiners.py``) and cross-partition report algebra
(``analysis/cross_partition_combiners.py``), re-designed array-first:

* Every quantity lives in a fixed-width float vector (a "stats row" per
  partition, a "report row" per metric) instead of nested dataclasses. The
  reference merges partitions by recursively walking dataclass fields; here a
  merge is vector addition, so the same code path runs as numpy on the host,
  as an XLA ``segment_sum`` on the device (``analysis/kernels.py``), and as a
  trivially picklable accumulator on distributed backends.
* All per-row formulas broadcast over a leading parameter-configuration axis
  K, so a 64-config sweep is one vectorized evaluation, not 64 combiner
  objects.

Functions take ``xp`` (numpy by default) so the jax kernel can reuse the
identical formulas under tracing.
"""

import math
from typing import List, Optional, Sequence

import numpy as np

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import partition_selection
from pipelinedp_tpu.analysis import metrics as metrics_dc
from pipelinedp_tpu.analysis import poisson_binomial

# ---------------------------------------------------------------------------
# Stats-row schema (per metric, per config, per partition): sufficient
# statistics accumulated additively over a partition's per-privacy-id rows.
# ---------------------------------------------------------------------------
RAW, CLIP_MIN, CLIP_MAX, L0_MEAN, L0_VAR = range(5)
STAT_WIDTH = 5

# Selection-moment schema (per config, per partition): moments of the
# post-l0-bounding privacy-id count (sum of independent Bernoullis).
SEL_MU, SEL_VAR, SEL_SKEW3 = range(3)
SEL_WIDTH = 3

# ---------------------------------------------------------------------------
# Report-row schema (per metric, per config): cross-partition accumulands.
# ABS_* fields are weighted absolute errors; REL_* the same divided by the
# partition's raw value (variances by its square); DROP_* unweighted dropped
# data amounts. Finalization divides ABS/REL by total weight and DROP by the
# metric's total raw sum — replacing the reference's recursive
# dataclass-multiply (``cross_partition_combiners.py:117-150``).
# ---------------------------------------------------------------------------
(ABS_MEAN, ABS_VAR, ABS_RMSE, ABS_RMSE_DROP, ABS_L1, ABS_L1_DROP, ABS_L0_MEAN,
 ABS_L0_VAR, ABS_LINF_MIN, ABS_LINF_MAX, REL_MEAN, REL_VAR, REL_RMSE,
 REL_RMSE_DROP, REL_L1, REL_L1_DROP, REL_L0_MEAN, REL_L0_VAR, REL_LINF_MIN,
 REL_LINF_MAX, DROP_L0, DROP_LINF, DROP_PS, SUM_ACTUAL) = range(24)
REPORT_WIDTH = 24

# Partition-info schema (per config): additive partition bookkeeping.
N_DATASET, N_EMPTY, KEEP_MEAN, KEEP_VAR, WEIGHT = range(5)
INFO_WIDTH = 5

# Beyond this many privacy ids per partition the exact Poisson-binomial PMF
# is replaced by the skew-corrected normal approximation (host path; the
# device kernel always approximates). Matches the reference's accumulator
# size cap (``per_partition_combiners.py:40``).
EXACT_PMF_LIMIT = 100


def keep_fraction(n_partitions, l0, xp=np):
    """P(a contribution survives l0 bounding) = min(1, l0 / n_partitions).

    Broadcasts: ``n_partitions`` is per-row, ``l0`` per-config.
    """
    safe_n = xp.maximum(n_partitions, 1)
    return xp.where(n_partitions > 0, xp.minimum(1.0, l0 / safe_n), 0.0)


def metric_stat_terms(values, lo, hi, keep_q, xp=np):
    """Per-row contributions to the 5 metric sufficient statistics.

    Args:
      values: per-row metric values (count / indicator / sum), shape [..., N].
      lo, hi: clipping bounds, broadcastable (e.g. [K, 1] against [N]).
      keep_q: per-row l0 keep fraction, same broadcast shape as the output.

    Returns:
      Array [..., N, STAT_WIDTH]; summing over N (or segment-summing over a
      partition index) yields the partition's stats row.
    """
    clipped = xp.clip(values, lo, hi)
    err = clipped - values
    raw = xp.broadcast_to(values, clipped.shape)
    return xp.stack(
        [
            raw,
            xp.where(values < lo, err, xp.zeros_like(err)),
            xp.where(values > hi, err, xp.zeros_like(err)),
            -clipped * (1.0 - keep_q),
            clipped * clipped * keep_q * (1.0 - keep_q),
        ],
        axis=-1,
    )


def selection_moment_terms(keep_q, xp=np):
    """Per-row Bernoulli moment contributions [..., N, SEL_WIDTH]."""
    centered = keep_q * (1.0 - keep_q)
    return xp.stack([keep_q, centered, centered * (1.0 - 2.0 * keep_q)],
                    axis=-1)


def metric_report_terms(stats, keep_prob, weight, noise_std, xp=np):
    """Per-partition report row [..., REPORT_WIDTH] from a stats row.

    Args:
      stats: [..., STAT_WIDTH] per-partition metric statistics.
      keep_prob: partition keep probability, broadcastable to stats[..., 0].
      weight: cross-partition averaging weight (same broadcast).
      noise_std: DP noise stddev (per-config scalar or broadcastable array).
    """
    raw = stats[..., RAW]
    mn = stats[..., CLIP_MIN]
    mx = stats[..., CLIP_MAX]
    l0m = stats[..., L0_MEAN]
    l0v = stats[..., L0_VAR]
    mean = l0m + mn + mx
    var = l0v + noise_std * noise_std
    rmse = xp.sqrt(mean * mean + var)
    rmse_drop = keep_prob * rmse + (1.0 - keep_prob) * xp.abs(raw)
    zero = xp.zeros_like(raw)
    # Relative errors divide by the raw value (variances by its square);
    # raw == 0 contributes zeros (metrics_dc.ValueErrors.to_relative).
    inv = xp.where(raw != 0, 1.0 / xp.where(raw != 0, raw, 1.0), 0.0)
    inv2 = inv * inv
    abs_fields = [mean, var, rmse, rmse_drop, zero, zero, l0m, l0v, mn, mx]
    rel_fields = [
        mean * inv, var * inv2, rmse * inv, rmse_drop * inv, zero, zero,
        l0m * inv, l0v * inv2, mn * inv, mx * inv
    ]
    drop_l0 = -l0m
    drop_linf = mn - mx
    drop_ps = (raw - drop_l0 - drop_linf) * (1.0 - keep_prob)
    weighted = [f * weight for f in abs_fields + rel_fields]
    return xp.stack(weighted + [drop_l0, drop_linf, drop_ps, raw], axis=-1)


def info_terms(n_users, keep_prob, weight, public: bool, xp=np):
    """Per-partition info row [..., INFO_WIDTH].

    All inputs broadcast against ``keep_prob``'s shape.
    """
    one = xp.ones_like(keep_prob)
    zero = xp.zeros_like(one)
    if public:
        non_empty = xp.where(n_users > 0, one, zero)
        return xp.stack(
            [non_empty, 1.0 - non_empty, zero, zero, one * weight], axis=-1)
    return xp.stack(
        [one, zero, keep_prob, keep_prob * (1.0 - keep_prob), weight * one],
        axis=-1)


# ---------------------------------------------------------------------------
# Host-side keep probability (exact for small partitions).
# ---------------------------------------------------------------------------


def _pmf_keep_probability(pmf, selector) -> float:
    """Integrates the selector's keep probability over an id-count PMF —
    one vectorized dot product instead of per-integer strategy calls."""
    counts = np.arange(pmf.start, pmf.start + len(pmf.probabilities))
    keep = selector.probability_of_keep_vec(counts)
    return float(np.clip(np.dot(pmf.probabilities, keep), 0.0, 1.0))


def host_keep_probability(per_row_q: np.ndarray,
                          selector) -> float:
    """P(partition kept) for one partition and one config.

    per_row_q: [M] keep fraction per contributing privacy id. Uses the exact
    Poisson-binomial PMF for at most EXACT_PMF_LIMIT ids, the refined-normal
    approximation beyond (reference ``per_partition_combiners.py:96-150``).
    """
    m = len(per_row_q)
    if m == 0:
        return 0.0
    if m <= EXACT_PMF_LIMIT:
        pmf = poisson_binomial.compute_pmf(list(per_row_q))
    else:
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(
            list(per_row_q))
        pmf = poisson_binomial.compute_pmf_approximation(exp, std, skew, m)
    return _pmf_keep_probability(pmf, selector)


def host_keep_probability_from_moments(mu: float, var: float, third: float,
                                       n_users: int, selector) -> float:
    """P(partition kept) from accumulated Bernoulli moments (the dense
    accumulator path — per-row keep fractions no longer available)."""
    if n_users == 0:
        return 0.0
    std = math.sqrt(max(var, 0.0))
    skew = 0.0 if std == 0 else third / std**3
    pmf = poisson_binomial.compute_pmf_approximation(mu, std, skew, n_users)
    return _pmf_keep_probability(pmf, selector)


# ---------------------------------------------------------------------------
# Config plumbing: noise stds, selectors and metric bounds per configuration.
# ---------------------------------------------------------------------------

# Canonical metric order inside stats/report matrices.
ANALYSIS_METRICS = (agg.Metrics.SUM, agg.Metrics.COUNT,
                    agg.Metrics.PRIVACY_ID_COUNT)


def ordered_metrics(params: agg.AggregateParams) -> List[agg.Metric]:
    """The analyzed metrics in canonical matrix order."""
    return [m for m in ANALYSIS_METRICS if m in params.metrics]


def metric_bounds(params: agg.AggregateParams, metric: agg.Metric):
    """(lo, hi) clipping bounds applied to the metric's per-row value."""
    if metric == agg.Metrics.SUM:
        return params.min_sum_per_partition, params.max_sum_per_partition
    if metric == agg.Metrics.COUNT:
        return 0.0, float(params.max_contributions_per_partition)
    if metric == agg.Metrics.PRIVACY_ID_COUNT:
        return 0.0, 1.0
    raise ValueError(f"Unsupported analysis metric {metric}")


def metric_values(metric: agg.Metric, counts: np.ndarray, sums: np.ndarray,
                  xp=np):
    """The per-row value the metric aggregates."""
    if metric == agg.Metrics.SUM:
        return sums
    if metric == agg.Metrics.COUNT:
        return counts
    if metric == agg.Metrics.PRIVACY_ID_COUNT:
        return xp.where(counts > 0, xp.ones_like(counts),
                        xp.zeros_like(counts))
    raise ValueError(f"Unsupported analysis metric {metric}")


def config_noise_std(params: agg.AggregateParams, metric: agg.Metric,
                     eps: float, delta: float) -> float:
    """DP noise stddev for one (config, metric).

    All analysis metrics behave as bounded sums with l0 = l0 bound and linf =
    max contributions (reference ``per_partition_combiners.py:270``: the
    count-noise formula is used for SUM analysis as well).
    """
    linf = params.max_contributions_per_partition
    if metric == agg.Metrics.PRIVACY_ID_COUNT:
        linf = 1
    scalar = dp_computations.ScalarNoiseParams(
        eps, delta, params.min_value, params.max_value,
        params.min_sum_per_partition, params.max_sum_per_partition,
        params.max_partitions_contributed, linf, params.noise_kind)
    return dp_computations.compute_dp_count_noise_std(scalar)


def config_selector(params: agg.AggregateParams, eps: float, delta: float):
    """The host partition-selection strategy for one configuration."""
    return partition_selection.create_partition_selection_strategy(
        params.partition_selection_strategy, eps, delta,
        params.max_partitions_contributed, params.pre_threshold)


# ---------------------------------------------------------------------------
# Per-partition analysis (host path): arrays in, flat result tuple out.
# ---------------------------------------------------------------------------


def partition_stats(counts: np.ndarray, sums: np.ndarray,
                    n_partitions: np.ndarray,
                    config_params: Sequence[agg.AggregateParams],
                    metric_list: Sequence[agg.Metric]) -> np.ndarray:
    """Stats matrix [K, n_metrics, STAT_WIDTH] for one partition's rows."""
    k = len(config_params)
    n_metrics = len(metric_list)
    out = np.zeros((k, n_metrics, STAT_WIDTH))
    if len(counts) == 0:
        return out
    l0 = np.array([[p.max_partitions_contributed] for p in config_params],
                  dtype=np.float64)
    q = keep_fraction(np.asarray(n_partitions, dtype=np.float64)[None, :], l0)
    for mi, metric in enumerate(metric_list):
        values = metric_values(metric, np.asarray(counts, dtype=np.float64),
                               np.asarray(sums, dtype=np.float64))
        lo = np.array([[metric_bounds(p, metric)[0]] for p in config_params])
        hi = np.array([[metric_bounds(p, metric)[1]] for p in config_params])
        out[:, mi, :] = metric_stat_terms(values[None, :], lo, hi,
                                          q).sum(axis=-2)
    return out


def stats_to_sum_metrics(stats_row: np.ndarray, metric: agg.Metric,
                         noise_std: float,
                         noise_kind: agg.NoiseKind) -> metrics_dc.SumMetrics:
    """One metric's per-partition SumMetrics from its stats row."""
    return metrics_dc.SumMetrics(
        aggregation=metric,
        sum=float(stats_row[RAW]),
        clipping_to_min_error=float(stats_row[CLIP_MIN]),
        clipping_to_max_error=float(stats_row[CLIP_MAX]),
        expected_l0_bounding_error=float(stats_row[L0_MEAN]),
        std_l0_bounding_error=math.sqrt(max(float(stats_row[L0_VAR]), 0.0)),
        std_noise=noise_std,
        noise_kind=noise_kind)


# ---------------------------------------------------------------------------
# Report finalization: summed report/info rows -> result dataclasses.
# ---------------------------------------------------------------------------


def finalize_value_errors(fields: np.ndarray,
                          total_weight: float) -> metrics_dc.ValueErrors:
    """ValueErrors from 10 accumulated (weighted) fields."""
    scale = 0.0 if total_weight == 0 else 1.0 / total_weight
    (mean, var, rmse, rmse_drop, l1, l1_drop, l0_mean, l0_var, linf_min,
     linf_max) = (float(f) * scale for f in fields)
    return metrics_dc.ValueErrors(
        bounding_errors=metrics_dc.ContributionBoundingErrors(
            l0=metrics_dc.MeanVariance(l0_mean, l0_var),
            linf_min=linf_min,
            linf_max=linf_max),
        mean=mean,
        variance=var,
        rmse=rmse,
        l1=l1,
        rmse_with_dropped_partitions=rmse_drop,
        l1_with_dropped_partitions=l1_drop)


def finalize_metric_utility(report_row: np.ndarray, metric: agg.Metric,
                            noise_std: float, noise_kind: agg.NoiseKind,
                            total_weight: float) -> metrics_dc.MetricUtility:
    """MetricUtility from one metric's accumulated report row."""
    sum_actual = float(report_row[SUM_ACTUAL])
    drop_scale = 1.0 if sum_actual == 0 else 1.0 / sum_actual
    data_dropped = metrics_dc.DataDropInfo(
        l0=float(report_row[DROP_L0]) * drop_scale,
        linf=float(report_row[DROP_LINF]) * drop_scale,
        partition_selection=float(report_row[DROP_PS]) * drop_scale)
    return metrics_dc.MetricUtility(
        metric=metric,
        noise_std=noise_std,
        noise_kind=noise_kind,
        ratio_data_dropped=data_dropped,
        absolute_error=finalize_value_errors(
            report_row[ABS_MEAN:ABS_LINF_MAX + 1], total_weight),
        relative_error=finalize_value_errors(
            report_row[REL_MEAN:REL_LINF_MAX + 1], total_weight))


def finalize_partitions_info(info_row: np.ndarray,
                             public: bool) -> metrics_dc.PartitionsInfo:
    """PartitionsInfo from an accumulated info row."""
    if public:
        return metrics_dc.PartitionsInfo(
            public_partitions=True,
            num_dataset_partitions=int(round(float(info_row[N_DATASET]))),
            num_non_public_partitions=0,
            num_empty_partitions=int(round(float(info_row[N_EMPTY]))))
    return metrics_dc.PartitionsInfo(
        public_partitions=False,
        num_dataset_partitions=int(round(float(info_row[N_DATASET]))),
        kept_partitions=metrics_dc.MeanVariance(float(info_row[KEEP_MEAN]),
                                                float(info_row[KEEP_VAR])))


def finalize_utility_report(
        report_rows: np.ndarray, info_row: np.ndarray,
        metric_list: Sequence[agg.Metric], noise_stds: Sequence[float],
        noise_kind: agg.NoiseKind, public: bool,
        configuration_index: int = -1) -> metrics_dc.UtilityReport:
    """UtilityReport from accumulated [n_metrics, REPORT_WIDTH] + info rows."""
    total_weight = float(info_row[WEIGHT])
    metric_errors = None
    if len(metric_list):
        metric_errors = [
            finalize_metric_utility(report_rows[mi], metric, noise_stds[mi],
                                    noise_kind, total_weight)
            for mi, metric in enumerate(metric_list)
        ]
    return metrics_dc.UtilityReport(
        configuration_index=configuration_index,
        partitions_info=finalize_partitions_info(info_row, public),
        metric_errors=metric_errors)
