"""Fused utility-analysis sweep kernel (jax).

The whole multi-configuration utility analysis — l0 keep fractions, clipping
error statistics, partition-selection keep probabilities and cross-partition
report reduction — runs as ONE jit-compiled XLA program over columnar row
arrays, with the parameter-configuration axis K materialized as an array
dimension (BASELINE config 5: a 64-budget ε-sweep is a single compiled
program, not 64 pipeline passes).

Capability parity with the reference's vectorized accumulators
(``analysis/per_partition_combiners.py:339-431``) and report reduction
(``analysis/cross_partition_combiners.py``); the formulas are shared with the
host path via ``analysis/error_model.py`` (xp=jnp).

Memory shape: configs are processed in chunks of ``config_chunk`` via
``lax.map`` and the partition-selection PMF windows in chunks of
``partition_chunk`` partitions, so peak usage is bounded regardless of K x P.
"""

import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu.analysis import error_model as em
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.runtime import trace as rt_trace


def _generate_bucket_bounds() -> Tuple[int, ...]:
    """Partition-size histogram buckets: [0, 1] + [1, 2, 5] * 10^i."""
    result = [0, 1]
    for i in range(1, 10):
        result += [10**i, 2 * 10**i, 5 * 10**i]
    return tuple(result)


BUCKET_BOUNDS = _generate_bucket_bounds()
N_BUCKETS = len(BUCKET_BOUNDS)

# Metric codes (static kernel arguments; jittable stand-ins for the enum).
METRIC_CODES = {
    agg.Metrics.SUM: 0,
    agg.Metrics.COUNT: 1,
    agg.Metrics.PRIVACY_ID_COUNT: 2,
}


class SweepConfigArrays(NamedTuple):
    """Per-configuration parameter arrays (all shape [K] or [K, n_metrics])."""
    l0: np.ndarray  # max_partitions_contributed
    lo: np.ndarray  # [K, n_metrics] clip lower bounds
    hi: np.ndarray  # [K, n_metrics] clip upper bounds
    noise_std: np.ndarray  # [K, n_metrics]
    # Partition-selection scalars (see ops/selection_ops.SelectionParams):
    sel_kind: np.ndarray
    sel_pre_shift: np.ndarray
    sel_eps1: np.ndarray
    sel_delta1: np.ndarray
    sel_n_cross: np.ndarray
    sel_pi_cross: np.ndarray
    sel_threshold: np.ndarray
    sel_scale: np.ndarray


def build_config_arrays(
        config_params: Sequence[agg.AggregateParams],
        metric_list: Sequence[agg.Metric],
        noise_stds: np.ndarray,
        selection_budget: Optional[Tuple[float, float]]) -> SweepConfigArrays:
    """Packs per-config AggregateParams into kernel input arrays.

    noise_stds: [K, n_metrics] precomputed DP noise stddevs.
    selection_budget: (eps, delta) of the partition-selection mechanism, or
      None for public partitions.
    """
    k = len(config_params)
    n_metrics = max(len(metric_list), 1)
    lo = np.zeros((k, n_metrics))
    hi = np.zeros((k, n_metrics))
    for ki, params in enumerate(config_params):
        for mi, metric in enumerate(metric_list):
            lo[ki, mi], hi[ki, mi] = em.metric_bounds(params, metric)
    sel = np.zeros((8, k))
    # Benign defaults (Laplace thresholding with scale 1) so padded/public
    # entries never produce NaNs inside unused where-branches.
    sel[0, :] = 1
    sel[7, :] = 1.0
    if selection_budget is not None:
        eps, delta = selection_budget
        for ki, params in enumerate(config_params):
            sp = selection_ops.selection_params_from_host(
                params.partition_selection_strategy, eps, delta,
                params.max_partitions_contributed, params.pre_threshold)
            sel[:, ki] = (sp.kind, sp.pre_shift, sp.eps1, sp.delta1,
                          sp.n_cross, sp.pi_cross, sp.threshold, sp.scale)
    return SweepConfigArrays(
        l0=np.array([p.max_partitions_contributed for p in config_params],
                    dtype=np.float64),
        lo=lo,
        hi=hi,
        noise_std=np.asarray(noise_stds, dtype=np.float64),
        sel_kind=sel[0],
        sel_pre_shift=sel[1],
        sel_eps1=sel[2],
        sel_delta1=sel[3],
        sel_n_cross=sel[4],
        sel_pi_cross=sel[5],
        sel_threshold=sel[6],
        sel_scale=sel[7])


def _keep_prob_batch(xs: jnp.ndarray, cfg: SweepConfigArrays) -> jnp.ndarray:
    """Selector keep probability at (possibly fractional) id-counts xs.

    xs: [KC, ...]; per-config selector scalars broadcast from cfg (traced
    arrays — unlike ops/selection_ops.keep_probabilities, which specializes
    on static python scalars). Branches for all three strategy kinds are
    evaluated and where-selected, with inert parameters sanitized so unused
    branches stay finite.
    """
    shape = (-1,) + (1,) * (xs.ndim - 1)
    is_tg = cfg.sel_kind == 0
    kind = cfg.sel_kind.reshape(shape)
    n = xs - cfg.sel_pre_shift.reshape(shape)
    eps1 = jnp.where(is_tg, cfg.sel_eps1, 1.0).reshape(shape)
    delta1 = jnp.where(is_tg, cfg.sel_delta1, 0.5).reshape(shape)
    n_cross = cfg.sel_n_cross.reshape(shape)
    pi_cross = cfg.sel_pi_cross.reshape(shape)
    threshold = cfg.sel_threshold.reshape(shape)
    scale = jnp.maximum(cfg.sel_scale.reshape(shape), 1e-30)
    # Truncated geometric (partition_selection.py closed form, log-space).
    n_eff = jnp.maximum(n, 1.0)
    n1 = jnp.minimum(n_eff, n_cross)
    log_pi1 = (jnp.log(delta1) + (n1 - 1.0) * eps1 +
               jnp.log1p(-jnp.exp(-n1 * eps1)) - jnp.log1p(-jnp.exp(-eps1)))
    pi1 = jnp.exp(jnp.minimum(log_pi1, 0.0))
    k = jnp.maximum(n_eff - n_cross, 0.0)
    decay = jnp.exp(-k * eps1)
    geo = jnp.where(eps1 < 700.0,
                    jnp.exp(-eps1) * (1.0 - decay) /
                    (1.0 - jnp.exp(-jnp.minimum(eps1, 700.0))), 0.0)
    q = decay * (1.0 - pi_cross) - delta1 * geo
    p_tg = jnp.clip(jnp.where(n_eff <= n_cross, pi1, 1.0 - jnp.maximum(q, 0)),
                    0.0, 1.0)
    # Laplace thresholding.
    z = (n - threshold) / scale
    p_lap = jnp.where(z >= 0, 1.0 - 0.5 * jnp.exp(-jnp.abs(z)),
                      0.5 * jnp.exp(-jnp.abs(z)))
    # Gaussian thresholding.
    zg = (threshold - n) / scale
    p_gauss = 0.5 * jax.scipy.special.erfc(zg / jnp.sqrt(2.0))
    probs = jnp.where(kind == 0, p_tg, jnp.where(kind == 1, p_lap, p_gauss))
    return jnp.where(n <= 0, 0.0, probs)


def _norm_cdf_skew(z: jnp.ndarray, skew: jnp.ndarray) -> jnp.ndarray:
    """Skew-corrected normal CDF (poisson_binomial.compute_pmf_approximation)."""
    cdf = 0.5 * jax.scipy.special.erfc(-z / jnp.sqrt(2.0))
    pdf = jnp.exp(-0.5 * z * z) / jnp.sqrt(2.0 * jnp.pi)
    return jnp.clip(cdf + skew * (1.0 - z * z) * pdf / 6.0, 0.0, 1.0)


def _windowed_keep_prob(mu, var, third, n_users, cfg: SweepConfigArrays, *,
                        window: int, partition_chunk: int) -> jnp.ndarray:
    """P(partition kept) from Poisson-binomial moments, per (config, pk).

    Integrates the selector keep probability against the refined-normal PMF
    over a ``window``-point support grid per partition (step >= 1 id), chunked
    over the partition axis. mu/var/third: [KC, P]; n_users: [P].
    """
    kc, p = mu.shape
    pad = (-p) % partition_chunk
    n_chunks = (p + pad) // partition_chunk

    def pad_t(x):  # [KC, P] -> [n_chunks, KC, partition_chunk]
        x = jnp.pad(x, ((0, 0), (0, pad)))
        return x.reshape(kc, n_chunks, partition_chunk).transpose(1, 0, 2)

    mu_c, var_c, third_c = pad_t(mu), pad_t(var), pad_t(third)
    n_c = jnp.pad(n_users, (0, pad)).reshape(n_chunks,
                                             partition_chunk)[:, None, :]

    def chunk(args):
        mu, var, third, n_users = args  # [KC, PC] / [1, PC]
        sigma = jnp.sqrt(jnp.maximum(var, 0.0))
        safe_sigma = jnp.maximum(sigma, 1e-30)
        skew = jnp.where(sigma > 0, third / safe_sigma**3, 0.0)
        step = jnp.maximum(1.0, 16.0 * sigma / window)
        offsets = jnp.arange(window) - (window - 1) / 2.0  # [W]
        xs = mu[..., None] + offsets * step[..., None]  # [KC, PC, W]
        z_hi = (xs + 0.5 * step[..., None] - mu[..., None]) / safe_sigma[...,
                                                                         None]
        z_lo = (xs - 0.5 * step[..., None] - mu[..., None]) / safe_sigma[...,
                                                                         None]
        sk = skew[..., None]
        pmf = jnp.maximum(
            _norm_cdf_skew(z_hi, sk) - _norm_cdf_skew(z_lo, sk), 0.0)
        # Restrict support to [0, n_users] like the host PMF.
        support = (xs > -0.5) & (xs <= n_users[..., None] + 0.5)
        pmf = jnp.where(support, pmf, 0.0)
        keep = _keep_prob_batch(xs, cfg)
        p_win = jnp.sum(pmf * keep, axis=-1)
        # Degenerate sigma: all-or-nothing ids -> PMF concentrated at mu.
        p_point = _keep_prob_batch(jnp.round(mu), cfg)
        return jnp.clip(jnp.where(sigma > 0, p_win, p_point), 0.0, 1.0)

    out = jax.lax.map(chunk, (mu_c, var_c, third_c, n_c))  # [n_chunks,KC,PC]
    return out.transpose(1, 0, 2).reshape(kc, -1)[:, :p]


@functools.partial(
    jax.jit,
    static_argnames=("n_partitions_total", "metric_codes", "public",
                     "config_chunk", "window", "partition_chunk",
                     "return_per_partition", "psum_axis"))
def sweep_kernel(counts,
                 sums,
                 contributed,
                 pk_idx,
                 cfg: SweepConfigArrays,
                 *,
                 n_partitions_total: int,
                 metric_codes: Tuple[int, ...],
                 public: bool,
                 config_chunk: int = 8,
                 window: int = 64,
                 partition_chunk: int = 4096,
                 return_per_partition: bool = True,
                 psum_axis: Optional[str] = None):
    """The fused analysis sweep.

    Args:
      counts/sums/contributed: per-(privacy_id, partition) row arrays [N]
        (contribution count, value sum, partitions contributed by the id).
      pk_idx: dense partition index per row [N], in [0, n_partitions_total);
        out-of-range indices (padding) contribute nothing.
      cfg: SweepConfigArrays with leading config axis K.
      metric_codes: static tuple of METRIC_CODES values, canonical order.
      psum_axis: when run per-shard under shard_map over row-split inputs,
        the mesh axis to psum the per-partition sufficient statistics over.
        Every downstream quantity (keep probabilities, report rows, bucket
        reduction) is a deterministic function of those sums — the sweep
        draws no randomness — so it computes replicated on every shard.
      public: public-partition analysis (keep probability 1, empty-partition
        bookkeeping) vs private selection modeling.

    Returns dict with:
      bucket_rows: [K, N_BUCKETS, n_metrics, REPORT_WIDTH]
      bucket_info: [K, N_BUCKETS, INFO_WIDTH]
      and, when return_per_partition: stats [K, P, n_metrics, STAT_WIDTH],
      keep_prob [K, P], n_users [P], n_rows [P].
    """
    f = counts.dtype
    p_total = n_partitions_total
    n_metrics = max(len(metric_codes), 1)
    ones = jnp.ones_like(counts)
    seg = functools.partial(jax.ops.segment_sum,
                            num_segments=p_total,
                            indices_are_sorted=False)

    def globalize(x):
        return x if psum_axis is None else jax.lax.psum(x, psum_axis)

    n_users = globalize(seg(ones, pk_idx))
    n_rows = globalize(seg(counts, pk_idx))

    metric_vals = []
    for code in metric_codes:
        if code == 0:
            metric_vals.append(sums)
        elif code == 1:
            metric_vals.append(counts)
        else:
            metric_vals.append(jnp.where(counts > 0, ones, 0.0))
    # Partition size (for the report histogram): first metric's raw sum,
    # privacy-id count for select-partitions analysis.
    size = globalize(seg(metric_vals[0],
                         pk_idx)) if metric_codes else n_users
    bounds = jnp.asarray(BUCKET_BOUNDS, dtype=f)
    bucket = jnp.clip(
        jnp.searchsorted(bounds, size, side="right") - 1, 0, N_BUCKETS - 1)
    bseg = functools.partial(jax.ops.segment_sum, num_segments=N_BUCKETS)

    k_total = cfg.l0.shape[0]
    kc = min(config_chunk, k_total)
    pad_k = (-k_total) % kc

    n_cfg_chunks = (k_total + pad_k) // kc

    def pad_cfg(x):
        widths = ((0, pad_k),) + ((0, 0),) * (x.ndim - 1)
        # Padded configs reuse config 0 so every branch stays numerically
        # benign; their outputs are sliced off below. Explicit chunk count:
        # -1 inference fails on zero-width dims (n_metrics == 0).
        return jnp.pad(x, widths, mode="edge").reshape(
            (n_cfg_chunks, kc) + x.shape[1:])

    cfg_chunks = SweepConfigArrays(*[pad_cfg(jnp.asarray(x)) for x in cfg])

    def chunk_fn(c: SweepConfigArrays):
        q = em.keep_fraction(contributed[None, :], c.l0[:, None], xp=jnp)
        stats = []
        for mi in range(len(metric_codes)):
            terms = em.metric_stat_terms(metric_vals[mi][None, :],
                                         c.lo[:, mi:mi + 1],
                                         c.hi[:, mi:mi + 1],
                                         q,
                                         xp=jnp)  # [KC, N, 5]
            stats.append(jax.vmap(lambda t: seg(t, pk_idx))(terms))
        stats = (globalize(jnp.stack(stats, axis=2)) if stats else jnp.zeros(
            (kc, p_total, 0, em.STAT_WIDTH), dtype=f))  # [KC, P, M, 5]
        if public:
            keep_prob = jnp.ones((kc, p_total), dtype=f)
            weight = keep_prob
        else:
            sel_terms = em.selection_moment_terms(q, xp=jnp)  # [KC, N, 3]
            sel = globalize(
                jax.vmap(lambda t: seg(t, pk_idx))(sel_terms))  # [KC, P, 3]
            keep_prob = _windowed_keep_prob(sel[..., em.SEL_MU],
                                            sel[..., em.SEL_VAR],
                                            sel[..., em.SEL_SKEW3],
                                            n_users,
                                            c,
                                            window=window,
                                            partition_chunk=partition_chunk)
            weight = keep_prob
        rows = em.metric_report_terms(stats, keep_prob[..., None],
                                      weight[..., None],
                                      c.noise_std[:, None, :],
                                      xp=jnp)  # [KC, P, M, 24]
        info = em.info_terms(n_users[None, :], keep_prob, weight, public,
                             xp=jnp)  # [KC, P, 5]
        bucket_rows = jax.vmap(lambda r: bseg(r, bucket))(rows)
        bucket_info = jax.vmap(lambda r: bseg(r, bucket))(info)
        if return_per_partition:
            return bucket_rows, bucket_info, stats, keep_prob
        return bucket_rows, bucket_info

    outs = jax.lax.map(chunk_fn, cfg_chunks)

    def unchunk(x):  # [n_chunks, KC, ...] -> [K, ...]
        # Explicit leading size: -1 inference fails on zero-width trailing
        # dims (select-partitions analysis has n_metrics == 0).
        return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])[:k_total]

    result = {
        "bucket_rows": unchunk(outs[0]),
        "bucket_info": unchunk(outs[1]),
        "n_users": n_users,
        "n_rows": n_rows,
        "bucket": bucket,
    }
    if return_per_partition:
        result["stats"] = unchunk(outs[2])
        result["keep_prob"] = unchunk(outs[3])
    return result


# Compile/dispatch attribution (runtime/trace.probe_jit, enforced by
# staticcheck's jit-boundary rule): sweep compiles are real wall time in
# utility-analysis runs and must show up in the e2e gap accounting.
sweep_kernel = rt_trace.probe_jit("sweep_kernel", sweep_kernel)


def sharded_sweep(mesh,
                  counts,
                  sums,
                  contributed,
                  pk_idx,
                  cfg: SweepConfigArrays,
                  *,
                  n_partitions_total: int,
                  metric_codes: Tuple[int, ...],
                  public: bool,
                  return_per_partition: bool = True,
                  config_chunk: int = 8,
                  window: int = 64,
                  partition_chunk: int = 4096):
    """Multi-chip analysis sweep: rows split over a mesh, psum'd statistics.

    BASELINE config 5's v5e-16 shape: each shard segment-sums its row split
    into per-partition sufficient statistics, psums over ICI make them
    global (three size-[P] psums for n_users/n_rows/size plus two
    size-[config_chunk, P, ...] psums per config chunk), and the
    (randomness-free) keep-probability and report phases run replicated —
    results identical on every shard. Rows need no co-location (per-row
    keep fractions depend only on each row's own n_partitions value,
    computed at preaggregation).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS

    n_shards = mesh.devices.size
    n = len(counts)
    pad = (-n) % n_shards

    def pad_rows(a, fill=0):
        return np.pad(np.asarray(a), (0, pad), constant_values=fill)

    counts = pad_rows(counts)
    sums = pad_rows(sums)
    contributed = pad_rows(contributed)
    # Out-of-range partition ids are dropped by segment_sum: padding rows
    # contribute nothing.
    pk_idx = pad_rows(pk_idx, n_partitions_total)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))
    row_args = [
        jax.device_put(jnp.asarray(a), sharding)
        for a in (counts, sums, contributed, pk_idx)
    ]
    cfg = SweepConfigArrays(*[jnp.asarray(x) for x in cfg])

    def per_shard(counts_s, sums_s, contributed_s, pk_s, cfg_r):
        return sweep_kernel(counts_s,
                            sums_s,
                            contributed_s,
                            pk_s,
                            cfg_r,
                            n_partitions_total=n_partitions_total,
                            metric_codes=metric_codes,
                            public=public,
                            config_chunk=config_chunk,
                            window=window,
                            partition_chunk=partition_chunk,
                            return_per_partition=return_per_partition,
                            psum_axis=SHARD_AXIS)

    from pipelinedp_tpu.parallel.mesh import shard_map
    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                             P(SHARD_AXIS), P()),
                   out_specs=P())
    return fn(*row_args, cfg)
