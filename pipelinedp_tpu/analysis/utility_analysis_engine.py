"""Utility-analysis engine: builds the per-partition analysis pipeline.

Capability parity with the reference ``analysis/utility_analysis_engine.py``
(analyze() returns a lazy collection of (partition_key, flat per-config
results); budget requests mirror the real aggregation's split). Re-designed:
the reference subclasses DPEngine and swaps graph nodes (combiners, bounders,
selection) to bend the DP dataflow into an analysis dataflow; here the
analysis pipeline is built directly — extract -> public filter ->
preaggregate -> group by partition -> one vectorized
``PerPartitionAnalyzer`` pass — since none of the DP stages (noising,
thresholding, selection) actually run during analysis.

The TPU path (``utility_analysis.perform_utility_analysis`` on a
LocalBackend/TPUBackend) bypasses this pipeline entirely and lowers the same
math to ``analysis/kernels.sweep_kernel``.
"""

from typing import Optional, Union

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import data_extractors as extractors
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu.analysis import contribution_bounders as analysis_bounders
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import error_model as em
from pipelinedp_tpu.analysis import per_partition_combiners


class UtilityAnalysisEngine:
    """Performs utility analysis for DP aggregations."""

    def __init__(self, budget_accountant: budget_accounting.BudgetAccountant,
                 backend: pipeline_backend.PipelineBackend):
        self._budget_accountant = budget_accountant
        self._backend = backend

    def aggregate(self, col, params, data_extractors, public_partitions=None):
        raise ValueError("UtilityAnalysisEngine.aggregate can't be called.\n"
                         "If you'd like to perform utility analysis, use "
                         "UtilityAnalysisEngine.analyze.\n"
                         "If you'd like to perform DP computations, use "
                         "DPEngine.aggregate.")

    def request_budgets(
            self, options: 'data_structures.UtilityAnalysisOptions',
            public_partitions) -> per_partition_combiners.PerPartitionAnalyzer:
        """Requests the budget split the real aggregation would make and
        returns the analyzer bound to the (lazily finalized) specs.

        One GENERIC request models private partition selection, one request
        per metric models its noise mechanism; all configurations share these
        specs (the sweep varies sensitivities, not the budget split).
        """
        params = options.aggregate_params
        metric_list = em.ordered_metrics(params)
        with self._budget_accountant.scope(weight=params.budget_weight):
            selection_spec = None
            if public_partitions is None:
                selection_spec = self._budget_accountant.request_budget(
                    agg.MechanismType.GENERIC, weight=params.budget_weight)
            mechanism_type = params.noise_kind.convert_to_mechanism_type()
            metric_specs = [
                self._budget_accountant.request_budget(
                    mechanism_type, weight=params.budget_weight)
                for _ in metric_list
            ]
        return per_partition_combiners.PerPartitionAnalyzer(
            config_params=list(data_structures.get_aggregate_params(options)),
            metric_list=metric_list,
            metric_specs=metric_specs,
            selection_spec=selection_spec)

    def preaggregated_rows(
            self, col, options: 'data_structures.UtilityAnalysisOptions',
            data_extractors: Union[extractors.DataExtractors,
                                   extractors.PreAggregateExtractors],
            public_partitions):
        """(partition_key, (count, sum, n_partitions, n_contributions)) rows.

        Public filtering happens before cross-partition statistics are taken
        (matching DPEngine._aggregate's stage order), so n_partitions counts
        only partitions that survive the public filter.
        """
        backend = self._backend
        if options.pre_aggregated_data:
            col = backend.map(
                col, lambda row: (data_extractors.partition_extractor(row),
                                  data_extractors.preaggregate_extractor(row)),
                "Extract (partition_key, preaggregate_data)")
            if public_partitions is not None:
                col = backend.filter_by_key(
                    col, public_partitions,
                    "Filter out non-public partitions")
            return col
        col = backend.map(
            col, lambda row: (data_extractors.privacy_id_extractor(row),
                              data_extractors.partition_extractor(row),
                              data_extractors.value_extractor(row)),
            "Extract (privacy_id, partition_key, value)")
        if public_partitions is not None:
            col = backend.map(col, lambda row: (row[1], row),
                              "Key by partition")
            col = backend.filter_by_key(col, public_partitions,
                                        "Filter out non-public partitions")
            col = backend.values(col, "Drop key")
        bounder = analysis_bounders.AnalysisContributionBounder(
            options.partitions_sampling_prob)
        col = bounder.bound_contributions(col,
                                          params=None,
                                          backend=backend,
                                          report_generator=None,
                                          aggregate_fn=lambda x: x)
        # ((privacy_id, partition_key), preaggregated row)
        return backend.map(col, lambda row: (row[0][1], row[1]),
                           "Drop privacy id")

    def analyze(self,
                col,
                options: 'data_structures.UtilityAnalysisOptions',
                data_extractors: Union[extractors.DataExtractors,
                                       extractors.PreAggregateExtractors],
                public_partitions=None,
                analyzer: Optional[
                    per_partition_combiners.PerPartitionAnalyzer] = None):
        """Per-partition utility analysis.

        Returns a lazy collection of (partition_key, flat results tuple) —
        see PerPartitionAnalyzer.analyze_rows for the tuple layout. Iterate
        only after BudgetAccountant.compute_budgets().
        """
        _check_utility_analysis_params(options, data_extractors)
        backend = self._backend
        if analyzer is None:
            analyzer = self.request_budgets(options, public_partitions)
        col = self.preaggregated_rows(col, options, data_extractors,
                                      public_partitions)
        if public_partitions is not None:
            # Empty-partition markers so missing public partitions surface.
            publics = backend.to_collection(public_partitions, col,
                                            "Public partitions to collection")
            markers = backend.map(publics, lambda pk: (pk, None),
                                  "Empty public partition markers")
            col = backend.flatten((col, markers),
                                  "Join markers with dataset rows")
        # Mergeable bounded accumulators (sparse rows -> dense moments above
        # SPARSE_CAP) so hot partitions reduce incrementally on distributed
        # backends instead of materializing every row on one worker.
        col = backend.map_values(col, analyzer.create_accumulator,
                                 "Wrap rows into analysis accumulators")
        col = backend.combine_accumulators_per_key(
            col, analyzer, "Merge analysis accumulators per partition")
        return backend.map_values(col, analyzer.compute,
                                  "Per-partition utility analysis")


def _check_utility_analysis_params(
        options: 'data_structures.UtilityAnalysisOptions',
        data_extractors: Union[extractors.DataExtractors,
                               extractors.PreAggregateExtractors]):
    if options.pre_aggregated_data:
        if not isinstance(data_extractors, extractors.PreAggregateExtractors):
            raise ValueError(
                "options.pre_aggregated_data is set to true but "
                "PreAggregateExtractors aren't provided. "
                "PreAggregateExtractors should be specified for "
                "pre-aggregated data.")
    elif not isinstance(data_extractors, extractors.DataExtractors):
        raise ValueError("DataExtractors should be specified for raw data.")

    params = options.aggregate_params
    if params.custom_combiners is not None:
        raise NotImplementedError("custom combiners are not supported")
    supported = {
        agg.Metrics.COUNT, agg.Metrics.SUM, agg.Metrics.PRIVACY_ID_COUNT
    }
    if not set(params.metrics).issubset(supported):
        not_supported = list(set(params.metrics) - supported)
        raise NotImplementedError(
            f"unsupported metric in metrics={not_supported}")
    if params.contribution_bounds_already_enforced:
        raise NotImplementedError(
            "utility analysis when contribution bounds are already enforced "
            "is not supported")
