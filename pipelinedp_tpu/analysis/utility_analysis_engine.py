"""DPEngine subclass for utility analysis.

Capability parity with the reference ``analysis/utility_analysis_engine.py``:
reuses the DP computation graph from DPEngine, swapping nodes — analysis
contribution bounder (no bounding, emits aggregates), one combiner set per
parameter configuration, no-op private partition selection, no annotation.
"""

from typing import Optional, Union

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu import contribution_bounders as dp_bounders
from pipelinedp_tpu import data_extractors as extractors
from pipelinedp_tpu import dp_engine
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu.analysis import contribution_bounders as analysis_bounders
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import per_partition_combiners


class UtilityAnalysisEngine(dp_engine.DPEngine):
    """Performs utility analysis for DP aggregations."""

    def __init__(self, budget_accountant: budget_accounting.BudgetAccountant,
                 backend: pipeline_backend.PipelineBackend):
        super().__init__(budget_accountant, backend)
        self._is_public_partitions = None
        self._options = None

    def aggregate(self,
                  col,
                  params: agg.AggregateParams,
                  data_extractors: extractors.DataExtractors,
                  public_partitions=None):
        raise ValueError("UtilityAnalysisEngine.aggregate can't be called.\n"
                         "If you'd like to perform utility analysis, use "
                         "UtilityAnalysisEngine.analyze.\n"
                         "If you'd like to perform DP computations, use "
                         "DPEngine.aggregate.")

    def analyze(self,
                col,
                options: 'data_structures.UtilityAnalysisOptions',
                data_extractors: Union[extractors.DataExtractors,
                                       extractors.PreAggregateExtractors],
                public_partitions=None):
        """Utility analysis per partition.

        Returns a collection of (partition_key, per-partition utility
        metrics) — one flat tuple of results per partition, covering every
        parameter configuration in 'options'.
        """
        _check_utility_analysis_params(options, data_extractors)
        self._options = options
        self._is_public_partitions = public_partitions is not None
        # Build the computation graph via the parent class.
        result = super().aggregate(col, options.aggregate_params,
                                   data_extractors, public_partitions)
        self._is_public_partitions = None
        self._options = None
        return result

    def _use_tpu_path(self, params: agg.AggregateParams) -> bool:
        # The analysis graph swaps combiners/bounders; route through the
        # generic graph (its per-partition kernels are numpy-vectorized).
        return False

    def _create_contribution_bounder(
            self, params: agg.AggregateParams,
            expects_per_partition_sampling: bool
    ) -> dp_bounders.ContributionBounder:
        if self._options.pre_aggregated_data:
            return analysis_bounders.NoOpContributionBounder()
        return analysis_bounders.AnalysisContributionBounder(
            self._options.partitions_sampling_prob)

    def _create_compound_combiner(
            self, aggregate_params: agg.AggregateParams
    ) -> dp_combiners.CompoundCombiner:
        mechanism_type = aggregate_params.noise_kind.convert_to_mechanism_type(
        )
        # One budget request for private partition selection and one per
        # metric — SHARED by all parameter configurations (the analysis
        # models the same budget split the real run would have).
        if not self._is_public_partitions:
            private_partition_selection_budget = (
                self._budget_accountant.request_budget(
                    agg.MechanismType.GENERIC,
                    weight=aggregate_params.budget_weight))
        budgets = {}
        for metric in aggregate_params.metrics:
            budgets[metric] = self._budget_accountant.request_budget(
                mechanism_type, weight=aggregate_params.budget_weight)

        # Internal combiners: RawStatistics first, then per configuration:
        # [partition selection?, SUM?, COUNT?, PRIVACY_ID_COUNT?].
        # Order matters — _pack_per_partition_metrics depends on it.
        internal_combiners = [per_partition_combiners.RawStatisticsCombiner()]
        for params in data_structures.get_aggregate_params(self._options):
            if not self._is_public_partitions:
                internal_combiners.append(
                    per_partition_combiners.PartitionSelectionCombiner(
                        dp_combiners.CombinerParams(
                            private_partition_selection_budget, params)))
            if agg.Metrics.SUM in aggregate_params.metrics:
                internal_combiners.append(
                    per_partition_combiners.SumCombiner(
                        dp_combiners.CombinerParams(
                            budgets[agg.Metrics.SUM], params)))
            if agg.Metrics.COUNT in aggregate_params.metrics:
                internal_combiners.append(
                    per_partition_combiners.CountCombiner(
                        dp_combiners.CombinerParams(
                            budgets[agg.Metrics.COUNT], params)))
            if agg.Metrics.PRIVACY_ID_COUNT in aggregate_params.metrics:
                internal_combiners.append(
                    per_partition_combiners.PrivacyIdCountCombiner(
                        dp_combiners.CombinerParams(
                            budgets[agg.Metrics.PRIVACY_ID_COUNT], params)))

        return per_partition_combiners.CompoundCombiner(
            internal_combiners, return_named_tuple=False)

    def _select_private_partitions_internal(
            self, col, max_partitions_contributed: int,
            max_rows_per_privacy_id: int,
            strategy: agg.PartitionSelectionStrategy,
            pre_threshold: Optional[int]):
        # Analysis of private partition selection happens in the
        # PartitionSelectionCombiner; no partitions are dropped here.
        return col

    def _extract_columns(
            self, col, data_extractors: Union[
                extractors.DataExtractors,
                extractors.PreAggregateExtractors]):
        if self._options.pre_aggregated_data:
            # (privacy_id=None, partition_key, preaggregate_data)
            return self._backend.map(
                col, lambda row: (None, data_extractors.partition_extractor(
                    row), data_extractors.preaggregate_extractor(row)),
                "Extract (partition_key, preaggregate_data)")
        return super()._extract_columns(col, data_extractors)

    def _check_aggregate_params(self,
                                col,
                                params: agg.AggregateParams,
                                data_extractors,
                                check_data_extractors: bool = True):
        # PreAggregateExtractors are checked by _check_utility_analysis_params.
        super()._check_aggregate_params(col,
                                        params,
                                        data_extractors=None,
                                        check_data_extractors=False)

    def _annotate(self, col, params, budget):
        # No DP computations are performed — nothing to annotate.
        return col


def _check_utility_analysis_params(
        options: 'data_structures.UtilityAnalysisOptions',
        data_extractors: Union[extractors.DataExtractors,
                               extractors.PreAggregateExtractors]):
    if options.pre_aggregated_data:
        if not isinstance(data_extractors, extractors.PreAggregateExtractors):
            raise ValueError(
                "options.pre_aggregated_data is set to true but "
                "PreAggregateExtractors aren't provided. "
                "PreAggregateExtractors should be specified for "
                "pre-aggregated data.")
    elif not isinstance(data_extractors, extractors.DataExtractors):
        raise ValueError("DataExtractors should be specified for raw data.")

    params = options.aggregate_params
    if params.custom_combiners is not None:
        raise NotImplementedError("custom combiners are not supported")
    supported = {
        agg.Metrics.COUNT, agg.Metrics.SUM, agg.Metrics.PRIVACY_ID_COUNT
    }
    if not set(params.metrics).issubset(supported):
        not_supported = list(set(params.metrics) - supported)
        raise NotImplementedError(
            f"unsupported metric in metrics={not_supported}")
    if params.contribution_bounds_already_enforced:
        raise NotImplementedError(
            "utility analysis when contribution bounds are already enforced "
            "is not supported")
