"""API dataclasses for utility analysis.

Capability parity with the reference ``analysis/data_structures.py:25-151``.
"""

import copy
import dataclasses
from typing import Iterable, Optional, Sequence

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import input_validators


@dataclasses.dataclass
class MultiParameterConfiguration:
    """Parameter sweeps for multi-parameter utility analysis.

    Each attribute mirrors one in AggregateParams and holds a sequence of
    values; all non-None attributes must have the same length N, defining N
    parameter configurations analyzed simultaneously
    (reference ``data_structures.py:25-96``).
    """
    max_partitions_contributed: Sequence[int] = None
    max_contributions_per_partition: Sequence[int] = None
    min_sum_per_partition: Sequence[float] = None
    max_sum_per_partition: Sequence[float] = None
    noise_kind: Sequence[agg.NoiseKind] = None
    partition_selection_strategy: Sequence[
        agg.PartitionSelectionStrategy] = None

    def __post_init__(self):
        attributes = dataclasses.asdict(self)
        sizes = [len(value) for value in attributes.values() if value]
        if not sizes:
            raise ValueError("MultiParameterConfiguration must have at least 1"
                             " non-empty attribute.")
        if min(sizes) != max(sizes):
            raise ValueError(
                "All set attributes in MultiParameterConfiguration must have "
                "the same length.")
        if (self.min_sum_per_partition is None) != (self.max_sum_per_partition
                                                    is None):
            raise ValueError(
                "MultiParameterConfiguration: min_sum_per_partition and "
                "max_sum_per_partition must be both set or both None.")
        self._size = sizes[0]

    @property
    def size(self):
        return self._size

    def get_aggregate_params(self, params: agg.AggregateParams,
                             index: int) -> agg.AggregateParams:
        """Returns AggregateParams with the index-th parameters applied."""
        params = copy.copy(params)
        if self.max_partitions_contributed:
            params.max_partitions_contributed = (
                self.max_partitions_contributed[index])
        if self.max_contributions_per_partition:
            params.max_contributions_per_partition = (
                self.max_contributions_per_partition[index])
        if self.min_sum_per_partition:
            params.min_sum_per_partition = self.min_sum_per_partition[index]
        if self.max_sum_per_partition:
            params.max_sum_per_partition = self.max_sum_per_partition[index]
        if self.noise_kind:
            params.noise_kind = self.noise_kind[index]
        if self.partition_selection_strategy:
            params.partition_selection_strategy = (
                self.partition_selection_strategy[index])
        return params


@dataclasses.dataclass
class UtilityAnalysisOptions:
    """Options for the utility analysis (reference ``:100-121``)."""
    epsilon: float
    delta: float
    aggregate_params: agg.AggregateParams
    multi_param_configuration: Optional[MultiParameterConfiguration] = None
    partitions_sampling_prob: float = 1
    pre_aggregated_data: bool = False

    def __post_init__(self):
        input_validators.validate_epsilon_delta(self.epsilon, self.delta,
                                                "UtilityAnalysisOptions")
        if (self.partitions_sampling_prob <= 0 or
                self.partitions_sampling_prob > 1):
            raise ValueError(
                "partitions_sampling_prob must be in the interval"
                f" (0, 1], but {self.partitions_sampling_prob} given.")

    @property
    def n_configurations(self):
        if self.multi_param_configuration is None:
            return 1
        return self.multi_param_configuration.size


def get_aggregate_params(
        options: UtilityAnalysisOptions) -> Iterable[agg.AggregateParams]:
    """Yields the AggregateParams of every configuration in the options."""
    multi_param = options.multi_param_configuration
    if multi_param is None:
        yield options.aggregate_params
    else:
        for i in range(multi_param.size):
            yield multi_param.get_aggregate_params(options.aggregate_params, i)


def get_partition_selection_strategy(
    options: UtilityAnalysisOptions
) -> Sequence[agg.PartitionSelectionStrategy]:
    """Partition selection strategy per configuration (reference ``:137-151``)."""
    multi_configuration = options.multi_param_configuration
    n_configurations = 1
    if multi_configuration is not None:
        if multi_configuration.partition_selection_strategy is not None:
            return multi_configuration.partition_selection_strategy
        n_configurations = multi_configuration.size
    return [options.aggregate_params.partition_selection_strategy
           ] * n_configurations
