"""Cross-partition aggregation of utility-analysis metrics.

Capability parity with the reference ``analysis/cross_partition_combiners.py``
(per-partition metrics -> UtilityReport with data-drop breakdown, RMSE and
weighted averaging), re-designed as flat vector algebra:

* A partition's contribution to the final report is a numeric matrix
  ([n_configs, n_metrics, error_model.REPORT_WIDTH] plus a
  [n_configs, INFO_WIDTH] partition-info block). Merging partitions is
  element-wise addition — no recursive dataclass walking — so the same
  reduction runs as a distributed-backend accumulator here and as a device
  ``segment_sum`` in ``analysis/kernels.py``.
* Result dataclasses (UtilityReport and friends) are materialized once at
  finalization from the summed vectors (``error_model.finalize_*``).
"""

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu.analysis import error_model as em
from pipelinedp_tpu.analysis import metrics as metrics_dc


def equal_weight_fn(per_partition: metrics_dc.PerPartitionMetrics) -> float:
    """Weights partitions by their keep probability (1 for public)."""
    return per_partition.partition_selection_probability_to_keep


def partition_size_weight_fn(
        per_partition: metrics_dc.PerPartitionMetrics) -> float:
    """Weights partitions by their (first metric's) size."""
    return per_partition.metric_errors[0].sum


# Accumulator: (report rows [K, n_metrics, REPORT_WIDTH],
#               info rows [K, INFO_WIDTH]).
AccumulatorType = Tuple[np.ndarray, np.ndarray]


class CrossPartitionAggregator:
    """Reduces per-partition metrics into per-configuration UtilityReports."""

    def __init__(self,
                 metric_list: Sequence[agg.Metric],
                 public_partitions: bool,
                 weight_fn: Callable[[metrics_dc.PerPartitionMetrics],
                                     float] = equal_weight_fn):
        self._metric_list = list(metric_list)
        self._public = public_partitions
        self._weight_fn = weight_fn

    def create_accumulator(
            self, packed: Sequence[metrics_dc.PerPartitionMetrics]
    ) -> AccumulatorType:
        """One partition's contribution; ``packed`` has one entry per
        configuration."""
        k = len(packed)
        n_metrics = len(self._metric_list)
        rows = np.zeros((k, n_metrics, em.REPORT_WIDTH))
        info = np.zeros((k, em.INFO_WIDTH))
        for ki, per_partition in enumerate(packed):
            keep_prob = (1.0 if self._public else
                         per_partition.partition_selection_probability_to_keep)
            weight = self._weight_fn(per_partition)
            for mi in range(n_metrics):
                sm = per_partition.metric_errors[mi]
                stats = np.array([
                    sm.sum, sm.clipping_to_min_error, sm.clipping_to_max_error,
                    sm.expected_l0_bounding_error,
                    sm.std_l0_bounding_error**2
                ])
                rows[ki, mi] = em.metric_report_terms(stats, keep_prob, weight,
                                                      sm.std_noise)
            n_users = per_partition.raw_statistics.privacy_id_count
            info[ki] = em.info_terms(np.asarray(float(n_users)),
                                     np.asarray(keep_prob),
                                     np.asarray(weight), self._public)
        return rows, info

    def merge_accumulators(self, acc1: AccumulatorType,
                           acc2: AccumulatorType) -> AccumulatorType:
        return acc1[0] + acc2[0], acc1[1] + acc2[1]

    def compute_reports(
        self,
        acc: AccumulatorType,
        noise_stds: np.ndarray,
        noise_kinds: Sequence[agg.NoiseKind],
        strategies: Optional[Sequence[agg.PartitionSelectionStrategy]] = None,
    ) -> List[metrics_dc.UtilityReport]:
        """Finalizes one report per configuration from the summed vectors.

        noise_stds: [K, n_metrics]; noise_kinds/strategies: per config.
        """
        rows, info = acc
        reports = []
        for ki in range(rows.shape[0]):
            report = em.finalize_utility_report(rows[ki], info[ki],
                                                self._metric_list,
                                                noise_stds[ki],
                                                noise_kinds[ki],
                                                self._public,
                                                configuration_index=ki)
            if strategies is not None:
                report.partitions_info.strategy = strategies[ki]
            reports.append(report)
        return reports
