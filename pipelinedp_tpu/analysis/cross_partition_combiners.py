"""Utility-analysis cross-partition combiners.

Capability parity with the reference ``analysis/cross_partition_combiners.py``:
per-partition metrics → UtilityReport with data-drop breakdown, RMSE, and
weighted averaging via recursive dataclass add/multiply.
"""

import copy
import dataclasses
import math
from typing import Callable, List, Tuple

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu.analysis import metrics


def _sum_metrics_to_data_dropped(
        sum_metrics: metrics.SumMetrics, partition_keep_probability: float,
        dp_metric: agg.Metric) -> metrics.DataDropInfo:
    """Attributes dropped data to bounding stages (reference ``:24-47``)."""
    linf_dropped = (sum_metrics.clipping_to_min_error -
                    sum_metrics.clipping_to_max_error)
    l0_dropped = -sum_metrics.expected_l0_bounding_error
    expected_after_bounding = sum_metrics.sum - l0_dropped - linf_dropped
    partition_selection_dropped = expected_after_bounding * (
        1 - partition_keep_probability)
    return metrics.DataDropInfo(
        l0=l0_dropped,
        linf=linf_dropped,
        partition_selection=partition_selection_dropped)


def _create_contribution_bounding_errors(
        sum_metrics: metrics.SumMetrics) -> metrics.ContributionBoundingErrors:
    l0_mean_var = metrics.MeanVariance(
        mean=sum_metrics.expected_l0_bounding_error,
        var=sum_metrics.std_l0_bounding_error**2)
    return metrics.ContributionBoundingErrors(
        l0=l0_mean_var,
        linf_min=sum_metrics.clipping_to_min_error,
        linf_max=sum_metrics.clipping_to_max_error)


def _sum_metrics_to_value_error(sum_metrics: metrics.SumMetrics,
                                keep_prob: float,
                                weight: float) -> metrics.ValueErrors:
    """Per-partition ValueErrors, weighted for the cross-partition average."""
    value = sum_metrics.sum
    bounding_errors = _create_contribution_bounding_errors(sum_metrics)
    mean = (bounding_errors.l0.mean + bounding_errors.linf_min +
            bounding_errors.linf_max)
    variance = (sum_metrics.std_l0_bounding_error**2 +
                sum_metrics.std_noise**2)
    rmse = math.sqrt(mean**2 + variance)
    l1 = 0  # not computed (reference TODO at :73)
    rmse_with_dropped_partitions = (keep_prob * rmse +
                                    (1 - keep_prob) * abs(value))
    l1_with_dropped_partitions = 0
    result = metrics.ValueErrors(
        bounding_errors=bounding_errors,
        mean=mean,
        variance=variance,
        rmse=rmse,
        l1=l1,
        rmse_with_dropped_partitions=rmse_with_dropped_partitions,
        l1_with_dropped_partitions=l1_with_dropped_partitions)
    if weight != 1:
        _multiply_float_dataclasses_field(result,
                                          weight,
                                          fields_to_ignore=["noise_std"])
    return result


def _sum_metrics_to_metric_utility(
        sum_metrics: metrics.SumMetrics, dp_metric: agg.Metric,
        partition_keep_probability: float,
        partition_weight: float) -> metrics.MetricUtility:
    """Cross-partition MetricUtility from one partition's utility."""
    data_dropped = _sum_metrics_to_data_dropped(sum_metrics,
                                                partition_keep_probability,
                                                dp_metric)
    absolute_error = _sum_metrics_to_value_error(sum_metrics,
                                                 partition_keep_probability,
                                                 partition_weight)
    relative_error = absolute_error.to_relative(sum_metrics.sum)
    return metrics.MetricUtility(metric=dp_metric,
                                 noise_std=sum_metrics.std_noise,
                                 noise_kind=sum_metrics.noise_kind,
                                 ratio_data_dropped=data_dropped,
                                 absolute_error=absolute_error,
                                 relative_error=relative_error)


def _partition_metrics_public_partitions(
        is_empty_partition: bool) -> metrics.PartitionsInfo:
    result = metrics.PartitionsInfo(public_partitions=True,
                                    num_dataset_partitions=0,
                                    num_non_public_partitions=0,
                                    num_empty_partitions=0)
    if is_empty_partition:
        result.num_empty_partitions = 1
    else:
        result.num_dataset_partitions = 1
    return result


def _partition_metrics_private_partitions(
        prob_keep: float) -> metrics.PartitionsInfo:
    kept_partitions = metrics.MeanVariance(mean=prob_keep,
                                           var=prob_keep * (1 - prob_keep))
    return metrics.PartitionsInfo(public_partitions=False,
                                  num_dataset_partitions=1,
                                  kept_partitions=kept_partitions)


def _add_dataclasses_by_fields(dataclass1, dataclass2,
                               fields_to_ignore: List[str]) -> None:
    """Recursively adds numeric fields of dataclass2 into dataclass1."""
    assert type(dataclass1) == type(dataclass2), \
        f"{type(dataclass1)} != {type(dataclass2)}"
    for field in dataclasses.fields(dataclass1):
        if field.name in fields_to_ignore:
            continue
        value1 = getattr(dataclass1, field.name)
        if value1 is None:
            continue
        value2 = getattr(dataclass2, field.name)
        if dataclasses.is_dataclass(value1):
            _add_dataclasses_by_fields(value1, value2, fields_to_ignore)
            continue
        setattr(dataclass1, field.name, value1 + value2)


def _multiply_float_dataclasses_field(dataclass,
                                      factor: float,
                                      fields_to_ignore: List[str] = ()
                                      ) -> None:
    """Recursively multiplies float fields of 'dataclass' in place."""
    for field in dataclasses.fields(dataclass):
        if field.name in fields_to_ignore:
            continue
        value = getattr(dataclass, field.name)
        if value is None:
            continue
        if field.type is float or isinstance(value, float):
            setattr(dataclass, field.name, value * factor)
        elif dataclasses.is_dataclass(value):
            _multiply_float_dataclasses_field(value, factor)


def _per_partition_to_utility_report(
        per_partition_utility: metrics.PerPartitionMetrics,
        dp_metrics: List[agg.Metric], public_partitions: bool,
        partition_weight: float) -> metrics.UtilityReport:
    """Converts per-partition metrics to a 1-partition UtilityReport."""
    if public_partitions:
        prob_to_keep = 1
        is_empty_partition = per_partition_utility.raw_statistics.count == 0
        partition_metrics = _partition_metrics_public_partitions(
            is_empty_partition)
    else:
        prob_to_keep = (
            per_partition_utility.partition_selection_probability_to_keep)
        partition_metrics = _partition_metrics_private_partitions(prob_to_keep)
    metric_errors = None
    if dp_metrics:
        assert len(per_partition_utility.metric_errors) == len(dp_metrics)
        metric_errors = [
            _sum_metrics_to_metric_utility(metric_error, dp_metric,
                                           prob_to_keep, partition_weight)
            for metric_error, dp_metric in zip(
                per_partition_utility.metric_errors, dp_metrics)
        ]
    return metrics.UtilityReport(configuration_index=-1,
                                 partitions_info=partition_metrics,
                                 metric_errors=metric_errors)


def _merge_partition_metrics(metrics1: metrics.PartitionsInfo,
                             metrics2: metrics.PartitionsInfo) -> None:
    _add_dataclasses_by_fields(metrics1, metrics2,
                               ["public_partitions", "strategy"])


def _merge_metric_utility(utility1: metrics.MetricUtility,
                          utility2: metrics.MetricUtility) -> None:
    _add_dataclasses_by_fields(utility1, utility2,
                               ["metric", "noise_std", "noise_kind"])


def _merge_utility_reports(report1: metrics.UtilityReport,
                           report2: metrics.UtilityReport) -> None:
    _merge_partition_metrics(report1.partitions_info, report2.partitions_info)
    if report1.metric_errors is None:
        return
    assert len(report1.metric_errors) == len(report2.metric_errors)
    for utility1, utility2 in zip(report1.metric_errors,
                                  report2.metric_errors):
        _merge_metric_utility(utility1, utility2)


def _average_utility_report(report: metrics.UtilityReport, sums_actual: Tuple,
                            total_weight: float) -> None:
    """Averages the report's error fields across partitions."""
    if not report.metric_errors:
        return
    for sum_actual, metric_error in zip(sums_actual, report.metric_errors):
        scaling_factor = 0 if total_weight == 0 else 1.0 / total_weight
        _multiply_float_dataclasses_field(
            metric_error,
            scaling_factor,
            fields_to_ignore=["noise_std", "ratio_data_dropped"])
        dropped_scaling_factor = 1 if sum_actual == 0 else 1.0 / sum_actual
        _multiply_float_dataclasses_field(metric_error.ratio_data_dropped,
                                          dropped_scaling_factor)


def partition_size_weight_fn(
        per_partition_metrics: metrics.PerPartitionMetrics) -> float:
    """Weights partitions by their size."""
    return per_partition_metrics.metric_errors[0].sum


def equal_weight_fn(
        per_partition_metrics: metrics.PerPartitionMetrics) -> float:
    """Weights partitions by their probability to be kept (1 for public)."""
    return per_partition_metrics.partition_selection_probability_to_keep


class CrossPartitionCombiner(dp_combiners.Combiner):
    """Aggregates per-partition error metrics into a UtilityReport.

    Accumulator: (sum of non-DP metrics for averaging, UtilityReport,
    accumulated weight).
    """
    AccumulatorType = Tuple[Tuple, metrics.UtilityReport, float]

    def __init__(self,
                 dp_metrics: List[agg.Metric],
                 public_partitions: bool,
                 weight_fn: Callable[[metrics.PerPartitionMetrics],
                                     float] = equal_weight_fn):
        self._dp_metrics = dp_metrics
        self._public_partitions = public_partitions
        self._weight_fn = weight_fn

    def create_accumulator(
            self,
            per_partition: metrics.PerPartitionMetrics) -> AccumulatorType:
        actual_metrics = tuple(me.sum for me in per_partition.metric_errors)
        weight = self._weight_fn(per_partition)
        return actual_metrics, _per_partition_to_utility_report(
            per_partition, self._dp_metrics, self._public_partitions,
            weight), weight

    def merge_accumulators(self, acc1: AccumulatorType,
                           acc2: AccumulatorType) -> AccumulatorType:
        sum_actual1, report1, weight1 = acc1
        sum_actual2, report2, weight2 = acc2
        sum_actual = tuple(x + y for x, y in zip(sum_actual1, sum_actual2))
        _merge_utility_reports(report1, report2)
        return sum_actual, report1, weight1 + weight2

    def compute_metrics(self, acc: AccumulatorType) -> metrics.UtilityReport:
        sum_actual, report, total_weight = acc
        report_copy = copy.deepcopy(report)
        _average_utility_report(report_copy, sum_actual, total_weight)
        return report_copy

    def metrics_names(self):
        return []

    def explain_computation(self):
        return None
