"""Framework-neutral private-collection API (L5).

The reference ships two framework-specific guarded APIs —
``pipeline_dp/private_beam.py`` (PrivatePCollection + PTransforms) and
``pipeline_dp/private_spark.py`` (PrivateRDD) — whose bodies are near-identical
per metric: build ``AggregateParams`` from the convenience params, wrap
extractors to peel the ``(privacy_id, element)`` pair, call
``DPEngine.aggregate``, extract the single metric from the result tuple.

The TPU-native design factors that shared logic here once, generic over any
``PipelineBackend`` (Local, TPU, MultiProc, Beam, Spark). ``PrivateCollection``
is the guarded container: only DP-aggregated data can leave it.
``private_beam.py`` / ``private_spark.py`` are thin idiomatic adapters over
these helpers.

Reference parity: private_beam.py:41-645, private_spark.py:21-383.
"""

import abc
import dataclasses
import typing
from typing import Any, Callable, Optional

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu import data_extractors
from pipelinedp_tpu import dp_engine as dp_engine_mod
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu import report_generator


def _privacy_id_extractor(contribution_bounds_already_enforced: bool):
    """Privacy ids are unneeded when bounds were enforced upstream
    (reference private_spark.py:368-374)."""
    if contribution_bounds_already_enforced:
        return None
    return lambda x: x[0]


def make_aggregate_params(metric_params, metric: agg.Metric,
                          **overrides) -> agg.AggregateParams:
    """Converts a per-metric convenience params dataclass into full
    AggregateParams for `metric` (reference private_beam.py:272-280 et al.)."""
    kwargs = dict(
        noise_kind=metric_params.noise_kind,
        metrics=[metric],
        max_partitions_contributed=metric_params.max_partitions_contributed,
        budget_weight=metric_params.budget_weight,
        contribution_bounds_already_enforced=getattr(
            metric_params, 'contribution_bounds_already_enforced', False),
    )
    kwargs['max_contributions_per_partition'] = getattr(
        metric_params, 'max_contributions_per_partition', 1)
    for field in ('min_value', 'max_value'):
        if hasattr(metric_params, field):
            kwargs[field] = getattr(metric_params, field)
    kwargs.update(overrides)
    return agg.AggregateParams(**kwargs)


def make_pair_extractors(
        metric_params,
        needs_value: bool) -> data_extractors.DataExtractors:
    """DataExtractors over (privacy_id, element) pairs: partition/value
    extractors from the params apply to element = x[1]."""
    enforced = getattr(metric_params, 'contribution_bounds_already_enforced',
                       False)
    # Value-less metrics (COUNT/PRIVACY_ID_COUNT) use 0.0, not None: None
    # becomes NaN in the float64 value column and the ingest boundary
    # rejects non-finite values (columnar.nonfinite_value_rows).
    value_extractor = ((lambda x: metric_params.value_extractor(x[1]))
                       if needs_value else (lambda x: 0.0))
    return data_extractors.DataExtractors(
        partition_extractor=lambda x: metric_params.partition_extractor(x[1]),
        privacy_id_extractor=_privacy_id_extractor(enforced),
        value_extractor=value_extractor)


_METRIC_OF = {
    'count': agg.Metrics.COUNT,
    'sum': agg.Metrics.SUM,
    'mean': agg.Metrics.MEAN,
    'variance': agg.Metrics.VARIANCE,
    'privacy_id_count': agg.Metrics.PRIVACY_ID_COUNT,
}

_NEEDS_VALUE = {'sum', 'mean', 'variance'}


def run_single_metric_aggregation(
        backend: pipeline_backend.PipelineBackend,
        budget_accountant: budget_accounting.BudgetAccountant,
        pair_col,
        metric_params,
        metric_name: str,
        public_partitions=None,
        out_explain_computation_report: Optional[
            report_generator.ExplainComputationReport] = None):
    """The shared body of every per-metric L5 transform: aggregate a
    (privacy_id, element) collection for one metric and unnest the result.

    Returns a collection of (partition_key, metric_value).
    """
    metric = _METRIC_OF[metric_name]
    engine = dp_engine_mod.DPEngine(budget_accountant, backend)
    overrides = {}
    if metric_name == 'privacy_id_count':
        overrides['max_contributions_per_partition'] = 1
    params = make_aggregate_params(metric_params, metric, **overrides)
    extractors = make_pair_extractors(metric_params,
                                      metric_name in _NEEDS_VALUE)
    dp_result = engine.aggregate(
        pair_col,
        params,
        extractors,
        public_partitions,
        out_explain_computation_report=out_explain_computation_report)
    # dp_result: (partition_key, MetricsTuple); extract the single metric.
    return backend.map_values(dp_result,
                              lambda v: getattr(v, metric_name),
                              f"Extract {metric_name}")


class PrivateCombineFn(abc.ABC):
    """Base class for custom private combine fns (experimental).

    Framework-neutral counterpart of reference private_beam.PrivateCombineFn
    (private_beam.py:486-543): users implement their own DP mechanism in
    extract_private_output() and contribution bounding in
    add_input_for_private_output().

    Warning: an advanced feature that can break DP guarantees if implemented
    incorrectly.
    """

    @abc.abstractmethod
    def create_accumulator(self):
        """Creates an empty accumulator."""

    @abc.abstractmethod
    def add_input_for_private_output(self, accumulator, input: Any) -> Any:
        """Adds an input that contributes to private output; should clip."""

    @abc.abstractmethod
    def merge_accumulators(self, accumulators):
        """Merges an iterable of accumulators into one."""

    @abc.abstractmethod
    def extract_private_output(self, accumulator, budget: Any,
                               aggregate_params: agg.AggregateParams) -> Any:
        """Computes the DP output; `budget` is what request_budget returned."""

    @abc.abstractmethod
    def request_budget(
            self,
            budget_accountant: budget_accounting.BudgetAccountant) -> Any:
        """Requests budget during graph construction; returns serializable
        budget object(s). Never store the budget_accountant itself."""


class _CombineFnCombiner(dp_combiners.CustomCombiner):
    """Adapts a PrivateCombineFn to the engine's CustomCombiner protocol
    (reference private_beam.py:546-578)."""

    def __init__(self, private_combine_fn: PrivateCombineFn):
        self._private_combine_fn = private_combine_fn

    def create_accumulator(self, values):
        accumulator = self._private_combine_fn.create_accumulator()
        for v in values:
            accumulator = (
                self._private_combine_fn.add_input_for_private_output(
                    accumulator, v))
        return accumulator

    def merge_accumulators(self, accumulator1, accumulator2):
        return self._private_combine_fn.merge_accumulators(
            [accumulator1, accumulator2])

    def compute_metrics(self, accumulator):
        return self._private_combine_fn.extract_private_output(
            accumulator, self._budget, self._aggregate_params)

    def explain_computation(self) -> str:
        return "Custom private combine fn."

    def request_budget(self,
                       budget_accountant: budget_accounting.BudgetAccountant):
        self._budget = self._private_combine_fn.request_budget(
            budget_accountant)


@dataclasses.dataclass
class CombinePerKeyParams:
    """Parameters for combine_per_key (reference private_beam.py:581-600)."""
    max_partitions_contributed: int
    max_contributions_per_partition: int
    budget_weight: float = 1
    public_partitions: typing.Any = None


def run_combine_per_key(
        backend: pipeline_backend.PipelineBackend,
        budget_accountant: budget_accounting.BudgetAccountant,
        pair_col,
        combine_fn: PrivateCombineFn,
        params: CombinePerKeyParams):
    """Custom-combiner aggregation over (privacy_id, (partition_key, value))
    pairs (reference private_beam.py:603-644)."""
    combiner = _CombineFnCombiner(combine_fn)
    aggregate_params = agg.AggregateParams(
        metrics=None,
        max_partitions_contributed=params.max_partitions_contributed,
        max_contributions_per_partition=params.max_contributions_per_partition,
        budget_weight=params.budget_weight,
        custom_combiners=[combiner])
    extractors = data_extractors.DataExtractors(
        privacy_id_extractor=lambda x: x[0],
        partition_extractor=lambda x: x[1][0],
        value_extractor=lambda x: x[1][1])
    engine = dp_engine_mod.DPEngine(budget_accountant, backend)
    dp_result = engine.aggregate(pair_col, aggregate_params, extractors,
                                 params.public_partitions)
    # One custom combiner → 1-tuple per key; unnest.
    return backend.map_values(dp_result, lambda v: v[0], "Unnest tuple")


class PrivateCollection:
    """Guarded collection: data can only leave via DP aggregations.

    Backend-generic counterpart of reference PrivatePCollection
    (private_beam.py:71-94) / PrivateRDD (private_spark.py:21-38). Holds
    (privacy_id, element) pairs plus the budget accountant; every aggregation
    method charges that accountant.
    """

    def __init__(self, col, backend: pipeline_backend.PipelineBackend,
                 budget_accountant: budget_accounting.BudgetAccountant):
        # Multiple aggregations may be charged against the same collection;
        # lazy single-pass iterators (LocalBackend) must be made re-iterable.
        self._col = backend.to_multi_transformable_collection(col)
        self._backend = backend
        self._budget_accountant = budget_accountant

    def map(self, fn: Callable) -> 'PrivateCollection':
        """Transforms elements, keeping privacy ids attached."""
        col = self._backend.map_values(self._col, fn, "Private Map")
        return PrivateCollection(col, self._backend, self._budget_accountant)

    def flat_map(self, fn: Callable) -> 'PrivateCollection':
        """Expands each element, keeping privacy ids attached."""

        def unnest(row):
            key, value = row
            for v in fn(value):
                yield key, v

        col = self._backend.flat_map(self._col, unnest, "Private FlatMap")
        return PrivateCollection(col, self._backend, self._budget_accountant)

    def count(self, count_params: agg.CountParams, public_partitions=None,
              out_explain_computation_report=None):
        return run_single_metric_aggregation(
            self._backend, self._budget_accountant, self._col, count_params,
            'count', public_partitions, out_explain_computation_report)

    def sum(self, sum_params: agg.SumParams, public_partitions=None,
            out_explain_computation_report=None):
        return run_single_metric_aggregation(
            self._backend, self._budget_accountant, self._col, sum_params,
            'sum', public_partitions, out_explain_computation_report)

    def mean(self, mean_params: agg.MeanParams, public_partitions=None,
             out_explain_computation_report=None):
        return run_single_metric_aggregation(
            self._backend, self._budget_accountant, self._col, mean_params,
            'mean', public_partitions, out_explain_computation_report)

    def variance(self, variance_params: agg.VarianceParams,
                 public_partitions=None,
                 out_explain_computation_report=None):
        return run_single_metric_aggregation(
            self._backend, self._budget_accountant, self._col,
            variance_params, 'variance', public_partitions,
            out_explain_computation_report)

    def privacy_id_count(self,
                         privacy_id_count_params: agg.PrivacyIdCountParams,
                         public_partitions=None,
                         out_explain_computation_report=None):
        return run_single_metric_aggregation(
            self._backend, self._budget_accountant, self._col,
            privacy_id_count_params, 'privacy_id_count', public_partitions,
            out_explain_computation_report)

    def select_partitions(self, params: agg.SelectPartitionsParams,
                          partition_extractor: Callable):
        """DP set of partition keys (reference private_spark.py:340-366)."""
        engine = dp_engine_mod.DPEngine(self._budget_accountant,
                                        self._backend)
        extractors = data_extractors.DataExtractors(
            partition_extractor=lambda x: partition_extractor(x[1]),
            privacy_id_extractor=lambda x: x[0])
        return engine.select_partitions(self._col, params, extractors)

    def combine_per_key(self, combine_fn: PrivateCombineFn,
                        params: CombinePerKeyParams):
        """Custom DP aggregation; elements must be (key, value) pairs."""
        return run_combine_per_key(self._backend, self._budget_accountant,
                                   self._col, combine_fn, params)


def make_private(
        col,
        backend: pipeline_backend.PipelineBackend,
        budget_accountant: budget_accounting.BudgetAccountant,
        privacy_id_extractor: Optional[Callable] = None) -> PrivateCollection:
    """Wraps a collection into a PrivateCollection.

    If privacy_id_extractor is None the collection is assumed to already be
    (privacy_id, element) pairs (reference private_spark.py:32-38).
    """
    if privacy_id_extractor is not None:
        col = backend.map(col, lambda x: (privacy_id_extractor(x), x),
                          "Extract privacy id")
    return PrivateCollection(col, backend, budget_accountant)
