"""Analysis driver: rules x modules -> findings, suppressions applied.

``analyze(modules)`` runs every registered rule over the shared model and
splits the raw findings into *active* (fail the build), *suppressed*
(silenced inline with a valid ``# staticcheck: disable=...`` comment) and
*ignored suppressions* (a reason-required rule suppressed without a
reason — the finding stays active, amended so the author knows why).
Baseline subtraction is layered on top by :mod:`baseline`.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence

from pipelinedp_tpu.staticcheck import rules as rules_mod
from pipelinedp_tpu.staticcheck.model import (Finding, Module,
                                              REASON_REQUIRED)

# Bump when rules are added/removed or their semantics change enough to
# invalidate baselines; surfaced in receipts so a finding-count change
# can be told apart from a rule-set change.
RULES_VERSION = "14"


@dataclasses.dataclass
class Analysis:
    """Outcome of one pass: what fails, what was waived, and why."""
    active: List[Finding]
    suppressed: List[Finding]

    @property
    def ok(self) -> bool:
        return not self.active


def rule_ids() -> List[str]:
    return sorted(rules_mod.RULES)


def rule_help() -> Dict[str, str]:
    return {rid: r.help for rid, r in sorted(rules_mod.RULES.items())}


def analyze(modules: Sequence[Module],
            only_rules: Optional[Sequence[str]] = None) -> Analysis:
    """Runs the (optionally restricted) rule set over parsed modules."""
    selected = rule_ids() if only_rules is None else list(only_rules)
    unknown = set(selected) - set(rules_mod.RULES)
    if unknown:
        raise ValueError(
            f"unknown rule id(s) {sorted(unknown)}; shipped rules: "
            f"{rule_ids()}")
    by_rel = {m.rel: m for m in modules}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rid in selected:
        for finding in rules_mod.RULES[rid].fn(list(modules)):
            mod = by_rel.get(finding.file)
            sup = (mod.suppression_for(finding.rule_id, finding.line)
                   if mod is not None else None)
            if sup is None:
                active.append(finding)
            elif finding.rule_id in REASON_REQUIRED and not sup.reason:
                active.append(dataclasses.replace(
                    finding,
                    message=finding.message +
                    " [suppression ignored: this rule requires a reason "
                    "— `# staticcheck: disable=" + finding.rule_id +
                    " — <why>`]"))
            else:
                suppressed.append(finding)
    active.sort(key=lambda f: (f.file, f.line, f.rule_id))
    suppressed.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return Analysis(active=active, suppressed=suppressed)
