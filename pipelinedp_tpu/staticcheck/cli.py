"""CLI: ``python -m pipelinedp_tpu.staticcheck [paths...]``.

Exit codes: 0 = clean (after suppressions and baseline), 1 = active
findings, 2 = usage error. ``--update-baseline`` rewrites the committed
baseline from the current active findings (preserving notes of entries
that still match) and exits 0.

Speed: ``--cache PATH`` keeps a content-hash pickle of parsed module
models (hash hit = no re-parse); ``--changed-only`` additionally trusts
the cache outright for files ``git status`` reports unchanged. Both
produce byte-identical findings to a cold full run — the whole tree is
always ANALYZED (the interprocedural rules need every module); the
selection only decides what gets re-parsed.

Formats: ``text`` (default), ``json``, and ``sarif`` (SARIF 2.1.0 —
findings render as annotations in standard CI viewers).
"""

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from pipelinedp_tpu.staticcheck import baseline as baseline_mod
from pipelinedp_tpu.staticcheck import cache as cache_mod
from pipelinedp_tpu.staticcheck import core
from pipelinedp_tpu.staticcheck import model

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PACKAGE_ROOT)

# The perf-harness and demo trees are measured/read, not production DP
# paths — the transfer/jit/registry rules are noise there — but key and
# host-RNG hygiene still hold: a benchmark that draws from hidden global
# state produces unreproducible receipts, and example code teaches the
# discipline readers copy. Intentional fixed-seed keys are baselined
# with reason notes.
AUX_RULES = ("key-hygiene", "host-rng")


def default_paths() -> List[str]:
    """The default analysis target: the installed package tree.

    benchmarks/ (and other non-product dirs) are excluded by
    model.DEFAULT_EXCLUDED_DIRS whether reached through this default or
    through an explicit repo-root path argument; the AUX_RULES subset
    runs over benchmarks/ and examples/ separately (aux_paths).
    """
    return [_PACKAGE_ROOT]


def aux_paths() -> List[str]:
    """benchmarks/ + examples/ trees, where the AUX_RULES subset runs."""
    out = []
    for name in ("benchmarks", "examples"):
        path = os.path.join(_REPO_ROOT, name)
        if os.path.isdir(path):
            out.append(path)
    return out


def _load(paths, cache=None, changed_only=False):
    trusted = None
    if changed_only and cache is not None:
        trusted = cache_mod.git_unchanged_paths(paths)
    return cache_mod.load_tree_cached(paths, cache=cache,
                                      trusted_paths=trusted)


def run_tree(paths: Optional[List[str]] = None,
             baseline_path: str = baseline_mod.DEFAULT_BASELINE_PATH,
             only_rules: Optional[List[str]] = None,
             cache: Optional["cache_mod.ModelCache"] = None,
             changed_only: bool = False):
    """One full pass: (analysis, active-after-baseline, baselined,
    stale-baseline-entries, modules). The programmatic entry the tier-1
    gate and the bench receipt share with the CLI.

    With default paths the AUX_RULES subset additionally runs over
    benchmarks/ and examples/, merged into the same result (one
    baseline, one exit code).
    """
    main_paths = paths or default_paths()
    modules = _load(main_paths, cache=cache, changed_only=changed_only)
    analysis = core.analyze(modules, only_rules=only_rules)
    if paths is None:
        aux = [r for r in AUX_RULES
               if only_rules is None or r in only_rules]
        aux_dirs = aux_paths()
        if aux and aux_dirs:
            aux_modules = _load(aux_dirs, cache=cache,
                                changed_only=changed_only)
            aux_analysis = core.analyze(aux_modules, only_rules=aux)
            modules = modules + aux_modules
            analysis = core.Analysis(
                active=sorted(
                    analysis.active + aux_analysis.active,
                    key=lambda f: (f.file, f.line, f.rule_id)),
                suppressed=sorted(
                    analysis.suppressed + aux_analysis.suppressed,
                    key=lambda f: (f.file, f.line, f.rule_id)))
    if cache is not None:
        cache.save()
    entries = baseline_mod.load(baseline_path) if baseline_path else []
    active, baselined, stale = baseline_mod.split(
        analysis.active, modules, entries)
    return analysis, active, baselined, stale, modules


def per_rule_counts(analysis: "core.Analysis", active, baselined) -> dict:
    """{rule: {"active": n, "baselined": n, "suppressed": n}} over one
    pass, zero-valued rules omitted — the bench-receipt shape that makes
    a per-family regression visible next to the perf numbers."""
    out: dict = {}

    def bump(findings, kind):
        for f in findings:
            entry = out.setdefault(f.rule_id,
                                   {"active": 0, "baselined": 0,
                                    "suppressed": 0})
            entry[kind] += 1

    bump(active, "active")
    bump(baselined, "baselined")
    bump(analysis.suppressed, "suppressed")
    return out


def to_sarif(active, stale) -> dict:
    """Findings as a SARIF 2.1.0 log (one run, one result per finding) —
    the schema CI annotation viewers ingest. Stale baseline entries ride
    along as tool notifications."""
    rules = [{
        "id": rid,
        "shortDescription": {"text": help_text},
    } for rid, help_text in core.rule_help().items()]
    results = [{
        "ruleId": f.rule_id,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.file},
                "region": {"startLine": f.line},
            },
        }],
    } for f in active]
    notifications = [{
        "level": "note",
        "message": {
            "text": f"stale baseline entry {e['rule']}@{e['file']} "
                    f"({e.get('text', '')!r}) — prune with "
                    f"--update-baseline"},
    } for e in stale]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "pipelinedp-tpu-staticcheck",
                    "version": core.RULES_VERSION,
                    "informationUri":
                        "https://github.com/pipelinedp-tpu",
                    "rules": rules,
                },
            },
            "results": results,
            "invocations": [{
                "executionSuccessful": True,
                "toolExecutionNotifications": notifications,
            }],
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pipelinedp_tpu.staticcheck",
        description="AST + interprocedural-dataflow DP-invariant "
                    "analyzer (key hygiene, release taint, lock order, "
                    "budget flow, thread-escape race detection, "
                    "determinism proofs, ledger discipline, "
                    "host-transfer & lock lints).",
        epilog="exit codes: 0 = clean (after suppressions and "
               "baseline), 1 = active findings, 2 = usage error")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the pipelinedp_tpu package, "
                             "plus key/RNG hygiene over benchmarks/ "
                             "and examples/)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--baseline",
                        default=baseline_mod.DEFAULT_BASELINE_PATH,
                        help="baseline file (default: the committed "
                             "staticcheck/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report everything")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current "
                             "active findings (notes of still-matching "
                             "entries are preserved)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE",
                        help="run a single rule family (repeatable; "
                             "combines with --rules) — the local "
                             "triage loop for one family")
    parser.add_argument("--cache", default=None, metavar="PATH",
                        help="content-hash pickle of parsed module "
                             "models; hash hits skip re-parsing "
                             "(findings stay byte-identical to a cold "
                             "run)")
    parser.add_argument("--changed-only", action="store_true",
                        help="trust the --cache outright for files git "
                             "reports unchanged (skips even the hash "
                             "read); the whole tree is still analyzed, "
                             "so findings are identical to a full run")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, help_text in core.rule_help().items():
            print(f"{rid}: {help_text}")
        return 0

    if args.changed_only and not args.cache:
        print("staticcheck: --changed-only needs --cache PATH (without "
              "a cache there is nothing to reuse; the run would just be "
              "a cold full pass)", file=sys.stderr)
        return 2

    only = ([r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None)
    if args.rule:
        only = (only or []) + [r for r in args.rule if r]
    cache = cache_mod.ModelCache(args.cache) if args.cache else None
    started = time.perf_counter()
    try:
        analysis, active, baselined, stale, modules = run_tree(
            args.paths or None,
            baseline_path=None if args.no_baseline else args.baseline,
            only_rules=only, cache=cache,
            changed_only=args.changed_only)
    except (ValueError, SyntaxError, OSError) as e:
        print(f"staticcheck: {e}", file=sys.stderr)
        return 2
    analysis_seconds = time.perf_counter() - started

    if args.update_baseline:
        n = baseline_mod.save(analysis.active, modules,
                              path=args.baseline,
                              rules_version=core.RULES_VERSION)
        print(f"staticcheck: baseline updated — {n} entr"
              f"{'y' if n == 1 else 'ies'} at {args.baseline}",
              file=sys.stderr)
        return 0

    if args.format == "json":
        print(json.dumps({
            "rules_version": core.RULES_VERSION,
            "findings": [f.__dict__ for f in active],
            "n_findings": len(active),
            "n_baselined": len(baselined),
            "n_suppressed": len(analysis.suppressed),
            "stale_baseline_entries": stale,
            "per_rule": per_rule_counts(analysis, active, baselined),
            "analysis_seconds": round(analysis_seconds, 3),
            **({"cache": {"hits": cache.hits, "misses": cache.misses,
                          "trusted": cache.trusted}} if cache else {}),
        }, indent=1))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(active, stale), indent=1))
    else:
        for f in active:
            print(f.render())
        for e in stale:
            print(f"staticcheck: stale baseline entry "
                  f"{e['rule']}@{e['file']} ({e.get('text', '')!r}) — "
                  f"the flagged code changed; prune with "
                  f"--update-baseline", file=sys.stderr)
        cache_note = ""
        if cache is not None:
            cache_note = (f", cache {cache.hits} hit/"
                          f"{cache.trusted} trusted/"
                          f"{cache.misses} parsed")
        print(f"staticcheck: {len(active)} finding(s), "
              f"{len(baselined)} baselined, "
              f"{len(analysis.suppressed)} suppressed "
              f"(rules v{core.RULES_VERSION}, "
              f"{analysis_seconds:.2f}s{cache_note})", file=sys.stderr)
    return 1 if active else 0
