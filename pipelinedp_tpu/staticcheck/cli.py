"""CLI: ``python -m pipelinedp_tpu.staticcheck [paths...]``.

Exit codes: 0 = clean (after suppressions and baseline), 1 = active
findings, 2 = usage error. ``--update-baseline`` rewrites the committed
baseline from the current active findings (preserving notes of entries
that still match) and exits 0.
"""

import argparse
import json
import os
import sys
from typing import List, Optional

from pipelinedp_tpu.staticcheck import baseline as baseline_mod
from pipelinedp_tpu.staticcheck import core
from pipelinedp_tpu.staticcheck import model

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_paths() -> List[str]:
    """The default analysis target: the installed package tree.

    benchmarks/ (and other non-product dirs) are excluded by
    model.DEFAULT_EXCLUDED_DIRS whether reached through this default or
    through an explicit repo-root path argument.
    """
    return [_PACKAGE_ROOT]


def run_tree(paths: Optional[List[str]] = None,
             baseline_path: str = baseline_mod.DEFAULT_BASELINE_PATH,
             only_rules: Optional[List[str]] = None):
    """One full pass: (analysis, active-after-baseline, baselined,
    stale-baseline-entries, modules). The programmatic entry the tier-1
    gate and the bench receipt share with the CLI."""
    modules = model.load_tree(paths or default_paths())
    analysis = core.analyze(modules, only_rules=only_rules)
    entries = baseline_mod.load(baseline_path) if baseline_path else []
    active, baselined, stale = baseline_mod.split(
        analysis.active, modules, entries)
    return analysis, active, baselined, stale, modules


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pipelinedp_tpu.staticcheck",
        description="AST-based DP-invariant analyzer (key hygiene, "
                    "ledger discipline, host-transfer & lock lints).")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the pipelinedp_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline",
                        default=baseline_mod.DEFAULT_BASELINE_PATH,
                        help="baseline file (default: the committed "
                             "staticcheck/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report everything")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current "
                             "active findings (notes of still-matching "
                             "entries are preserved)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, help_text in core.rule_help().items():
            print(f"{rid}: {help_text}")
        return 0

    only = ([r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None)
    try:
        analysis, active, baselined, stale, modules = run_tree(
            args.paths or None,
            baseline_path=None if args.no_baseline else args.baseline,
            only_rules=only)
    except (ValueError, SyntaxError, OSError) as e:
        print(f"staticcheck: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        n = baseline_mod.save(analysis.active, modules,
                              path=args.baseline,
                              rules_version=core.RULES_VERSION)
        print(f"staticcheck: baseline updated — {n} entr"
              f"{'y' if n == 1 else 'ies'} at {args.baseline}",
              file=sys.stderr)
        return 0

    if args.format == "json":
        print(json.dumps({
            "rules_version": core.RULES_VERSION,
            "findings": [f.__dict__ for f in active],
            "n_findings": len(active),
            "n_baselined": len(baselined),
            "n_suppressed": len(analysis.suppressed),
            "stale_baseline_entries": stale,
        }, indent=1))
    else:
        for f in active:
            print(f.render())
        for e in stale:
            print(f"staticcheck: stale baseline entry "
                  f"{e['rule']}@{e['file']} ({e.get('text', '')!r}) — "
                  f"the flagged code changed; prune with "
                  f"--update-baseline", file=sys.stderr)
        print(f"staticcheck: {len(active)} finding(s), "
              f"{len(baselined)} baselined, "
              f"{len(analysis.suppressed)} suppressed "
              f"(rules v{core.RULES_VERSION})", file=sys.stderr)
    return 1 if active else 0
