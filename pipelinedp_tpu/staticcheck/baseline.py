"""Committed baseline: grandfathered findings that do not fail the gate.

A baseline entry matches a finding by ``(rule, file, source-line text)``
— NOT by line number, so unrelated edits that shift lines never
invalidate it, while any edit to the flagged line itself (the thing the
rule actually looks at) re-surfaces the finding for fresh triage. Each
entry carries a ``note`` explaining why the finding is tolerated; the
tier-1 gate (tests/test_staticcheck.py) fails entries with an empty
note, so a baseline can never silently absorb findings.

``--update-baseline`` rewrites the file from the current active
findings, PRESERVING the notes of entries that still match — updating a
line number never loses its justification.
"""

import collections
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from pipelinedp_tpu.staticcheck.model import Finding, Module

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                                     "baseline.json")


def _key(rule: str, file: str, text: str) -> Tuple[str, str, str]:
    return (rule, file, " ".join(text.split()))


def load(path: str = DEFAULT_BASELINE_PATH) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    return payload.get("entries", [])


def split(findings: Sequence[Finding], modules: Sequence[Module],
          entries: Sequence[dict]
          ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(still-active, baselined, stale-entries).

    Each baseline entry absorbs at most one finding; entries that match
    nothing are stale (the flagged code changed or went away) and should
    be pruned with --update-baseline.
    """
    by_rel = {m.rel: m for m in modules}
    pool = collections.Counter(
        _key(e["rule"], e["file"], e.get("text", "")) for e in entries)
    active: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        mod = by_rel.get(f.file)
        text = mod.line_text(f.line) if mod is not None else ""
        key = _key(f.rule_id, f.file, text)
        if pool[key] > 0:
            pool[key] -= 1
            baselined.append(f)
        else:
            active.append(f)
    stale = []
    for e in entries:
        key = _key(e["rule"], e["file"], e.get("text", ""))
        if pool[key] > 0:
            pool[key] -= 1
            stale.append(e)
    return active, baselined, stale


def save(findings: Sequence[Finding], modules: Sequence[Module],
         path: str = DEFAULT_BASELINE_PATH,
         previous: Optional[Sequence[dict]] = None,
         rules_version: str = "") -> int:
    """Writes `findings` as the new baseline, carrying over the notes of
    previous entries that still match. Returns the entry count."""
    by_rel = {m.rel: m for m in modules}
    notes: Dict[Tuple[str, str, str], List[str]] = {}
    for e in (previous if previous is not None else load(path)):
        key = _key(e["rule"], e["file"], e.get("text", ""))
        if e.get("note"):
            notes.setdefault(key, []).append(e["note"])
    entries = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule_id)):
        mod = by_rel.get(f.file)
        text = mod.line_text(f.line) if mod is not None else ""
        key = _key(f.rule_id, f.file, text)
        carried = notes.get(key)
        entries.append({
            "rule": f.rule_id,
            "file": f.file,
            "line": f.line,
            "text": text,
            "note": carried.pop(0) if carried else "",
        })
    payload = {"rules_version": rules_version, "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return len(entries)
