import sys

from pipelinedp_tpu.staticcheck.cli import main

if __name__ == "__main__":
    sys.exit(main())
