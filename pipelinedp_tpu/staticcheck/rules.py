"""The shipped rules: AST checks for the invariants no unit test can see.

Each rule is a generator over the shared :mod:`model` tree, registered in
:data:`RULES`. Rules are *structural*: they prove properties of the
source (a key is never drawn twice, a guarded attribute is only touched
under its lock, every knob maps to an invoked validator), which is
exactly the class of DP-correctness property that runtime tests cannot
establish — a test observes one execution; the invariant quantifies over
all of them.
"""

import ast
import collections
import math
import re
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

from pipelinedp_tpu.staticcheck import dataflow
from pipelinedp_tpu.staticcheck import threads as threads_mod
from pipelinedp_tpu.staticcheck.model import CallGraph, Finding, Module

Rule = collections.namedtuple("Rule", ["rule_id", "help", "fn"])

RULES: Dict[str, Rule] = {}


def rule(rule_id: str, help_text: str):
    def deco(fn: Callable[[List[Module]], Iterator[Finding]]):
        RULES[rule_id] = Rule(rule_id, help_text, fn)
        return fn
    return deco


def _walk_no_nested_scopes(root: ast.AST) -> Iterator[ast.AST]:
    """ast.walk (root included) that does not descend into nested
    function/lambda bodies — they are separate scopes, visited on their
    own."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        if node is root or not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _function_scopes(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.Module)):
            yield node


def _stored_names(node: ast.AST) -> Set[str]:
    return {
        n.id
        for n in _walk_no_nested_scopes(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                          ast.Del))
    }


# ---------------------------------------------------------------------------
# (1) key-hygiene
# ---------------------------------------------------------------------------

# jax.random functions that CONSUME a key (a draw); split/fold_in DERIVE.
_KEY_DRAWS = frozenset({
    "uniform", "normal", "laplace", "exponential", "bits", "bernoulli",
    "gumbel", "randint", "choice", "permutation", "categorical",
    "truncated_normal", "poisson", "gamma", "beta", "cauchy", "logistic",
    "rademacher", "shuffle", "t", "dirichlet", "multivariate_normal",
})

# The one sanctioned PRNGKey constructor: every other key in product code
# must arrive through the seed plumbing and be derived via split/fold_in.
_SANCTIONED_KEY_CONSTRUCTORS = frozenset({"make_noise_key"})


def _draw_key_name(mod: Module, node: ast.AST) -> Optional[Tuple[str, int]]:
    """(key variable name, line) when node is a jax.random draw keyed by a
    bare variable."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    name = mod.dotted(node.func)
    if name is None or not name.startswith("jax.random."):
        return None
    if name.rsplit(".", 1)[1] not in _KEY_DRAWS:
        return None
    key = node.args[0]
    if isinstance(key, ast.Name):
        return key.id, node.lineno
    return None


def _check_scope_key_reuse(mod: Module, scope: ast.AST
                           ) -> Iterator[Finding]:
    versions: Dict[str, int] = {}
    # (name, version) -> first draw line.
    seen: Dict[Tuple[str, int], int] = {}

    if isinstance(scope, ast.Lambda):
        draws: Dict[str, int] = {}
        for node in _walk_no_nested_scopes(scope.body):
            hit = _draw_key_name(mod, node)
            if hit is None:
                continue
            name, line = hit
            if name in draws:
                yield Finding(
                    "key-hygiene", mod.rel, line,
                    f"PRNG key {name!r} consumed by a second jax.random "
                    f"draw (first at line {draws[name]}) without an "
                    f"intervening split/fold_in — correlated noise is a "
                    f"privacy failure, not a statistics bug")
            else:
                draws[name] = line
        return

    body = scope.body if not isinstance(scope, ast.Module) else scope.body

    def bump(target: ast.AST) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                              ast.Del)):
                versions[n.id] = versions.get(n.id, 0) + 1
                seen.pop((n.id, versions[n.id]), None)

    def expr_draws(node: Optional[ast.AST], loop_stores: Set[str],
                   out: List[Finding]) -> None:
        if node is None:
            return
        for n in _walk_no_nested_scopes(node):
            hit = _draw_key_name(mod, n)
            if hit is None:
                continue
            name, line = hit
            if loop_stores and name not in loop_stores:
                out.append(Finding(
                    "key-hygiene", mod.rel, line,
                    f"PRNG key {name!r} consumed inside a loop without a "
                    f"per-iteration split/fold_in derivation — every "
                    f"iteration draws the same randomness"))
                continue
            ver = versions.get(name, 0)
            if (name, ver) in seen:
                out.append(Finding(
                    "key-hygiene", mod.rel, line,
                    f"PRNG key {name!r} consumed by a second jax.random "
                    f"draw (first at line {seen[(name, ver)]}) without an "
                    f"intervening split/fold_in — correlated noise is a "
                    f"privacy failure, not a statistics bug"))
            else:
                seen[(name, ver)] = line

    def walk(stmts: Iterable[ast.stmt], loop_stores: Set[str],
             out: List[Finding]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope / own pass
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                expr_draws(stmt.value, loop_stores, out)
                targets = (stmt.targets
                           if isinstance(stmt, ast.Assign) else
                           [stmt.target])
                for t in targets:
                    bump(t)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                expr_draws(stmt.iter, loop_stores, out)
                inner = loop_stores | _stored_names(stmt)
                bump(stmt.target)
                walk(stmt.body, inner, out)
                walk(stmt.orelse, loop_stores, out)
            elif isinstance(stmt, ast.While):
                expr_draws(stmt.test, loop_stores, out)
                walk(stmt.body, loop_stores | _stored_names(stmt), out)
                walk(stmt.orelse, loop_stores, out)
            elif isinstance(stmt, ast.If):
                expr_draws(stmt.test, loop_stores, out)
                fork = dict(seen)
                walk(stmt.body, loop_stores, out)
                after_body = dict(seen)
                seen.clear()
                seen.update(fork)
                walk(stmt.orelse, loop_stores, out)
                seen.update(after_body)  # post-if reuse collides with either
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    expr_draws(item.context_expr, loop_stores, out)
                    if item.optional_vars is not None:
                        bump(item.optional_vars)
                walk(stmt.body, loop_stores, out)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, loop_stores, out)
                for handler in stmt.handlers:
                    walk(handler.body, loop_stores, out)
                walk(stmt.orelse, loop_stores, out)
                walk(stmt.finalbody, loop_stores, out)
            else:
                expr_draws(stmt, loop_stores, out)

    out: List[Finding] = []
    walk(body, set(), out)
    yield from out


@rule(
    "key-hygiene",
    "A PRNG key must never be consumed by two jax.random draws without "
    "an intervening split/fold_in, and jax.random.PRNGKey may only be "
    "constructed by the sanctioned seed plumbing (ops/noise.py "
    "make_noise_key) — ad-hoc keys bypass the fold_in(final_key, b) "
    "derivation the bit-identical-retry guarantee rests on.")
def key_hygiene(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        for scope in _function_scopes(mod.tree):
            yield from _check_scope_key_reuse(mod, scope)
        func_stack: List[str] = []

        def visit(node: ast.AST) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    yield from visit(child)
                func_stack.pop()
                return
            if (isinstance(node, ast.Call) and
                    mod.dotted(node.func) == "jax.random.PRNGKey" and
                    not (set(func_stack) &
                         _SANCTIONED_KEY_CONSTRUCTORS)):
                yield Finding(
                    "key-hygiene", mod.rel, node.lineno,
                    "jax.random.PRNGKey constructed outside "
                    "make_noise_key — product keys must come through the "
                    "seed plumbing and be derived via split/fold_in so "
                    "retries and resumes replay the same release")
            for child in ast.iter_child_nodes(node):
                yield from visit(child)

        yield from visit(mod.tree)


# ---------------------------------------------------------------------------
# (2) host-rng
# ---------------------------------------------------------------------------

_GLOBAL_NP_DRAWS = frozenset({
    "rand", "randn", "random", "random_sample", "ranf", "sample",
    "randint", "random_integers", "choice", "shuffle", "permutation",
    "normal", "laplace", "uniform", "binomial", "poisson", "exponential",
    "geometric", "beta", "gamma", "gumbel", "logistic",
    "standard_normal", "standard_cauchy", "standard_exponential", "seed",
    "bytes",
})
_STDLIB_RANDOM_DRAWS = frozenset({
    "random", "randint", "randrange", "uniform", "sample", "choice",
    "choices", "shuffle", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "betavariate", "gammavariate", "triangular",
    "vonmisesvariate", "paretovariate", "weibullvariate", "seed",
    "getrandbits", "randbytes",
})
_RNG_CONSTRUCTORS = frozenset({
    "numpy.random.default_rng", "numpy.random.RandomState",
    "numpy.random.Generator", "random.Random", "random.SystemRandom",
})


@rule(
    "host-rng",
    "No hidden host randomness: module-global RNG instances and draws "
    "from the process-global numpy/stdlib RNG state are forbidden — "
    "noise and sampling must come from explicitly seeded, injectable "
    "generators (or the device-side counter-based keys), or a resumed "
    "job cannot replay the same release.")
def host_rng(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        in_function = [False]

        def visit(node: ast.AST) -> Iterator[Finding]:
            entered = isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda))
            if entered:
                in_function.append(True)
            if isinstance(node, ast.Call):
                name = mod.dotted(node.func)
                if name in _RNG_CONSTRUCTORS and not in_function[-1]:
                    yield Finding(
                        "host-rng", mod.rel, node.lineno,
                        f"module-global RNG instance ({name}) — shared "
                        f"mutable RNG state hides the seed; use an "
                        f"explicitly seeded, injectable generator "
                        f"created at (or passed into) the call site")
                elif name is not None and name.startswith("numpy.random."):
                    fn = name.rsplit(".", 1)[1]
                    if fn in _GLOBAL_NP_DRAWS:
                        yield Finding(
                            "host-rng", mod.rel, node.lineno,
                            f"{name}() draws from numpy's process-global "
                            f"RNG — route through an injectable "
                            f"np.random.Generator (sampling_utils / the "
                            f"module's seeded rng) instead")
                elif name is not None and name.startswith("random."):
                    fn = name.split(".", 1)[1]
                    if fn in _STDLIB_RANDOM_DRAWS:
                        yield Finding(
                            "host-rng", mod.rel, node.lineno,
                            f"stdlib {name}() draws from the "
                            f"process-global RNG — use an injectable "
                            f"generator instead")
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            if entered:
                in_function.pop()

        yield from visit(mod.tree)


# ---------------------------------------------------------------------------
# (3) host-transfer
# ---------------------------------------------------------------------------

_TRANSFER_CALLS = frozenset({
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.device_get",
})
_TRANSFER_METHODS = frozenset({"item", "tolist"})
# The sanctioned device->host routing points: transfers INSIDE these
# functions are the implementation of the routing itself.
_SANCTIONED_FETCH_FUNCS = frozenset({
    ("pipelinedp_tpu/parallel/mesh.py", "host_fetch"),
    ("pipelinedp_tpu/parallel/mesh.py", "sync_fetch"),
})


# Device-resident modules beyond the parallel/ and ops/ trees: the
# streaming executor's staging queue hands device arrays between stages,
# so a smuggled np.asarray there would serialize the exact overlap the
# module exists to create.
_DEVICE_RESIDENT_FILES = frozenset({
    "pipelinedp_tpu/runtime/pipeline.py",
    # The hash-device encode module: raw hash columns stream host ->
    # device once, codes are assigned inside jit, and the ONLY sanctioned
    # device->host traffic is the unique-count control scalars and the
    # O(kept) decode prefetch — all through mesh.host_fetch.
    "pipelinedp_tpu/device_encode.py",
})


def _is_device_resident(mod: Module) -> bool:
    dirs = mod.parts[:-1]
    return ("parallel" in dirs or "ops" in dirs or
            mod.rel in _DEVICE_RESIDENT_FILES)


@rule(
    "host-transfer",
    "Device-resident modules (parallel/, ops/, runtime/pipeline.py) "
    "must not smuggle host "
    "transfers: np.asarray/np.array/jax.device_get/.item()/.tolist() on "
    "device values block on a device->host copy. Route control-plane "
    "fetches through mesh.host_fetch (retried, watchdog-guarded, "
    "traced); O(kept)/O(D) post-drain staging is baselined with a note "
    "or suppressed with a reason — the runtime counterpart is "
    "reshard.forbid_row_fetches.")
def host_transfer(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        if not _is_device_resident(mod):
            continue
        func_stack: List[str] = []

        def visit(node: ast.AST) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    yield from visit(child)
                func_stack.pop()
                return
            sanctioned = any((mod.rel, fn) in _SANCTIONED_FETCH_FUNCS
                             for fn in func_stack)
            if isinstance(node, ast.Call) and not sanctioned:
                name = mod.dotted(node.func)
                if name in _TRANSFER_CALLS:
                    yield Finding(
                        "host-transfer", mod.rel, node.lineno,
                        f"{name}() in a device-resident module forces a "
                        f"blocking device->host transfer — route through "
                        f"mesh.host_fetch, or suppress with a reason / "
                        f"baseline with a note if the volume is bounded "
                        f"(O(kept), O(D))")
                elif (isinstance(node.func, ast.Attribute) and
                      node.func.attr in _TRANSFER_METHODS and
                      not node.args and not node.keywords):
                    yield Finding(
                        "host-transfer", mod.rel, node.lineno,
                        f".{node.func.attr}() in a device-resident module "
                        f"forces a blocking device->host transfer — "
                        f"route through mesh.host_fetch, or suppress "
                        f"with a reason")
            for child in ast.iter_child_nodes(node):
                yield from visit(child)

        yield from visit(mod.tree)


# ---------------------------------------------------------------------------
# (3b) dtype-discipline
# ---------------------------------------------------------------------------

# Reductions whose accumulator dtype defaults to the input dtype: on an
# f32 column that is an implicit f32 accumulator — the exact overflow /
# precision-loss channel the numeric-armor sentinel exists to catch.
_ACCUM_REDUCTIONS = frozenset({
    "jax.numpy.sum", "jax.numpy.cumsum", "jax.numpy.prod",
})
_NARROW_INT_DTYPES = frozenset({
    "int8", "int16", "int32", "uint8", "uint16", "uint32",
})


def _astype_target_leaf(call: ast.Call, mod: Module) -> Optional[str]:
    """The dtype leaf name of an ``.astype(X)`` call, if determinable."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    name = mod.dotted(arg)
    if name:
        return name.rsplit(".", 1)[-1]
    return None


@rule(
    "dtype-discipline",
    "Numeric dtype discipline in device-resident modules (parallel/, "
    "ops/, runtime/pipeline.py): reductions (jnp.sum/jnp.cumsum/"
    "jnp.prod) must declare their accumulator — dtype= or an explicit "
    ".astype on the operand — because an implicit f32 accumulator "
    "silently loses integer exactness past 2**24 and wraps at scale; "
    "fractional float literals must not be ==/!= compared against "
    "computed values (an accumulated or noised float is never reliably "
    "equal to a decimal literal — compare integers or use a tolerance); "
    "and a reduction must not be .astype-narrowed to an integer dtype "
    "in the same expression (probe or clip the accumulator first, or "
    "suppress with the proven range).")
def dtype_discipline(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        if not _is_device_resident(mod):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                name = mod.dotted(node.func) or ""
                if name in _ACCUM_REDUCTIONS:
                    has_dtype = any(kw.arg == "dtype"
                                    for kw in node.keywords)
                    operand_cast = bool(node.args) and (
                        isinstance(node.args[0], ast.Call) and
                        isinstance(node.args[0].func, ast.Attribute) and
                        node.args[0].func.attr == "astype")
                    if not has_dtype and not operand_cast:
                        leaf = name.rsplit(".", 1)[-1]
                        yield Finding(
                            "dtype-discipline", mod.rel, node.lineno,
                            f"jnp.{leaf}() without an explicit accumulator "
                            f"dtype in a device-resident module — an "
                            f"implicit f32 accumulator loses integer "
                            f"exactness past 2**24; pass dtype= (or cast "
                            f"the operand with .astype) to make the "
                            f"accumulation width a reviewed decision")
                elif (isinstance(node.func, ast.Attribute) and
                      node.func.attr == "astype" and
                      isinstance(node.func.value, ast.Call) and
                      (mod.dotted(node.func.value.func) or "")
                      in _ACCUM_REDUCTIONS):
                    target = _astype_target_leaf(node, mod)
                    if target in _NARROW_INT_DTYPES:
                        yield Finding(
                            "dtype-discipline", mod.rel, node.lineno,
                            f"reduction result .astype({target}) in one "
                            f"expression — the accumulator is truncated "
                            f"un-probed; check the range (or clip) before "
                            f"narrowing, or suppress with the proven "
                            f"bound")
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                frac_lit = any(
                    isinstance(o, ast.Constant) and
                    isinstance(o.value, float) and
                    math.isfinite(o.value) and
                    o.value != int(o.value)
                    for o in operands)
                if frac_lit and any(isinstance(op, (ast.Eq, ast.NotEq))
                                    for op in node.ops):
                    yield Finding(
                        "dtype-discipline", mod.rel, node.lineno,
                        "==/!= against a fractional float literal in a "
                        "device-resident module — computed f32 values "
                        "(accumulated, noised, rescaled) are never "
                        "reliably equal to a decimal literal; compare "
                        "integers, exact sentinels (0.0), or use a "
                        "tolerance")


# ---------------------------------------------------------------------------
# (4) lock-discipline
# ---------------------------------------------------------------------------

def _guarded_decl(mod: Module, stmt: ast.stmt
                  ) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """Parses ``_GUARDED_BY = guarded_by("<lock>", "<attr>", ...)``."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and
            isinstance(stmt.targets[0], ast.Name) and
            stmt.targets[0].id == "_GUARDED_BY" and
            isinstance(stmt.value, ast.Call)):
        return None
    callee = mod.dotted(stmt.value.func) or ""
    if callee.rsplit(".", 1)[-1] != "guarded_by":
        return None
    names = []
    for arg in stmt.value.args:
        if not (isinstance(arg, ast.Constant) and
                isinstance(arg.value, str)):
            return None
        names.append(arg.value)
    if len(names) < 2:
        return None
    return names[0], tuple(names[1:])


def _with_locks(mod: Module, stmt: ast.stmt, self_form: bool) -> Set[str]:
    locks: Set[str] = set()
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            name = mod.dotted(item.context_expr)
            if name is None:
                continue
            if self_form and name.startswith("self."):
                locks.add(name[len("self."):])
            elif not self_form and "." not in name:
                locks.add(name)
    return locks


def _check_guarded_body(mod: Module, body: Iterable[ast.stmt], lock: str,
                        attrs: Tuple[str, ...], self_form: bool,
                        where: str) -> Iterator[Finding]:

    def visit(node: ast.AST, held: bool) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested function/lambda runs later, outside the lock that
            # was held at definition time.
            body_nodes = (node.body if isinstance(node.body, list)
                          else [node.body])
            for child in body_nodes:
                yield from visit(child, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquires = lock in _with_locks(mod, node, self_form)
            for item in node.items:
                yield from visit(item.context_expr, held)
                if item.optional_vars is not None:
                    yield from visit(item.optional_vars, held)
            for child in node.body:
                yield from visit(child, held or acquires)
            return
        touched = None
        if self_form:
            if (isinstance(node, ast.Attribute) and
                    isinstance(node.value, ast.Name) and
                    node.value.id == "self" and node.attr in attrs):
                touched = f"self.{node.attr}"
        else:
            if isinstance(node, ast.Name) and node.id in attrs:
                touched = node.id
        if touched is not None and not held:
            lock_name = f"self.{lock}" if self_form else lock
            yield Finding(
                "lock-discipline", mod.rel, node.lineno,
                f"{touched} is declared guarded_by({lock!r}) in {where} "
                f"but is touched outside `with {lock_name}:` — a silent "
                f"data race with the watchdog/monitor threads")
        for child in ast.iter_child_nodes(node):
            yield from visit(child, held)

    for stmt in body:
        yield from visit(stmt, False)


@rule(
    "lock-discipline",
    "Attributes declared via `_GUARDED_BY = guarded_by(\"_lock\", ...)` "
    "(runtime/concurrency.py) must only be touched inside "
    "`with <lock>:`. __init__ and module-scope initialization are "
    "exempt (construction happens-before publication); helpers whose "
    "caller holds the lock carry a def-line suppression with a reason.")
def lock_discipline(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        # Module-scope declaration: guarded globals, checked inside every
        # function of the module (module-level statements initialize).
        for stmt in mod.tree.body:
            decl = _guarded_decl(mod, stmt)
            if decl is None:
                continue
            lock, attrs = decl
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    yield from _check_guarded_body(
                        mod, [node], lock, attrs, self_form=False,
                        where=f"module {mod.rel}")
        # Class-scope declarations: guarded instance attributes.
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for stmt in cls.body:
                decl = _guarded_decl(mod, stmt)
                if decl is None:
                    continue
                lock, attrs = decl
                for method in cls.body:
                    if not isinstance(method, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                        continue
                    if method.name == "__init__":
                        continue
                    yield from _check_guarded_body(
                        mod, method.body, lock, attrs, self_form=True,
                        where=f"class {cls.name}")


# ---------------------------------------------------------------------------
# (5) jit-boundary
# ---------------------------------------------------------------------------

_SHAPE_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding",
                          "aval"})


def _jit_decorator_info(mod: Module, dec: ast.AST
                        ) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when `dec` jit-compiles, else
    None. Handles @jax.jit and @functools.partial(jax.jit, ...)."""
    if mod.dotted(dec) == "jax.jit":
        return set(), set()
    if not isinstance(dec, ast.Call):
        return None
    callee = mod.dotted(dec.func)
    if callee == "jax.jit":
        call = dec
    elif callee in ("functools.partial", "partial") and dec.args and \
            mod.dotted(dec.args[0]) == "jax.jit":
        call = dec
    else:
        return None
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


# Attribution wrappers the jit-boundary rule accepts: probe_jit (the
# traced-dispatch probe) and aot_probe (runtime/aot.py — probe_jit plus
# the AOT executable cache; it wraps probe_jit internally, so its
# compiles and dispatches carry the same per-entry-point attribution).
_PROBE_WRAPPERS = frozenset({"probe_jit", "aot_probe"})

# The one module allowed to call .lower().compile() directly: it IS the
# attribution wrapper (aot_probe counts the compile into
# trace.note_compile + aot_cache_misses before executing).
_AOT_REL = "pipelinedp_tpu/runtime/aot.py"


def _probe_wrapped_names(mod: Module) -> Set[str]:
    wrapped: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            callee = mod.dotted(node.func) or ""
            if callee.rsplit(".", 1)[-1] in _PROBE_WRAPPERS:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        wrapped.add(arg.id)
    return wrapped


def _lowered_compile_findings(mod: Module) -> Iterator[Finding]:
    """AOT entry points: a ``<jitted>.lower(...).compile()`` chain
    builds an executable that dispatches OUTSIDE jit's probed path —
    unless it lives in runtime/aot.py (whose aot_probe is the sanctioned
    attribution wrapper), its compiles and dispatches are invisible to
    the compile/dispatch accounting and the aot_cache_hits/misses
    evidence."""
    if mod.rel == _AOT_REL:
        return
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == "compile"):
            continue
        inner = node.func.value
        if (isinstance(inner, ast.Call) and
                isinstance(inner.func, ast.Attribute) and
                inner.func.attr == "lower"):
            yield Finding(
                "jit-boundary", mod.rel, node.lineno,
                "bare .lower().compile() builds an AOT executable "
                "outside runtime/aot.py — its compile seconds and "
                "dispatches are invisible to the per-entry-point "
                "attribution and the aot_cache_hits/misses evidence; "
                "wrap the entry point in rt_aot.aot_probe(name, fn, "
                "static_argnames=...) instead")


def _traced_if_findings(mod: Module, fn: ast.AST, traced: Set[str]
                        ) -> Iterator[Finding]:
    shielded: Set[ast.AST] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and \
                node.attr in _SHAPE_ATTRS and \
                isinstance(node.value, ast.Name):
            shielded.add(node.value)
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for n in ast.walk(node):
                if isinstance(n, ast.Name):
                    shielded.add(n)
    for node in _walk_no_nested_scopes(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        for n in ast.walk(node.test):
            if isinstance(n, ast.Name) and n.id in traced and \
                    n not in shielded:
                yield Finding(
                    "jit-boundary", mod.rel, node.lineno,
                    f"Python `if`/`while` on traced argument {n.id!r} "
                    f"inside a jitted body — tracing evaluates this once "
                    f"at compile time, not per value; use lax.cond / "
                    f"jnp.where, or declare the argument static")
                break


@rule(
    "jit-boundary",
    "Every jax.jit/pjit entry point must be wrapped in trace.probe_jit "
    "or runtime/aot.aot_probe (compile/dispatch attribution — an "
    "unwrapped kernel's compiles are invisible in the e2e gap "
    "accounting), jitted bodies must not branch in Python on traced "
    "arguments, and .lower().compile() AOT executables may only be "
    "built inside runtime/aot.py, whose aot_probe carries the same "
    "attribution.")
def jit_boundary(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        wrapped = _probe_wrapped_names(mod)
        yield from _lowered_compile_findings(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                info = _jit_decorator_info(mod, dec)
                if info is None:
                    continue
                static_names, static_nums = info
                if node.name not in wrapped:
                    yield Finding(
                        "jit-boundary", mod.rel, node.lineno,
                        f"jit entry point {node.name!r} is not wrapped "
                        f"in trace.probe_jit — its compiles and "
                        f"dispatches are invisible to the compile/"
                        f"dispatch attribution (reassign: {node.name} = "
                        f"rt_trace.probe_jit({node.name!r}, "
                        f"{node.name}))")
                args = node.args
                traced = {
                    a.arg
                    for i, a in enumerate(args.posonlyargs + args.args)
                    if a.arg not in static_names and i not in static_nums
                } | {a.arg for a in args.kwonlyargs
                     if a.arg not in static_names}
                yield from _traced_if_findings(mod, node, traced)
                break


# ---------------------------------------------------------------------------
# (6a) registry-drift
# ---------------------------------------------------------------------------

_TELEMETRY_REL = "pipelinedp_tpu/runtime/telemetry.py"

# Declaration helper -> the metric kind it declares. Bare Metric(...)
# calls carry their kind as the second positional argument.
_DECL_HELPERS = {"_counter": "counter", "_gauge": "gauge"}


def _declared_metrics(mod: Module) -> Dict[str, Tuple[int, str]]:
    """{metric name: (line, kind)} declared in telemetry.REGISTRY."""
    declared: Dict[str, Tuple[int, str]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = (mod.dotted(node.func) or "").rsplit(".", 1)[-1]
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        if callee in _DECL_HELPERS:
            declared[node.args[0].value] = (node.lineno,
                                            _DECL_HELPERS[callee])
        elif callee == "Metric":
            kind = "counter"
            if len(node.args) > 1 and \
                    isinstance(node.args[1], ast.Constant) and \
                    isinstance(node.args[1].value, str):
                kind = node.args[1].value
            declared[node.args[0].value] = (node.lineno, kind)
    return declared


def _metric_call_literals(modules: List[Module], func_name: str
                          ) -> Dict[str, List[Tuple[str, int]]]:
    """First-arg string literals of every `<func_name>("...")` call."""
    found: Dict[str, List[Tuple[str, int]]] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            hit = (isinstance(func, ast.Attribute) and
                   func.attr == func_name) or \
                  (isinstance(func, ast.Name) and func.id == func_name)
            if not hit:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str)\
                    and arg.value.isidentifier():
                found.setdefault(arg.value, []).append(
                    (mod.rel, node.lineno))
    return found


def _recorded_literals(modules: List[Module]
                       ) -> Dict[str, List[Tuple[str, int]]]:
    return _metric_call_literals(modules, "record")


@rule(
    "registry-drift",
    "telemetry.REGISTRY and the source tree must agree in BOTH "
    "directions and BOTH kinds: every telemetry.record(\"name\") / "
    "set_gauge(\"name\") literal names a declared metric of the right "
    "kind (counter / gauge), every declared counter is recorded "
    "somewhere and every declared gauge is set somewhere — dead metrics "
    "mislead receipt and scrape readers, undeclared ones fork the "
    "namespace.")
def registry_drift(modules: List[Module]) -> Iterator[Finding]:
    telemetry = next((m for m in modules if m.rel == _TELEMETRY_REL), None)
    if telemetry is None:
        return
    declared = _declared_metrics(telemetry)
    for func_name, want_kind, other_api in (
            ("record", "counter", "set_gauge"),
            ("set_gauge", "gauge", "record")):
        used = _metric_call_literals(modules, func_name)
        for name, sites in sorted(used.items()):
            rel, line = sites[0]
            if name not in declared:
                yield Finding(
                    "registry-drift", rel, line,
                    f"telemetry.{func_name}({name!r}) has no REGISTRY "
                    f"declaration — declare it (name, kind, help) in "
                    f"runtime/telemetry.py first")
            elif declared[name][1] != want_kind:
                yield Finding(
                    "registry-drift", rel, line,
                    f"telemetry.{func_name}({name!r}) targets a metric "
                    f"declared as a {declared[name][1]} — use "
                    f"{other_api}() or fix the declaration's kind")
        for name, (line, kind) in sorted(declared.items()):
            if kind == want_kind and name not in used:
                verb = ("records" if want_kind == "counter" else "sets")
                yield Finding(
                    "registry-drift", _TELEMETRY_REL, line,
                    f"REGISTRY declares {want_kind} {name!r} but no "
                    f"source file {verb} it — a dead metric misleads "
                    f"receipt readers; drop it or wire it up")


# ---------------------------------------------------------------------------
# (6b) knob-validation
# ---------------------------------------------------------------------------

_ENTRY_REL = "pipelinedp_tpu/runtime/entry.py"
_VALIDATORS_REL = "pipelinedp_tpu/input_validators.py"
_BACKEND_REL = "pipelinedp_tpu/pipeline_backend.py"
_SERVICE_REL = "pipelinedp_tpu/service/service.py"

# Runtime knob -> the input_validators function that must vet it.
KNOB_VALIDATORS: Dict[str, str] = {
    "retry": "validate_retry_policy",
    "journal": "validate_journal",
    "timeout_s": "validate_timeout_s",
    "watchdog": "validate_watchdog",
    "elastic": "validate_elastic",
    "min_devices": "validate_min_devices",
    "job_id": "validate_job_id",
    "trace": "validate_trace",
    "pipeline_depth": "validate_pipeline_depth",
    "encode_threads": "validate_encode_threads",
    "encode_mode": "validate_encode_mode",
    "num_processes": "validate_num_processes",
    "coordinator_address": "validate_coordinator_address",
    "metrics_port": "validate_metrics_port",
    "metrics_path": "validate_metrics_path",
    # Warm-path knobs (PR 14): the AOT executable cache, the fused
    # release kernels and the compute/drain overlap. The driver-level
    # `fused`/`overlap` route selectors share the backend validators
    # (validated in runtime/entry.py's wrapper).
    "aot": "validate_aot",
    "fused_release": "validate_fused_release",
    "overlap_drain": "validate_overlap_drain",
    "fused": "validate_fused_release",
    "overlap": "validate_overlap_drain",
    # Multi-tenant service knobs (validated in
    # DPAggregationService.__init__ — the service API boundary).
    "max_concurrent_jobs": "validate_max_concurrent_jobs",
    "tenant_budget_epsilon": "validate_tenant_budget_epsilon",
    "queue_timeout_s": "validate_queue_timeout_s",
    "shed_watermark_fraction": "validate_shed_watermark_fraction",
    # Megabatched-serving knobs (PR 16): the coalescing tier's switch,
    # window and lane cap — a bad window or lane cap would silently
    # stall every identical-spec job in an unfillable batch window.
    "batching": "validate_batching",
    "batch_window_ms": "validate_batch_window_ms",
    "max_batch_jobs": "validate_max_batch_jobs",
    # Fleet-operations knobs (PR 17): scale-UP admission and the
    # service's drain window — an unvetted grow switch or drain
    # timeout changes failure semantics (which jobs finish vs cancel
    # during a rolling restart), so both go through the validators.
    "elastic_grow": "validate_elastic_grow",
    "drain_timeout_s": "validate_drain_timeout_s",
    # Chaos/robustness knobs (PR 18): the per-job deadline is failure
    # semantics by definition — it decides which jobs settle CANCELLED —
    # and is validated at its own API boundary
    # (DPAggregationService.submit).
    "deadline_s": "validate_deadline_s",
    # Numeric-armor knobs (PR 19): the accumulation discipline decides
    # whether overflow wraps or fails closed, and the snapping-grid
    # floor changes which values a release can legally take — both are
    # release semantics, validated in TPUBackend.__init__.
    "numeric_mode": "validate_numeric_mode",
    "snap_grid_bits": "validate_snap_grid_bits",
    # PLD-accounting knobs (PR 20): the accounting mode decides which
    # spend number admission charges (privacy semantics by definition),
    # and the discretization interval sizes the loss grid every
    # composed bound is computed on — both validated at the service
    # API boundary (and in TenantLedger / PLDBudgetAccountant).
    "tenant_accounting": "validate_tenant_accounting",
    "pld_discretization": "validate_pld_discretization",
}

# Data-plane parameters: configuration, not failure semantics — adding
# one here is a deliberate reviewed decision, not a default.
KNOB_EXEMPT = frozenset({
    # driver data/geometry knobs
    "block_partitions", "row_chunk", "secure_tables", "reshard",
    "phase_times",
    # TPUBackend configuration
    "mesh", "max_partitions", "noise_seed", "secure_noise",
    "large_partition_threshold",
    # DPAggregationService configuration (data-plane: where ledgers
    # live and what the shed check divides by — not failure semantics)
    "ledger_dir", "memory_limit_bytes",
})

_DRIVER_FUNCS: Dict[str, Tuple[str, ...]] = {
    "pipelinedp_tpu/parallel/large_p.py": (
        "aggregate_blocked", "aggregate_blocked_sharded",
        "select_partitions_blocked", "select_partitions_blocked_sharded"),
    "pipelinedp_tpu/parallel/sharded.py": (
        "sharded_aggregate_arrays", "sharded_select_partitions"),
}


def _keyword_knobs(fn: ast.FunctionDef) -> Dict[str, int]:
    """Defaulted-positional + keyword-only parameter names -> line."""
    knobs: Dict[str, int] = {}
    args = fn.args
    defaulted = args.args[len(args.args) - len(args.defaults):] \
        if args.defaults else []
    for a in defaulted:
        knobs[a.arg] = a.lineno
    for a in args.kwonlyargs:
        knobs[a.arg] = a.lineno
    return knobs


def _find_funcdef(mod: Module, name: str,
                  cls: Optional[str] = None) -> Optional[ast.FunctionDef]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and cls is not None and \
                node.name == cls:
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef) and sub.name == name:
                    return sub
        elif cls is None and isinstance(node, ast.FunctionDef) and \
                node.name == name:
            return node
    return None


def _invoked_validators(node: ast.AST, mod: Module) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            callee = mod.dotted(n.func) or ""
            leaf = callee.rsplit(".", 1)[-1]
            if leaf.startswith("validate_"):
                out.add(leaf)
    return out


@rule(
    "knob-validation",
    "Every runtime knob on the drivers, the shared runtime_entry wrapper "
    "and TPUBackend must map to an input_validators.validate_* function "
    "that exists and is invoked at the API boundary (runtime/entry.py "
    "for drivers, TPUBackend.__init__ for the backend); stale map "
    "entries are flagged in the reverse direction.")
def knob_validation(modules: List[Module]) -> Iterator[Finding]:
    by_rel = {m.rel: m for m in modules}
    entry = by_rel.get(_ENTRY_REL)
    validators_mod = by_rel.get(_VALIDATORS_REL)
    backend_mod = by_rel.get(_BACKEND_REL)

    defined_validators = None
    if validators_mod is not None:
        defined_validators = {
            node.name
            for node in ast.walk(validators_mod.tree)
            if isinstance(node, ast.FunctionDef)
        }

    all_knobs: Dict[str, Tuple[str, int]] = {}

    def check_knobs(knobs: Dict[str, int], rel: str, owner: str,
                    invoked: Set[str], boundary: str) -> Iterator[Finding]:
        for knob, line in sorted(knobs.items()):
            all_knobs.setdefault(knob, (rel, line))
            if knob in KNOB_EXEMPT:
                continue
            if knob not in KNOB_VALIDATORS:
                yield Finding(
                    "knob-validation", rel, line,
                    f"{owner} grew a runtime knob {knob!r} with no "
                    f"validator mapping — add input_validators."
                    f"validate_{knob}, map it in staticcheck/rules.py "
                    f"KNOB_VALIDATORS and invoke it at {boundary} (or "
                    f"exempt it deliberately as a data-plane parameter)")
                continue
            validator = KNOB_VALIDATORS[knob]
            if defined_validators is not None and \
                    validator not in defined_validators:
                yield Finding(
                    "knob-validation", rel, line,
                    f"input_validators.{validator} (mapped for knob "
                    f"{knob!r}) does not exist")
            if validator not in invoked:
                yield Finding(
                    "knob-validation", rel, line,
                    f"{boundary} never invokes {validator} for "
                    f"{knob!r} — the knob skips validation at the API "
                    f"boundary")

    if entry is not None:
        wrapper = _find_funcdef(entry, "wrapper")
        entry_invoked = _invoked_validators(entry.tree, entry)
        if wrapper is not None:
            yield from check_knobs(
                _keyword_knobs(wrapper), entry.rel,
                "the runtime_entry wrapper", entry_invoked,
                "runtime/entry.py")
        for rel, names in _DRIVER_FUNCS.items():
            driver_mod = by_rel.get(rel)
            if driver_mod is None:
                continue
            for name in names:
                fn = _find_funcdef(driver_mod, name)
                if fn is None:
                    yield Finding(
                        "knob-validation", rel, 1,
                        f"driver {name!r} expected in {rel} but not "
                        f"found — update staticcheck/rules.py "
                        f"_DRIVER_FUNCS")
                    continue
                yield from check_knobs(
                    _keyword_knobs(fn), rel, f"driver {name}",
                    entry_invoked, "runtime/entry.py")

    if backend_mod is not None:
        init = _find_funcdef(backend_mod, "__init__", cls="TPUBackend")
        if init is not None:
            knobs = {a.arg: a.lineno
                     for a in init.args.args if a.arg != "self"}
            knobs.update(_keyword_knobs(init))
            knobs.pop("self", None)
            yield from check_knobs(
                knobs, backend_mod.rel, "TPUBackend",
                _invoked_validators(init, backend_mod),
                "TPUBackend.__init__")

    # The multi-tenant service is its own API boundary: every defaulted
    # DPAggregationService.__init__ parameter is a runtime knob under
    # the same discipline as TPUBackend's.
    service_mod = by_rel.get(_SERVICE_REL)
    if service_mod is not None:
        init = _find_funcdef(service_mod, "__init__",
                             cls="DPAggregationService")
        if init is not None:
            yield from check_knobs(
                _keyword_knobs(init), service_mod.rel,
                "DPAggregationService",
                _invoked_validators(init, service_mod),
                "DPAggregationService.__init__")
        # submit() is a second service boundary: its keyword-only
        # knobs (deadline_s) gate per-job failure semantics and must
        # be vetted before the job is ever queued.
        submit = _find_funcdef(service_mod, "submit",
                               cls="DPAggregationService")
        if submit is not None:
            yield from check_knobs(
                _keyword_knobs(submit), service_mod.rel,
                "DPAggregationService.submit",
                _invoked_validators(submit, service_mod),
                "DPAggregationService.submit")

    # Reverse direction: a mapping whose knob no longer exists anywhere
    # is stale — it would silently pass while guarding nothing.
    if entry is not None and backend_mod is not None:
        for knob in sorted(set(KNOB_VALIDATORS) - set(all_knobs)):
            yield Finding(
                "knob-validation", _ENTRY_REL, 1,
                f"KNOB_VALIDATORS maps {knob!r} -> "
                f"{KNOB_VALIDATORS[knob]!r} but no driver, wrapper or "
                f"TPUBackend parameter with that name exists — stale "
                f"mapping; drop it or restore the knob")


# ---------------------------------------------------------------------------
# (7) broad-except
# ---------------------------------------------------------------------------

_BLE_OK = re.compile(r"#\s*noqa:\s*BLE001\s*[-—]\s*\S")


@rule(
    "broad-except",
    "`except Exception` / bare `except:` must carry a classification "
    "comment (`# noqa: BLE001 - <why this breadth is safe>`): the "
    "runtime's retry/degradation machinery depends on exceptions being "
    "CLASSIFIED (transient/oom/timeout/device-fatal), and an "
    "unclassified broad except swallows the classification.")
def broad_except(modules: List[Module]) -> Iterator[Finding]:
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None
            if node.type is not None:
                types = node.type.elts if isinstance(node.type, ast.Tuple)\
                    else [node.type]
                broad = any(mod.dotted(t) == "Exception" for t in types)
            if not broad:
                continue
            if _BLE_OK.search(mod.line_text(node.lineno)):
                continue
            yield Finding(
                "broad-except", mod.rel, node.lineno,
                "broad `except Exception` without a classification "
                "comment — classify-and-reraise (see runtime/retry.py "
                "sites) or annotate `# noqa: BLE001 - <reason>`")


# ---------------------------------------------------------------------------
# Interprocedural families (8-10): one shared call graph per pass
# ---------------------------------------------------------------------------

# Rules 8-10 are flows across functions; they share one CallGraph (and
# the dataflow engines built on it) per analyze() pass instead of each
# re-deriving it. The cache is keyed by the identities of the Module
# objects (core.analyze hands each rule a fresh list wrapping the SAME
# parsed modules).
_GRAPH_CACHE: "collections.OrderedDict[tuple, CallGraph]" = \
    collections.OrderedDict()


def _call_graph(modules: List[Module]) -> CallGraph:
    key = tuple(id(m) for m in modules)
    hit = _GRAPH_CACHE.get(key)
    if hit is None:
        # The entry pins the module list: while it lives, no id in the
        # key can be recycled by the allocator for a different Module.
        hit = (CallGraph(modules), list(modules))
        _GRAPH_CACHE[key] = hit
        while len(_GRAPH_CACHE) > 4:
            _GRAPH_CACHE.popitem(last=False)
    return hit[0]


# ---------------------------------------------------------------------------
# (8) release-taint
# ---------------------------------------------------------------------------

_EXECUTOR_REL = "pipelinedp_tpu/executor.py"
_COLUMNAR_REL = "pipelinedp_tpu/columnar.py"
_INGEST_REL = "pipelinedp_tpu/ingest.py"
_OBSERVABILITY_REL = "pipelinedp_tpu/runtime/observability.py"

# Raw-row sources: functions whose return carries un-noised row-column
# data (encoded codes, partition vocabularies, raw value columns).
TAINT_SOURCES: Dict[Tuple[str, str], str] = {
    (_COLUMNAR_REL, "factorize"): "columnar.factorize",
    (_COLUMNAR_REL, "encode_with_vocab"): "columnar.encode_with_vocab",
    (_COLUMNAR_REL, "encode_columns"): "columnar.encode_columns",
    (_COLUMNAR_REL, "encode"): "columnar.encode",
    (_INGEST_REL, "chunk_factorize"): "ingest.chunk_factorize",
    (_INGEST_REL, "stream_encode_columns"):
        "ingest.stream_encode_columns",
    (_INGEST_REL, "encode_shard"): "ingest.encode_shard",
    (_INGEST_REL, "encode_local_shard_to_mesh"):
        "ingest.encode_local_shard_to_mesh",
    (_INGEST_REL, "ChunkedVocabEncoder.encode"):
        "ChunkedVocabEncoder.encode",
    (_INGEST_REL, "ChunkedVocabEncoder.merge"):
        "ChunkedVocabEncoder.merge",
    (_INGEST_REL, "ChunkedVocabEncoder.vocabulary"):
        "ChunkedVocabEncoder.vocabulary",
}

# DP release points: values coming out of these are noised and/or
# DP-threshold-selected — taint is cleared. (Bounding/offset kernels are
# deliberately NOT here: bounded-but-un-noised stats are still raw.)
TAINT_SANITIZERS: Set[Tuple[str, str]] = {
    (_EXECUTOR_REL, "aggregate_kernel"),
    (_EXECUTOR_REL, "select_kept_pair_stream"),
    (_EXECUTOR_REL, "select_partitions_kernel"),
    (_EXECUTOR_REL, "sweep_kernel"),
    ("pipelinedp_tpu/parallel/large_p.py", "_block_kernel_dev"),
    ("pipelinedp_tpu/parallel/large_p.py", "_selection_block_kernel"),
    ("pipelinedp_tpu/parallel/large_p.py", "_sharded_block_kernel"),
    ("pipelinedp_tpu/parallel/large_p.py", "_sharded_selection_block"),
    ("pipelinedp_tpu/parallel/large_p.py", "_sharded_select_compact"),
    ("pipelinedp_tpu/parallel/sharded.py", "_sharded_kernel"),
    ("pipelinedp_tpu/parallel/sharded.py", "_sharded_select_kernel"),
    ("pipelinedp_tpu/ops/selection_ops.py", "sample_keep_decisions"),
    ("pipelinedp_tpu/ops/noise.py", "laplace_noise"),
    ("pipelinedp_tpu/ops/noise.py", "gaussian_noise"),
    ("pipelinedp_tpu/ops/noise.py", "additive_noise"),
    ("pipelinedp_tpu/dp_computations.py", "apply_laplace_mechanism"),
    ("pipelinedp_tpu/dp_computations.py", "apply_gaussian_mechanism"),
    ("pipelinedp_tpu/dp_computations.py", "_add_random_noise"),
    ("pipelinedp_tpu/dp_computations.py", "add_noise_vector"),
    ("pipelinedp_tpu/dp_computations.py", "compute_dp_var"),
}

# Mechanism methods sanitize wherever the receiver came from.
TAINT_SANITIZER_ATTRS = frozenset({
    "add_noise", "compute_mean", "add_noise_vector",
})
TAINT_SANITIZER_DOTTED = frozenset()

# Cardinality/metadata declassifiers (module docstring of dataflow.py).
TAINT_DECLASS_CALLS = frozenset({"len", "bool", "isinstance", "hasattr",
                                 "id", "type", "range"})
TAINT_DECLASS_ATTRS = frozenset({"shape", "ndim", "size", "nbytes",
                                 "dtype", "n_rows", "n_partitions",
                                 "itemsize"})

# Driver release functions: the engine-facing normalization points whose
# return/yield IS the released output — anything tainted leaving here
# un-noised is a privacy leak, not a telemetry nit.
TAINT_RELEASE_FUNCS: Set[Tuple[str, str]] = {
    (_EXECUTOR_REL, "lazy_aggregate"),
    (_EXECUTOR_REL, "lazy_select_partitions"),
}

# Observability entry points that serialize their arguments off-process.
_OBS_EXPORT_FUNCS = frozenset({
    "export_process_state", "write_pod_rollup", "record_mechanism",
    "persist_odometer", "account_bytes", "release_bytes",
})


def _taint_sink_args(graph, mod, scope, call, callee):
    """Sink detector for release-taint (dataflow.TaintConfig.sink_args):
    [(sink label, [arg expressions whose taint is a finding])]."""
    hits = []
    dotted = mod.dotted(call.func) or ""
    leaf = dotted.rsplit(".", 1)[-1]
    attr = call.func.attr if isinstance(call.func, ast.Attribute) else None
    kw_exprs = [kw.value for kw in call.keywords]
    if callee is not None and callee.rel == _OBSERVABILITY_REL and \
            callee.qualname in _OBS_EXPORT_FUNCS:
        hits.append((f"observability export ({callee.qualname})",
                     list(call.args) + kw_exprs))
        return hits
    if leaf == "span" and (
            (callee is not None and
             callee.rel == "pipelinedp_tpu/runtime/trace.py") or
            ".span" in dotted or dotted == "span"):
        hits.append(("trace-span attr", kw_exprs))
    elif attr == "set" and not call.args and call.keywords:
        # Span token attr update: sp.set(bytes=..., rows=...).
        hits.append(("trace-span attr", kw_exprs))
    elif leaf == "instant":
        hits.append(("trace instant attr", kw_exprs))
    elif leaf == "record" and call.args and \
            isinstance(call.args[0], ast.Constant):
        hits.append(("telemetry counter attr",
                     list(call.args[1:]) + kw_exprs))
    elif leaf == "set_gauge" and len(call.args) >= 2:
        hits.append(("telemetry gauge value", [call.args[1]]))
    elif attr == "put" and len(call.args) == 3:
        # BlockJournal.put(job_id, key, record): the persisted payload.
        hits.append(("journal payload", [call.args[1], call.args[2]]))
    return hits


@rule(
    "release-taint",
    "Values derived from raw row columns (columnar/ingest sources) must "
    "pass through a registered DP mechanism (dp_computations mechanisms, "
    "the noised/selection kernels) before reaching an export sink: "
    "trace-span/instant attrs, telemetry.record/set_gauge values, "
    "journal payloads, observability exports, or the drivers' released "
    "return values. Interprocedural: findings carry the full "
    "source->sink call path. Sizes (len/.shape/.nbytes/...) are "
    "cardinality metadata and declassify.")
def release_taint(modules: List[Module]) -> Iterator[Finding]:
    graph = _call_graph(modules)
    cfg = dataflow.TaintConfig(
        sources=TAINT_SOURCES,
        sanitizers=TAINT_SANITIZERS,
        sanitizer_attrs=TAINT_SANITIZER_ATTRS,
        sanitizer_dotted=TAINT_SANITIZER_DOTTED,
        declass_calls=TAINT_DECLASS_CALLS,
        declass_attrs=TAINT_DECLASS_ATTRS,
        release_funcs=TAINT_RELEASE_FUNCS,
        sink_args=_taint_sink_args,
    )
    for f in sorted(dataflow.run_taint(graph, cfg),
                    key=lambda f: (f.rel, f.line, f.sink,
                                   f.origin.label)):
        yield Finding(
            "release-taint", f.rel, f.line,
            f"un-noised raw-row-derived value reaches {f.sink} — route "
            f"it through a registered DP mechanism first, or suppress "
            f"with a reason naming the sanctioned release. Path: "
            f"{f.origin.render_path()} -> {f.sink} ({f.rel}:{f.line})")


# ---------------------------------------------------------------------------
# (9) lock-order
# ---------------------------------------------------------------------------

# Syntactic blocking patterns: calls that can wait on another thread,
# the scheduler, a device or the disk. Receiver-string constants are
# excluded by the engine (",".join() is not Thread.join()).
LOCK_BLOCKING_ATTRS = frozenset({
    "join", "start", "result", "acquire", "wait", "serve_forever",
    "shutdown", "fsync",
})
LOCK_BLOCKING_DOTTED = frozenset({
    "time.sleep", "os.fsync", "subprocess.run", "subprocess.check_call",
    "subprocess.check_output",
})
LOCK_BLOCKING_FUNCS: Set[Tuple[str, str]] = {
    ("pipelinedp_tpu/parallel/mesh.py", "host_fetch"),
    ("pipelinedp_tpu/parallel/mesh.py", "sync_fetch"),
}

_CALLER_HOLDS_RE = re.compile(r"caller holds", re.IGNORECASE)


def _declared_locks(modules: List[Module]
                    ) -> Dict[Tuple[str, str], Set[str]]:
    """{(rel, cls-or-""): lock names} from guarded_by declarations."""
    declared: Dict[Tuple[str, str], Set[str]] = {}
    for mod in modules:
        for stmt in mod.tree.body:
            decl = _guarded_decl(mod, stmt)
            if decl is not None:
                declared.setdefault((mod.rel, ""), set()).add(decl[0])
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for stmt in cls.body:
                decl = _guarded_decl(mod, stmt)
                if decl is not None:
                    declared.setdefault((mod.rel, cls.name),
                                        set()).add(decl[0])
    return declared


def _declared_guarded_attrs(modules: List[Module]
                            ) -> Set[Tuple[str, str, str]]:
    """{(rel, cls-or-"", attr)} of every attribute a ``_GUARDED_BY``
    declaration covers — lock-discipline territory the thread-escape
    rule must not duplicate."""
    out: Set[Tuple[str, str, str]] = set()
    for mod in modules:
        for stmt in mod.tree.body:
            decl = _guarded_decl(mod, stmt)
            if decl is not None:
                out.update((mod.rel, "", attr) for attr in decl[1])
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for stmt in cls.body:
                decl = _guarded_decl(mod, stmt)
                if decl is not None:
                    out.update((mod.rel, cls.name, attr)
                               for attr in decl[1])
    return out


def _lock_name(lock: "dataflow.LockId") -> str:
    rel, cls, name = lock
    owner = f"{cls}." if cls else ""
    return f"{rel}:{owner}{name}"


def _caller_holds_helpers(graph: CallGraph
                          ) -> Dict[Tuple[str, str], str]:
    """Functions whose def line carries a lock-discipline suppression
    documented as 'caller holds <lock>': {func key: lock attr name}."""
    out: Dict[Tuple[str, str], str] = {}
    for info in graph.iter_functions():
        mod = graph.modules[info.rel]
        sup = mod.suppression_for("lock-discipline", info.node.lineno)
        if sup is None or not sup.reason or \
                not _CALLER_HOLDS_RE.search(sup.reason):
            continue
        declared = _declared_locks([mod]).get(
            (info.rel, info.cls or ""), set())
        m = re.search(r"(_[a-z_]*lock[a-z_]*)", sup.reason)
        lock = m.group(1) if m else None
        if lock is None and len(declared) == 1:
            lock = next(iter(declared))
        if lock is not None:
            out[info.key] = lock
    return out


@rule(
    "lock-order",
    "The lock-acquisition graph over the runtime must be acyclic "
    "(a cycle is a deadlock two threads can reach), no blocking call "
    "(queue waits, thread join/start, future result, host_fetch, "
    "sleep, fsync) may run while a lock is held — another thread may "
    "need that lock to make the blocking operation complete — and a "
    "helper documented 'caller holds <lock>' must actually be called "
    "with the lock held at every resolved call site. Interprocedural: "
    "held locks propagate through the call graph and findings carry "
    "the call path.")
def lock_order(modules: List[Module]) -> Iterator[Finding]:
    graph = _call_graph(modules)
    cfg = dataflow.LockConfig(
        declared=_declared_locks(modules),
        blocking_attrs=LOCK_BLOCKING_ATTRS,
        blocking_dotted=LOCK_BLOCKING_DOTTED,
        blocking_funcs=LOCK_BLOCKING_FUNCS,
    )
    report = dataflow.run_locks(graph, cfg)

    # (a) deadlock proof: the acquisition graph must be acyclic.
    for cycle in dataflow.find_lock_cycles(report.edges):
        ring = cycle + cycle[:1]
        witness_rel, witness_line, _ = report.edges[(ring[0], ring[1])]
        yield Finding(
            "lock-order", witness_rel, witness_line,
            "lock-order cycle (deadlock reachable): " +
            " -> ".join(_lock_name(l) for l in ring) +
            " — two threads taking these locks in opposite orders wait "
            "on each other forever; impose one global order")

    # (b) blocking while holding a lock.
    for rel, line, held, site in sorted(
            report.blocking, key=lambda b: (b[0], b[1], b[3].desc)):
        path = (" via " + " -> ".join(site.path)) if site.path else ""
        yield Finding(
            "lock-order", rel, line,
            f"blocking operation {site.desc} while holding "
            f"{_lock_name(held)}{path} — a thread that needs this lock "
            f"to let the operation complete deadlocks (and every other "
            f"contender stalls for the operation's full duration); move "
            f"the wait outside the critical section")

    # (c) caller-holds-lock helpers: verify every resolved call site.
    helpers = _caller_holds_helpers(graph)
    if helpers:
        held_at: Dict[Tuple[str, str],
                      List[Tuple[str, int, Set[str]]]] = {}
        engine = dataflow._LockEngine(graph, cfg)
        for info in graph.iter_functions():
            mod = graph.modules[info.rel]

            def on_call(call, held, info=info, mod=mod):
                callee = graph.resolve_call(mod, call, info)
                if callee is not None and callee.key in helpers:
                    held_at.setdefault(callee.key, []).append(
                        (info.rel, call.lineno,
                         {lock[2] for lock in held}))

            engine._walk(info, on_call, lambda *a: None)
        for key, lock in sorted(helpers.items()):
            for rel, line, held_names in held_at.get(key, []):
                if lock not in held_names:
                    yield Finding(
                        "lock-order", rel, line,
                        f"{key[1]} is documented 'caller holds "
                        f"{lock}' but this call site does not hold it — "
                        f"the helper touches guarded state unlocked")


# ---------------------------------------------------------------------------
# (10) budget-flow
# ---------------------------------------------------------------------------

_BUDGET_REL = "pipelinedp_tpu/budget_accounting.py"
_DP_COMPUTATIONS_REL = "pipelinedp_tpu/dp_computations.py"

# Noise-mechanism constructors: only dp_computations may build them (and
# only from a registered MechanismSpec, via create_additive_mechanism /
# create_mean_mechanism).
_MECHANISM_CONSTRUCTORS = frozenset({
    "LaplaceMechanism", "GaussianMechanism",
})
_MECHANISM_FACTORY_ATTRS = frozenset({
    "create_from_epsilon", "create_from_epsilon_delta",
    "create_from_std_deviation",
})


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _register_calls_referencing(stmts: Iterable[ast.stmt],
                                var: str) -> bool:
    """True when some statement calls *_register_mechanism(...) with
    `var` reachable in its arguments (MechanismSpecInternal wrapping
    included)."""
    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            leaf = func.attr if isinstance(func, ast.Attribute) else \
                (func.id if isinstance(func, ast.Name) else "")
            if leaf != "_register_mechanism":
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if var in _names_in(arg):
                    return True
    return False


@rule(
    "budget-flow",
    "Every constructed MechanismSpec must reach BudgetAccountant."
    "_register_mechanism on all paths (the static dual of the runtime "
    "no_new_mechanisms guard): specs may only be constructed in "
    "budget_accounting.py and must be registered in the same suite "
    "before any return; noise mechanisms (Laplace/Gaussian) may only be "
    "built inside dp_computations.py from a registered spec; "
    "_register_mechanism may only be called from request_budget "
    "(graph-build time); and a request_budget() result must be bound — "
    "a discarded spec is budget spent on noise nobody can calibrate.")
def budget_flow(modules: List[Module]) -> Iterator[Finding]:
    graph = _call_graph(modules)
    for info in graph.iter_functions():
        mod = graph.modules[info.rel]
        fn = info.node
        # (1) + (2): MechanismSpec construction siting + registration.
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf == "MechanismSpec" and (
                    dotted == "MechanismSpec" or
                    dotted.endswith("budget_accounting.MechanismSpec") or
                    ".MechanismSpec" in dotted):
                if info.rel != _BUDGET_REL:
                    yield Finding(
                        "budget-flow", info.rel, node.lineno,
                        "MechanismSpec constructed outside "
                        "budget_accounting.py — specs exist only as "
                        "receipts of BudgetAccountant.request_budget, "
                        "which registers them with the ledger; an "
                        "ad-hoc spec is unaccounted noise")
            # (3): direct mechanism construction outside dp_computations.
            ctor = leaf if leaf in _MECHANISM_CONSTRUCTORS else None
            factory = (node.func.attr
                       if isinstance(node.func, ast.Attribute) and
                       node.func.attr in _MECHANISM_FACTORY_ATTRS
                       else None)
            if (ctor or factory) and info.rel not in (
                    _DP_COMPUTATIONS_REL,):
                what = ctor or factory
                yield Finding(
                    "budget-flow", info.rel, node.lineno,
                    f"noise mechanism built directly ({what}) outside "
                    f"dp_computations.py — mechanisms must be created "
                    f"by create_additive_mechanism/create_mean_mechanism "
                    f"from a MechanismSpec the ledger registered, or "
                    f"the noise it draws is outside every privacy proof")
        # Registration-dominance inside budget_accounting.py.
        if info.rel == _BUDGET_REL:
            yield from _check_spec_registration(mod, info)
        # (4): discarded request_budget results. Only the ACCOUNTANT's
        # request_budget returns the spec receipt; a combiner's
        # same-named hook stores its spec itself and returns None.
        for node in ast.walk(fn):
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                call = node.value
                leaf = (call.func.attr
                        if isinstance(call.func, ast.Attribute)
                        else (call.func.id
                              if isinstance(call.func, ast.Name)
                              else ""))
                resolved = graph.resolve_call(mod, call, info)
                dotted = mod.dotted(call.func) or ""
                accountant_recv = "accountant" in \
                    dotted.rsplit(".", 1)[0].lower()
                if leaf == "request_budget" and (
                        accountant_recv or
                        (resolved is not None and
                         resolved.rel == _BUDGET_REL)):
                    yield Finding(
                        "budget-flow", info.rel, node.lineno,
                        "request_budget() result discarded — the ledger "
                        "registered (and will spend) budget for a "
                        "mechanism whose spec nobody holds, so its noise "
                        "can never be calibrated; bind the returned "
                        "MechanismSpec or drop the request")
        # (5): _register_mechanism called outside request_budget.
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            leaf = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id
                          if isinstance(node.func, ast.Name) else ""))
            if leaf != "_register_mechanism":
                continue
            if info.rel == _BUDGET_REL and info.name in (
                    "request_budget", "_register_mechanism"):
                continue
            yield Finding(
                "budget-flow", info.rel, node.lineno,
                f"_register_mechanism called from {info.qualname} — "
                f"registration belongs to request_budget (graph-build "
                f"time) only; any other caller is the static shape of "
                f"the double-spend no_new_mechanisms guards against")


def _check_spec_registration(mod: Module,
                             info) -> Iterator[Finding]:
    """Within budget_accounting.py: a `x = MechanismSpec(...)` must be
    followed, in the same statement suite, by a _register_mechanism call
    referencing x."""
    def suites(node: ast.AST) -> Iterator[List[ast.stmt]]:
        for child in ast.walk(node):
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(child, field, None)
                if isinstance(stmts, list) and stmts and \
                        isinstance(stmts[0], ast.stmt):
                    yield stmts

    for suite in suites(info.node):
        for i, stmt in enumerate(suite):
            if not (isinstance(stmt, ast.Assign) and
                    isinstance(stmt.value, ast.Call)):
                continue
            dotted = mod.dotted(stmt.value.func) or ""
            if dotted.rsplit(".", 1)[-1] != "MechanismSpec":
                continue
            targets = [t.id for t in stmt.targets
                       if isinstance(t, ast.Name)]
            if not targets:
                continue
            var = targets[0]
            if not _register_calls_referencing(suite[i + 1:], var):
                yield Finding(
                    "budget-flow", mod.rel, stmt.lineno,
                    f"MechanismSpec bound to {var!r} is never passed to "
                    f"_register_mechanism in this suite — a spec that "
                    f"skips the ledger is noise outside the privacy "
                    f"proof; register it (or construct it inside the "
                    f"_register_mechanism call)")


# ---------------------------------------------------------------------------
# (11) thread-escape
# ---------------------------------------------------------------------------


def _loc_desc(loc: Tuple[str, str, str]) -> str:
    rel, cls, name = loc
    return f"self.{name} ({rel}:{cls})" if cls else \
        f"module global {name!r} ({rel})"


@rule(
    "thread-escape",
    "No shared mutable state between thread roots without a common "
    "lock. Thread roots are discovered structurally "
    "(threading.Thread(target=)/Timer, ThreadPoolExecutor.submit/map, "
    "BaseHTTPRequestHandler subclasses, __main__ subprocess entries); "
    "module globals and self.-attributes written from two roots — or "
    "written from one and read from another — where some cross-root "
    "access pair holds no common lock are races, reported with both "
    "root->access call paths. queue/Event/Lock/local state, "
    "immutable-after-__init__ attributes and _GUARDED_BY-declared "
    "attributes (lock-discipline's territory) are declassified "
    "structurally. Consistently-locked-but-undeclared locations get a "
    "fix-it naming the _GUARDED_BY declaration to add.")
def thread_escape(modules: List[Module]) -> Iterator[Finding]:
    graph = _call_graph(modules)
    report = threads_mod.run_threads(graph, _declared_locks(modules),
                                     _declared_guarded_attrs(modules))
    for race in report.races:
        desc = _loc_desc(race.loc)
        if race.kind == "guard-candidate":
            yield Finding(
                "thread-escape", race.rel, race.line,
                f"{desc} is shared across thread roots and every access "
                f"holds {race.candidate_lock!r}, but the attribute is "
                f"not declared — add _GUARDED_BY = guarded_by("
                f"{race.candidate_lock!r}, {race.loc[2]!r}) so the "
                f"lock-discipline rule enforces it from now on. "
                f"Roots: {race.a.root.describe()} and "
                f"{race.b.root.describe()}")
            continue
        fixit = ""
        if race.candidate_lock is not None:
            fixit = (f"; other accesses hold {race.candidate_lock!r} — "
                     f"declare _GUARDED_BY = guarded_by("
                     f"{race.candidate_lock!r}, {race.loc[2]!r}) and "
                     f"take it here")
        yield Finding(
            "thread-escape", race.rel, race.line,
            f"{race.kind} race: {desc} is accessed from two thread "
            f"roots with no common lock{fixit}. "
            f"Path A: {race.a.render()}. Path B: {race.b.render()}")


# ---------------------------------------------------------------------------
# (12) determinism
# ---------------------------------------------------------------------------

# Iteration-order sources: their result's ORDER is not stable across
# processes/runs (set/frozenset iteration under hash randomization,
# directory listings, object identity). Matched by exact canonical
# dotted name (an `ev.set()` never matches bare "set").
DETERMINISM_SOURCES: Dict[str, str] = {
    "set": "set() iteration order",
    "frozenset": "frozenset() iteration order",
    "os.listdir": "os.listdir() order",
    "os.scandir": "os.scandir() order",
    "glob.glob": "glob.glob() order",
    "glob.iglob": "glob.iglob() order",
    "id": "id() value",
}

# Order-insensitive reductions and explicit-ordering constructs clear
# order taint: sorted() IS the sanctioned fix.
DETERMINISM_DECLASS_CALLS = frozenset({
    "sorted", "len", "min", "max", "sum", "any", "all", "bool",
    "isinstance", "hasattr", "range",
    # Sorted-output uniques (numpy/jax sort; pandas.unique does NOT and
    # deliberately has no entry here).
    "numpy.unique", "jax.numpy.unique",
})
DETERMINISM_SANITIZER_ATTRS = frozenset({"sort"})


def _determinism_sink_args(graph, mod, scope, call, callee):
    """Sink detector for the determinism rule: flows whose ORDER is the
    released/persisted/derived artifact."""
    hits = []
    dotted = mod.dotted(call.func) or ""
    leaf = dotted.rsplit(".", 1)[-1]
    attr = call.func.attr if isinstance(call.func, ast.Attribute) else None
    kw_exprs = [kw.value for kw in call.keywords]
    if leaf == "fold_in" and call.args:
        # jax.random.fold_in(key, data): `data` selects the noise
        # stream — an order-dependent value here forks the release.
        hits.append(("fold_in noise-key derivation",
                     list(call.args[1:]) + kw_exprs))
    elif leaf == "make_noise_key":
        hits.append(("noise-key derivation", list(call.args) + kw_exprs))
    elif attr == "put" and len(call.args) == 3:
        # BlockJournal.put(job_id, key, record): the journal KEY —
        # resume-time addressing must be reproducible.
        hits.append(("journal key", [call.args[1]]))
    elif leaf == "record_mechanism":
        # Odometer records must append in a reproducible order, or the
        # ledger's bit-exact left-to-right eps fold diverges on replay.
        hits.append(("odometer record", list(call.args) + kw_exprs))
    return hits


@rule(
    "determinism",
    "Bit-identical releases require order-deterministic flows: values "
    "whose ORDER comes from set()/frozenset iteration, os.listdir/glob "
    "listings or id() must not reach a release sink (the drivers' "
    "released values), a journal key, a fold_in/noise-key derivation "
    "or an odometer record. sorted(...) (and order-insensitive "
    "reductions: len/min/max/sum/any/all) sanitize. Interprocedural: "
    "findings carry the full source->sink call path.")
def determinism(modules: List[Module]) -> Iterator[Finding]:
    graph = _call_graph(modules)
    cfg = dataflow.TaintConfig(
        sources={},
        sanitizers=set(),
        sanitizer_attrs=DETERMINISM_SANITIZER_ATTRS,
        sanitizer_dotted=frozenset(),
        declass_calls=DETERMINISM_DECLASS_CALLS,
        declass_attrs=frozenset({"shape", "ndim", "size", "nbytes",
                                 "dtype", "itemsize"}),
        release_funcs=TAINT_RELEASE_FUNCS,
        sink_args=_determinism_sink_args,
        source_calls=DETERMINISM_SOURCES,
        literal_set_label="set-literal iteration order",
    )
    for f in sorted(dataflow.run_taint(graph, cfg),
                    key=lambda f: (f.rel, f.line, f.sink,
                                   f.origin.label)):
        yield Finding(
            "determinism", f.rel, f.line,
            f"iteration-order-dependent value reaches {f.sink} — the "
            f"order is not stable across processes/restarts, so a "
            f"resumed or retried job would replay a DIFFERENT release; "
            f"sort the flow (sorted(...)) or suppress with a reason "
            f"proving the order cannot vary. Path: "
            f"{f.origin.render_path()} -> {f.sink} ({f.rel}:{f.line})")
