"""AST-based DP-invariant analyzer (``python -m pipelinedp_tpu.staticcheck``).

The system's correctness rests on invariants no unit test can observe
locally: noise keys must be pure ``fold_in(final_key, b)`` derivations,
every mechanism must hit the budget ledger exactly once, device-resident
paths must not smuggle host transfers, and the runtime modules share
state across monitor threads under declared locks. This package parses
every module once into a shared AST model (:mod:`model`) — including a
project call graph — and runs pluggable rules (:mod:`rules`) over it,
producing ``Finding(rule_id, file, line, message)`` records, with
inline suppressions, a committed baseline for grandfathered findings
(:mod:`baseline`), a content-hash model cache (:mod:`cache`) and a CLI
(:mod:`cli`). Five rule families are interprocedural over the call
graph: privacy-release taint (raw row data must be noised before any
export sink, findings carry the source->sink call path), lock-order
deadlock proofs (acyclic acquisition graph, no blocking while locked),
budget-flow verification (every mechanism spec provably reaches the
ledger) — both engines in :mod:`dataflow` — plus the v3 families:
thread-escape race detection over structurally discovered thread roots
(:mod:`threads`, RacerD-style: no annotations, ownership and
immutable-after-init declassify, findings carry both root->access
paths) and determinism proofs (set/listdir/id iteration order must
never reach a release, journal key, fold_in derivation or odometer
record; sorted() sanitizes). The tier-1 gate
(tests/test_staticcheck.py) fails on any non-baselined finding.

See README "Static analysis" for the rule table, the suppression syntax
and the baseline workflow.
"""

from pipelinedp_tpu.staticcheck.baseline import DEFAULT_BASELINE_PATH
from pipelinedp_tpu.staticcheck.cli import default_paths, main, run_tree
from pipelinedp_tpu.staticcheck.core import (Analysis, RULES_VERSION,
                                             analyze, rule_help, rule_ids)
from pipelinedp_tpu.staticcheck.model import (Finding, Module, load_tree,
                                              parse_source)

__all__ = [
    "Analysis", "DEFAULT_BASELINE_PATH", "Finding", "Module",
    "RULES_VERSION", "analyze", "default_paths", "load_tree", "main",
    "parse_source", "rule_help", "rule_ids", "run_tree",
]
