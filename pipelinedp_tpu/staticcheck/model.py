"""Shared source model for the static analyzer.

Every analyzed module is parsed ONCE into a :class:`Module` — AST, source
lines, import-alias map and inline suppressions — and every rule runs
over the same model, so a full-tree pass costs one ``ast.parse`` per file
regardless of how many rules ship.

Suppressions
------------
A finding is silenced in place with::

    some_call()  # staticcheck: disable=rule-id — reason

* Several rules: ``disable=rule-a,rule-b``. ``disable=all`` silences
  every rule on the line.
* The reason follows an em-dash (``—``) or a double dash (``--``). For
  rules in :data:`REASON_REQUIRED` a suppression WITHOUT a reason is
  ignored (and says so in the finding message): those rules guard DP
  invariants, and an unexplained waiver is indistinguishable from a
  mistake two reviews later.
* A suppression on a ``def``/``class`` header line applies to the whole
  body — the form used for helpers documented as "caller holds the
  lock".
* A suppression on a comment-only line applies to the next line.
"""

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

# Rules whose suppressions must carry a reason (see module docstring).
REASON_REQUIRED = frozenset({
    "host-transfer",
    "lock-discipline",
    "key-hygiene",
    # The interprocedural families guard release/deadlock/ledger
    # invariants; an unexplained waiver on any of them is indistinguishable
    # from a leak two reviews later.
    "release-taint",
    "lock-order",
    "budget-flow",
    # The v3 families guard bit-identity itself (a silent race or a
    # set-iteration release breaks it); waivers must say why not.
    "thread-escape",
    "determinism",
})

_SUPPRESS_RE = re.compile(
    r"#\s*staticcheck:\s*disable=([a-z0-9,\- ]+?)"
    r"(?:\s*(?:—|--)\s*(?P<reason>\S.*?))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule_id: str
    file: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule_id}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: Tuple[str, ...]  # ("all",) silences everything
    reason: Optional[str]
    line: int               # line the comment sits on

    def covers(self, rule_id: str) -> bool:
        return "all" in self.rules or rule_id in self.rules


class Module:
    """One parsed source file plus the lookup structures rules share."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.aliases = _import_aliases(self.tree)
        # line -> suppressions active on exactly that line.
        self._line_suppressions: Dict[int, List[Suppression]] = {}
        # (start, end, suppression) ranges from def/class-header comments.
        self._range_suppressions: List[Tuple[int, int, Suppression]] = []
        self._collect_suppressions()

    # -- suppressions ----------------------------------------------------

    def _collect_suppressions(self) -> None:
        comments: Dict[int, Tuple[str, bool]] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    only = not tok.line[:tok.start[1]].strip()
                    comments[tok.start[0]] = (tok.string, only)
        except tokenize.TokenError:
            pass
        header_lines = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                header_lines[node.lineno] = node.end_lineno
        for lineno, (text, comment_only) in comments.items():
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip())
            sup = Suppression(rules=rules, reason=m.group("reason"),
                              line=lineno)
            if comment_only:
                # A standalone comment suppresses the line below it.
                self._line_suppressions.setdefault(lineno + 1, []).append(sup)
            else:
                self._line_suppressions.setdefault(lineno, []).append(sup)
                end = header_lines.get(lineno)
                if end is not None:
                    self._range_suppressions.append((lineno, end, sup))

    def suppression_for(self, rule_id: str,
                        line: int) -> Optional[Suppression]:
        """The suppression covering (rule, line), if any — reason
        requirements are enforced by the caller (core.run)."""
        for sup in self._line_suppressions.get(line, []):
            if sup.covers(rule_id):
                return sup
        for start, end, sup in self._range_suppressions:
            if start <= line <= end and sup.covers(rule_id):
                return sup
        return None

    # -- shared lookups --------------------------------------------------

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with the root
        segment resolved through this module's import aliases — so
        ``np.asarray`` canonicalizes to ``numpy.asarray`` and
        ``jnp.asarray`` to ``jax.numpy.asarray`` regardless of how the
        module spelled its imports."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    @property
    def parts(self) -> Tuple[str, ...]:
        return tuple(self.rel.split("/"))


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                aliases[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and \
                node.level == 0:
            for alias in node.names:
                aliases[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return aliases


def canonical_rel(path: str) -> str:
    """Stable module identity: the path from the ``pipelinedp_tpu``
    package segment onward (posix-separated) — likewise from a
    ``benchmarks``/``examples`` segment for the perf/demo trees — or the
    cwd-relative path for files outside all of them."""
    parts = os.path.abspath(path).split(os.sep)
    for anchor in ("pipelinedp_tpu", "benchmarks", "examples"):
        if anchor in parts:
            return "/".join(parts[parts.index(anchor):])
    return os.path.relpath(path).replace(os.sep, "/")


def module_dotted(rel: str) -> str:
    """Dotted import name of a canonical rel path:
    ``pipelinedp_tpu/runtime/telemetry.py`` -> ``pipelinedp_tpu.runtime.
    telemetry``; package ``__init__.py`` maps to the package itself."""
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[:-len(".__init__")]
    return name


def parse_source(rel: str, source: str) -> Module:
    """Parses an in-memory snippet as a module (fixtures, tests)."""
    return Module(rel, source)


def parse_file(path: str) -> Module:
    with open(path, encoding="utf-8") as f:
        return Module(canonical_rel(path), f.read())


DEFAULT_EXCLUDED_DIRS = frozenset({
    "__pycache__", ".git", "build", "dist", "node_modules",
    # Perf-harness code is measured, not analyzed: benchmarks stage data
    # to/from the host by design, so every transfer lint there is noise.
    "benchmarks",
})


def iter_python_files(paths: Iterable[str],
                      excluded_dirs: frozenset = DEFAULT_EXCLUDED_DIRS
                      ) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in excluded_dirs)
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    out.append(os.path.join(dirpath, fname))
    return out


def load_tree(paths: Iterable[str]) -> List[Module]:
    """Parses every .py under the given paths into the shared model."""
    modules = []
    for path in iter_python_files(paths):
        modules.append(parse_file(path))
    return modules


# ---------------------------------------------------------------------------
# Project call graph + per-function summary layer
# ---------------------------------------------------------------------------
#
# The interprocedural rule families (release-taint, lock-order,
# budget-flow) quantify over *flows across functions*, which needs one
# shared answer to "which function does this call reach?". The graph is
# deliberately syntactic and conservative:
#
#   * bare names resolve through the lexical scope chain (nested defs,
#     then module level), `self.m()` resolves through the class and its
#     project-resolvable bases, and dotted calls resolve through the
#     same import-alias canonicalization Module.dotted already applies —
#     so `tele.record(...)` lands on runtime/telemetry.py:record however
#     the import was spelled;
#   * a call that cannot be resolved to a project function returns None.
#     Each rule states its own unknown-callee policy (taint passes
#     through conservatively; lock/budget facts are only claimed for
#     resolved callees) — see dataflow.py.


@dataclasses.dataclass
class FunctionInfo:
    """One function/method (including nested defs) in the project."""
    rel: str
    qualname: str               # "f", "Cls.m", "outer.inner"
    node: ast.AST               # FunctionDef / AsyncFunctionDef
    cls: Optional[str]          # enclosing class name, if a method
    enclosing: Tuple[str, ...]  # qualnames of enclosing functions, outer->in

    @property
    def key(self) -> Tuple[str, str]:
        return (self.rel, self.qualname)

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        return names


@dataclasses.dataclass
class ClassInfo:
    rel: str
    name: str
    node: ast.ClassDef
    bases: Tuple[str, ...]      # canonical dotted base names


class CallGraph:
    """Project-wide function index + call resolution over the shared
    model. Build once per analysis pass and share across rules."""

    def __init__(self, modules: Iterable[Module]):
        self.modules: Dict[str, Module] = {m.rel: m for m in modules}
        self.by_dotted: Dict[str, Module] = {
            module_dotted(rel): m for rel, m in self.modules.items()
        }
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        # (id(call node), scope qualname) -> resolution. The graph owns
        # the modules (and therefore the AST nodes), so node ids stay
        # pinned for its lifetime; fixpoint engines re-resolve the same
        # call sites every round, and memoizing here is what keeps the
        # interprocedural pass at seconds on the full tree.
        self._resolve_memo: Dict[Tuple[int, Optional[str]],
                                 Optional["FunctionInfo"]] = {}
        for mod in self.modules.values():
            self._index_module(mod)

    # -- indexing --------------------------------------------------------

    def _index_module(self, mod: Module) -> None:
        def walk(node: ast.AST, cls: Optional[str],
                 enclosing: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    if not enclosing and cls is None:
                        self.classes[(mod.rel, child.name)] = ClassInfo(
                            rel=mod.rel, name=child.name, node=child,
                            bases=tuple(
                                d for d in (mod.dotted(b)
                                            for b in child.bases)
                                if d is not None))
                    walk(child, child.name, enclosing)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    prefix = ".".join(enclosing)
                    qual = (f"{cls}.{child.name}" if cls and not enclosing
                            else (f"{prefix}.{child.name}" if prefix
                                  else child.name))
                    info = FunctionInfo(rel=mod.rel, qualname=qual,
                                        node=child,
                                        cls=cls if not enclosing else None,
                                        enclosing=enclosing)
                    self.functions[info.key] = info
                    walk(child, None, enclosing + (qual,))
                else:
                    walk(child, cls, enclosing)

        walk(mod.tree, None, ())

    def iter_functions(self) -> Iterable[FunctionInfo]:
        return self.functions.values()

    # -- resolution ------------------------------------------------------

    def _resolve_class(self, mod: Module,
                       dotted: str) -> Optional[ClassInfo]:
        if "." not in dotted:
            return self.classes.get((mod.rel, dotted))
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            owner = self.by_dotted.get(".".join(parts[:i]))
            if owner is not None and len(parts) - i == 1:
                return self.classes.get((owner.rel, parts[i]))
        return None

    def resolve_method(self, rel: str, cls: str,
                       name: str) -> Optional[FunctionInfo]:
        """Method lookup through the class and its project bases."""
        seen = set()
        queue = [(rel, cls)]
        while queue:
            key = queue.pop(0)
            if key in seen:
                continue
            seen.add(key)
            hit = self.functions.get((key[0], f"{key[1]}.{name}"))
            if hit is not None:
                return hit
            info = self.classes.get(key)
            if info is None:
                continue
            owner = self.modules.get(key[0])
            for base in info.bases:
                base_cls = self._resolve_class(owner, base)
                if base_cls is not None:
                    queue.append((base_cls.rel, base_cls.name))
        return None

    def resolve_call(self, mod: Module, call: ast.Call,
                     scope: Optional[FunctionInfo] = None
                     ) -> Optional[FunctionInfo]:
        """The project function a call lands on, or None (unknown:
        builtins, third-party, dynamic dispatch on locals)."""
        memo_key = (id(call), scope.qualname if scope else None)
        if memo_key in self._resolve_memo:
            return self._resolve_memo[memo_key]
        hit = self._resolve_call_uncached(mod, call, scope)
        self._resolve_memo[memo_key] = hit
        return hit

    def _resolve_call_uncached(self, mod: Module, call: ast.Call,
                               scope: Optional[FunctionInfo] = None
                               ) -> Optional[FunctionInfo]:
        dotted = mod.dotted(call.func)
        if dotted is None:
            return None
        parts = dotted.split(".")
        # self.m() -> method of the enclosing class (or a base).
        if parts[0] == "self" and len(parts) == 2 and scope is not None:
            cls = scope.cls
            if cls is None and scope.enclosing:
                outer = self.functions.get((mod.rel, scope.enclosing[0]))
                cls = outer.cls if outer is not None else None
            if cls is not None:
                return self.resolve_method(mod.rel, cls, parts[1])
            return None
        if len(parts) == 1:
            name = parts[0]
            # Lexical chain: nested defs of the enclosing functions first.
            if scope is not None:
                chain = scope.enclosing + (scope.qualname,)
                for outer in reversed(chain):
                    hit = self.functions.get((mod.rel, f"{outer}.{name}"))
                    if hit is not None:
                        return hit
            hit = self.functions.get((mod.rel, name))
            if hit is not None:
                return hit
            cls_info = self.classes.get((mod.rel, name))
            if cls_info is not None:
                return self.resolve_method(mod.rel, name, "__init__")
            return None
        # Dotted: longest prefix that names a project module.
        for i in range(len(parts) - 1, 0, -1):
            owner = self.by_dotted.get(".".join(parts[:i]))
            if owner is None:
                continue
            rest = parts[i:]
            if len(rest) == 1:
                hit = self.functions.get((owner.rel, rest[0]))
                if hit is not None:
                    return hit
                if (owner.rel, rest[0]) in self.classes:
                    return self.resolve_method(owner.rel, rest[0],
                                               "__init__")
                return None
            if len(rest) == 2:
                if (owner.rel, rest[0]) in self.classes:
                    return self.resolve_method(owner.rel, rest[0], rest[1])
                return None
            return None
        return None
