"""Shared source model for the static analyzer.

Every analyzed module is parsed ONCE into a :class:`Module` — AST, source
lines, import-alias map and inline suppressions — and every rule runs
over the same model, so a full-tree pass costs one ``ast.parse`` per file
regardless of how many rules ship.

Suppressions
------------
A finding is silenced in place with::

    some_call()  # staticcheck: disable=rule-id — reason

* Several rules: ``disable=rule-a,rule-b``. ``disable=all`` silences
  every rule on the line.
* The reason follows an em-dash (``—``) or a double dash (``--``). For
  rules in :data:`REASON_REQUIRED` a suppression WITHOUT a reason is
  ignored (and says so in the finding message): those rules guard DP
  invariants, and an unexplained waiver is indistinguishable from a
  mistake two reviews later.
* A suppression on a ``def``/``class`` header line applies to the whole
  body — the form used for helpers documented as "caller holds the
  lock".
* A suppression on a comment-only line applies to the next line.
"""

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

# Rules whose suppressions must carry a reason (see module docstring).
REASON_REQUIRED = frozenset({
    "host-transfer",
    "lock-discipline",
    "key-hygiene",
})

_SUPPRESS_RE = re.compile(
    r"#\s*staticcheck:\s*disable=([a-z0-9,\- ]+?)"
    r"(?:\s*(?:—|--)\s*(?P<reason>\S.*?))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule_id: str
    file: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule_id}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: Tuple[str, ...]  # ("all",) silences everything
    reason: Optional[str]
    line: int               # line the comment sits on

    def covers(self, rule_id: str) -> bool:
        return "all" in self.rules or rule_id in self.rules


class Module:
    """One parsed source file plus the lookup structures rules share."""

    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.aliases = _import_aliases(self.tree)
        # line -> suppressions active on exactly that line.
        self._line_suppressions: Dict[int, List[Suppression]] = {}
        # (start, end, suppression) ranges from def/class-header comments.
        self._range_suppressions: List[Tuple[int, int, Suppression]] = []
        self._collect_suppressions()

    # -- suppressions ----------------------------------------------------

    def _collect_suppressions(self) -> None:
        comments: Dict[int, Tuple[str, bool]] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    only = not tok.line[:tok.start[1]].strip()
                    comments[tok.start[0]] = (tok.string, only)
        except tokenize.TokenError:
            pass
        header_lines = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                header_lines[node.lineno] = node.end_lineno
        for lineno, (text, comment_only) in comments.items():
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip())
            sup = Suppression(rules=rules, reason=m.group("reason"),
                              line=lineno)
            if comment_only:
                # A standalone comment suppresses the line below it.
                self._line_suppressions.setdefault(lineno + 1, []).append(sup)
            else:
                self._line_suppressions.setdefault(lineno, []).append(sup)
                end = header_lines.get(lineno)
                if end is not None:
                    self._range_suppressions.append((lineno, end, sup))

    def suppression_for(self, rule_id: str,
                        line: int) -> Optional[Suppression]:
        """The suppression covering (rule, line), if any — reason
        requirements are enforced by the caller (core.run)."""
        for sup in self._line_suppressions.get(line, []):
            if sup.covers(rule_id):
                return sup
        for start, end, sup in self._range_suppressions:
            if start <= line <= end and sup.covers(rule_id):
                return sup
        return None

    # -- shared lookups --------------------------------------------------

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with the root
        segment resolved through this module's import aliases — so
        ``np.asarray`` canonicalizes to ``numpy.asarray`` and
        ``jnp.asarray`` to ``jax.numpy.asarray`` regardless of how the
        module spelled its imports."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    @property
    def parts(self) -> Tuple[str, ...]:
        return tuple(self.rel.split("/"))


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                aliases[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and \
                node.level == 0:
            for alias in node.names:
                aliases[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return aliases


def canonical_rel(path: str) -> str:
    """Stable module identity: the path from the ``pipelinedp_tpu``
    package segment onward (posix-separated), or the cwd-relative path
    for files outside the package."""
    parts = os.path.abspath(path).split(os.sep)
    if "pipelinedp_tpu" in parts:
        return "/".join(parts[parts.index("pipelinedp_tpu"):])
    return os.path.relpath(path).replace(os.sep, "/")


def parse_source(rel: str, source: str) -> Module:
    """Parses an in-memory snippet as a module (fixtures, tests)."""
    return Module(rel, source)


def parse_file(path: str) -> Module:
    with open(path, encoding="utf-8") as f:
        return Module(canonical_rel(path), f.read())


DEFAULT_EXCLUDED_DIRS = frozenset({
    "__pycache__", ".git", "build", "dist", "node_modules",
    # Perf-harness code is measured, not analyzed: benchmarks stage data
    # to/from the host by design, so every transfer lint there is noise.
    "benchmarks",
})


def iter_python_files(paths: Iterable[str],
                      excluded_dirs: frozenset = DEFAULT_EXCLUDED_DIRS
                      ) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in excluded_dirs)
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    out.append(os.path.join(dirpath, fname))
    return out


def load_tree(paths: Iterable[str]) -> List[Module]:
    """Parses every .py under the given paths into the shared model."""
    modules = []
    for path in iter_python_files(paths):
        modules.append(parse_file(path))
    return modules
