"""Thread-escape race detection over the project call graph.

The repo now runs at least seven threaded subsystems (the service
worker pool, the blocked drivers' drainer thread, ``map_overlapped``'s
feeder + encode pool, the watchdog monitor, the metrics HTTP exporter,
multihost children, the ledger's persist loop). PR 7's lock-discipline
rule protects only attributes someone remembered to declare
``_GUARDED_BY``; this module closes the gap the way RacerD does —
*structurally*, with no annotations required:

  1. **Thread roots** are discovered from the spawn sites themselves:
     ``threading.Thread(target=f)``, ``threading.Timer(t, f)``,
     ``ThreadPoolExecutor.submit(f, ...)`` / ``.map(f, ...)``, methods
     of ``BaseHTTPRequestHandler`` subclasses (each request runs on a
     server thread), and project functions invoked from an
     ``if __name__ == "__main__":`` block (subprocess entry points —
     ``multihost._child_main``). The watchdog monitor is a plain
     ``Thread(target=self._run_monitor)`` and needs no special case.
  2. **Per-root reachability** walks the shared :class:`model.CallGraph`
     from each root, propagating the set of locks *guaranteed held at
     entry* (intersection over all discovered call chains, union'd with
     the locks held at each call site — so a helper only ever called
     under ``self._lock`` is analyzed as holding it).
  3. **Shared-state accesses** (module-global reads/writes, ``self.``
     attribute reads/writes, container mutations through either) are
     collected per function under the same held-lock scoping the
     lock-order engine uses.
  4. A location written from two different roots — or written from one
     and read from another — where some cross-root access pair holds
     **no common lock** is a race. Findings carry both full
     root→access call paths (same hop format and 10-hop cap as taint
     paths).

Declassified structurally, never by baseline:

  * state reached only through **concurrency primitives**
    (``queue.Queue``, ``threading.Event``/``Lock``/``Semaphore``/
    ``local``, ``collections.deque``) — synchronized by construction;
  * **immutable-after-init** attributes: every write sits in the
    owner's ``__init__``/``__new__`` (construction happens-before
    thread start / publication);
  * attributes already **declared** ``_GUARDED_BY``: the lock-discipline
    rule proves every access locked — re-reporting them here would
    duplicate that family, so this one only covers what it missed.

When the accesses of an undeclared location *are* consistently guarded
by one lock, the report carries a fix-it naming the ``_GUARDED_BY``
declaration to add — racy-but-partially-locked locations name the same
candidate, so the fix is one declaration plus taking the lock at the
flagged site.
"""

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from pipelinedp_tpu.staticcheck import dataflow
from pipelinedp_tpu.staticcheck.model import CallGraph, FunctionInfo, Module

_MAX_PATH = 10

# A shared location: (rel, owner-class-or-"", name). Same identity
# convention as dataflow.LockId, so lock/attr ownership lines up.
Loc = Tuple[str, str, str]

# Constructors whose product is synchronized (or thread-local) by
# construction: state reached only through one of these is declassified.
_PRIMITIVE_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
    "threading.Thread", "queue.Queue", "queue.PriorityQueue",
    "queue.LifoQueue", "queue.SimpleQueue", "collections.deque",
})

# Method calls that mutate their receiver in place: `g.append(x)` is a
# WRITE to g even though g's name appears in Load context.
_MUTATOR_ATTRS = frozenset({
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "sort", "reverse",
    "appendleft", "popleft",
})

# `.submit(f, ...)` / `.map(f, ...)` receivers that are thread pools:
# either provably constructed from ThreadPoolExecutor in the module, or
# named like one. (`backend.map(col, fn)` never matches — the receiver
# heuristic is what keeps the pipeline-backend API out.)
_EXECUTOR_RECV_RE = re.compile(r"pool|executor", re.IGNORECASE)


# ---------------------------------------------------------------------------
# Thread-root discovery
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    kind: str                # the structural spawn pattern matched
    func: Tuple[str, str]    # (rel, qualname) of the root function
    rel: str                 # spawn site
    line: int

    def describe(self) -> str:
        return f"{self.func[1]} [{self.kind} @ {self.rel}:{self.line}]"


def _walk_scope(root: ast.AST) -> Iterator[ast.AST]:
    """Nodes of one function scope: nested defs/lambdas/classes are
    separate FunctionInfos and are walked on their own."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _resolve_ref(graph: CallGraph, mod: Module,
                 scope: Optional[FunctionInfo],
                 expr: ast.AST) -> Optional[FunctionInfo]:
    """Resolves a callable REFERENCE (``target=f``, ``submit(f, ..)``)
    exactly the way a call to it would resolve."""
    if not isinstance(expr, (ast.Name, ast.Attribute)):
        return None
    call = ast.Call(func=expr, args=[], keywords=[])
    # Uncached resolve: the synthetic Call's id is not stable, so it
    # must never enter the graph's id-keyed memo.
    return graph._resolve_call_uncached(mod, call, scope)


def _executor_vars(mod: Module) -> Set[str]:
    """Names assigned from a ThreadPoolExecutor constructor anywhere in
    the module (closure use included — the collection is deliberately
    scope-insensitive)."""
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        dotted = mod.dotted(node.value.func) or ""
        if dotted.rsplit(".", 1)[-1] != "ThreadPoolExecutor":
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _is_main_guard(test: ast.AST) -> bool:
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1 and
            isinstance(test.ops[0], ast.Eq)):
        return False
    sides = [test.left] + list(test.comparators)
    names = {n.id for n in sides if isinstance(n, ast.Name)}
    consts = {c.value for c in sides if isinstance(c, ast.Constant)}
    return "__name__" in names and "__main__" in consts


def discover_roots(graph: CallGraph) -> List[ThreadRoot]:
    """Every structurally-discovered thread root, sorted for stable
    reporting. See the module docstring for the pattern list."""
    roots: Dict[Tuple[str, str], ThreadRoot] = {}

    def note(fn: Optional[FunctionInfo], kind: str, rel: str,
             line: int) -> None:
        if fn is not None:
            roots.setdefault(fn.key,
                             ThreadRoot(kind=kind, func=fn.key, rel=rel,
                                        line=line))

    scopes: List[Tuple[Module, Optional[FunctionInfo], ast.AST]] = []
    for info in graph.iter_functions():
        scopes.append((graph.modules[info.rel], info, info.node))
    for mod in graph.modules.values():
        scopes.append((mod, None, mod.tree))

    pool_cache: Dict[str, Set[str]] = {}
    for mod, scope, tree in scopes:
        pool_names = pool_cache.get(mod.rel)
        if pool_names is None:
            pool_names = pool_cache[mod.rel] = _executor_vars(mod)
        for node in _walk_scope(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf == "Thread" and dotted.endswith("threading.Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        note(_resolve_ref(graph, mod, scope, kw.value),
                             "Thread(target=)", mod.rel, node.lineno)
            elif leaf == "Timer" and dotted.endswith("threading.Timer") \
                    and len(node.args) >= 2:
                note(_resolve_ref(graph, mod, scope, node.args[1]),
                     "Timer", mod.rel, node.lineno)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("submit", "map") and node.args:
                recv = mod.dotted(node.func.value) or ""
                recv_leaf = recv.rsplit(".", 1)[-1]
                if recv_leaf in pool_names or \
                        _EXECUTOR_RECV_RE.search(recv_leaf):
                    note(_resolve_ref(graph, mod, scope, node.args[0]),
                         f"executor.{node.func.attr}", mod.rel,
                         node.lineno)

    # HTTP handler classes: every request runs each handler method on a
    # server thread.
    handler_classes = {
        key for key, cls in graph.classes.items()
        if any("BaseHTTPRequestHandler" in b for b in cls.bases)
    }
    for info in graph.iter_functions():
        if info.cls is not None and (info.rel, info.cls) in handler_classes:
            note(info, "http-handler", info.rel, info.node.lineno)

    # `if __name__ == "__main__":` project calls: subprocess/CLI entry
    # points (multihost's spawned controllers run _child_main this way).
    for mod in graph.modules.values():
        for stmt in mod.tree.body:
            if not (isinstance(stmt, ast.If) and _is_main_guard(stmt.test)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    note(graph.resolve_call(mod, node, None),
                         "__main__ entry", mod.rel, node.lineno)

    return sorted(roots.values(), key=lambda r: (r.func, r.rel, r.line))


# ---------------------------------------------------------------------------
# Per-root reachability with guaranteed-held entry locks
# ---------------------------------------------------------------------------


def _ctor_types(graph: CallGraph, mod: Module,
                info: FunctionInfo) -> Dict[str, Tuple[str, str]]:
    """Local names assigned from a project-class constructor in this
    function: {name: (rel, class)}. The one step of type inference the
    syntactic graph lacks — `engine = DPEngine(...)` followed by
    `engine.aggregate(...)` resolves through it, which is what carries
    the service worker root into the engine's cone."""
    out: Dict[str, Tuple[str, str]] = {}
    for node in _walk_scope(info.node):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        callee = graph.resolve_call(mod, node.value, info)
        if callee is None or callee.cls is None or \
                callee.name != "__init__":
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = (callee.rel, callee.cls)
    return out


def _reachable(graph: CallGraph, engine: "dataflow._LockEngine",
               ctor_cache: Dict[Tuple[str, str],
                                Dict[str, Tuple[str, str]]],
               root: Tuple[str, str]
               ) -> Tuple[Dict[Tuple[str, str], FrozenSet],
                          Dict[Tuple[str, str], Tuple[str, ...]]]:
    """(entry_locks, path) per function reachable from ``root``.

    entry_locks[f] is the set of locks held on EVERY discovered call
    chain root→f (intersection — only guaranteed locks count toward a
    common-lock proof). path[f] is the first-discovered chain, hop
    format identical to taint paths, capped at _MAX_PATH. Converges on
    recursive (even self-spawning) code: entries only shrink and the
    visited set is keyed by function."""
    entry: Dict[Tuple[str, str], FrozenSet] = {root: frozenset()}
    paths: Dict[Tuple[str, str], Tuple[str, ...]] = {root: ()}
    work = [root]
    while work:
        fkey = work.pop()
        info = graph.functions.get(fkey)
        if info is None:
            continue
        mod = graph.modules[fkey[0]]
        base = entry[fkey]
        ctors = ctor_cache.get(fkey)
        if ctors is None:
            ctors = ctor_cache[fkey] = _ctor_types(graph, mod, info)
        for event in engine._function_events(info):
            if event[0] != "call":
                continue
            call, held = event[1], event[2]
            callee = graph.resolve_call(mod, call, info)
            if callee is None and \
                    isinstance(call.func, ast.Attribute) and \
                    isinstance(call.func.value, ast.Name):
                typ = ctors.get(call.func.value.id)
                if typ is not None:
                    callee = graph.resolve_method(typ[0], typ[1],
                                                  call.func.attr)
            if callee is None:
                continue
            new_entry = frozenset(base | set(held))
            old = entry.get(callee.key)
            if old is None:
                entry[callee.key] = new_entry
                hop = f"{callee.qualname} ({info.rel}:{call.lineno})"
                paths[callee.key] = (paths[fkey] + (hop,))[:_MAX_PATH]
                work.append(callee.key)
            elif not old <= new_entry:
                entry[callee.key] = old & new_entry
                work.append(callee.key)
    return entry, paths


# ---------------------------------------------------------------------------
# Shared-state access collection
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Access:
    loc: Loc
    write: bool
    rel: str
    line: int
    locks: FrozenSet    # locks held at the access (local `with` scoping)


def _module_globals(mod: Module) -> Set[str]:
    """Names bound by module-scope statements (assignment targets, not
    defs/classes/imports)."""
    out: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom)):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Store):
                out.add(node.id)
    return out


def _primitive_locs(graph: CallGraph) -> Set[Loc]:
    """Locations whose (every observed) initializer is a concurrency
    primitive: module globals assigned one at module scope, and
    ``self.x = threading.Event()``-style attributes anywhere in the
    owner class."""
    out: Set[Loc] = set()

    def ctor_of(value: ast.AST, mod: Module) -> bool:
        return isinstance(value, ast.Call) and \
            (mod.dotted(value.func) or "") in _PRIMITIVE_CTORS

    for mod in graph.modules.values():
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and ctor_of(stmt.value, mod):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add((mod.rel, "", t.id))
    for info in graph.iter_functions():
        if info.cls is None:
            continue
        mod = graph.modules[info.rel]
        for node in _walk_scope(info.node):
            if not (isinstance(node, ast.Assign) and
                    ctor_of(node.value, mod)):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.add((info.rel, info.cls, t.attr))
    return out


def _owner_class(graph: CallGraph, info: FunctionInfo) -> Optional[str]:
    """The class owning ``self`` inside ``info`` (methods directly;
    nested defs through their enclosing method)."""
    if info.cls is not None:
        return info.cls
    if info.enclosing:
        outer = graph.functions.get((info.rel, info.enclosing[0]))
        if outer is not None:
            return outer.cls
    return None


def _local_names(info: FunctionInfo) -> Set[str]:
    """Names that are function-local in ``info`` (params + stores),
    minus explicit ``global`` declarations."""
    args = info.node.args
    names = {a.arg for a in (args.posonlyargs + args.args +
                             args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    declared_global: Set[str] = set()
    for node in _walk_scope(info.node):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])
    return names - declared_global


class _AccessCollector:
    """Per-function shared-state access walk under held-lock scoping."""

    def __init__(self, graph: CallGraph, cfg: "dataflow.LockConfig",
                 skip: Set[Loc]):
        self.graph = graph
        self.cfg = cfg
        self.skip = skip      # primitives + declared-guarded locations
        self._mod_globals: Dict[str, Set[str]] = {}

    def module_globals(self, mod: Module) -> Set[str]:
        hit = self._mod_globals.get(mod.rel)
        if hit is None:
            hit = self._mod_globals[mod.rel] = _module_globals(mod)
        return hit

    def collect(self, info: FunctionInfo) -> List[Access]:
        mod = self.graph.modules[info.rel]
        mod_globals = self.module_globals(mod)
        local = _local_names(info)
        owner = _owner_class(self.graph, info)
        out: List[Access] = []

        def loc_of_name(name: str) -> Optional[Loc]:
            if name in local or name not in mod_globals:
                return None
            loc = (info.rel, "", name)
            return None if loc in self.skip else loc

        def loc_of_expr(node: ast.AST) -> Optional[Loc]:
            if isinstance(node, ast.Name):
                return loc_of_name(node.id)
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and owner is not None:
                loc = (info.rel, owner, node.attr)
                return None if loc in self.skip else loc
            return None

        def emit(loc: Optional[Loc], write: bool, line: int,
                 held: Tuple) -> None:
            if loc is not None:
                out.append(Access(loc=loc, write=write, rel=info.rel,
                                  line=line, locks=frozenset(held)))

        def visit(node: ast.AST, held: Tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return  # separate scope, runs outside these locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock = dataflow._lock_of_with_item(
                        mod, self.cfg, item, info)
                    if lock is not None:
                        acquired.append(lock)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_ATTRS:
                emit(loc_of_expr(node.func.value), True, node.lineno,
                     held)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                emit(loc_of_expr(node.value), True, node.lineno, held)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                emit(loc_of_expr(node),
                     isinstance(node.ctx, (ast.Store, ast.Del)),
                     node.lineno, held)
                return  # the `self` Name below it is not an access
            elif isinstance(node, ast.Name):
                emit(loc_of_name(node.id),
                     isinstance(node.ctx, (ast.Store, ast.Del)),
                     node.lineno, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in info.node.body:
            visit(stmt, ())
        return out


# ---------------------------------------------------------------------------
# Race computation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RaceAccess:
    root: ThreadRoot
    access: Access
    # Guaranteed entry locks of the function containing the access.
    entry_locks: FrozenSet
    path: Tuple[str, ...]
    # Class-level ownership (RacerD's idea at our per-class identity):
    # True when the owner class's __init__ is in this root's cone — the
    # root manufactures its own instances, so its accesses land on
    # thread-confined state unless the instance is published. A pair of
    # OWNED accesses from two roots is two instances, not a race. (The
    # known miss: a root that both constructs and receives shared
    # instances of the same class.)
    owned: bool = False

    @property
    def locks(self) -> FrozenSet:
        return self.access.locks | self.entry_locks

    def render(self) -> str:
        verb = "write" if self.access.write else "read"
        chain = (f"root {self.root.describe()}",) + self.path + (
            f"{verb} at {self.access.rel}:{self.access.line}",)
        return " -> ".join(chain)


@dataclasses.dataclass(frozen=True)
class RaceFinding:
    loc: Loc
    kind: str            # "write-write" | "write-read" | "guard-candidate"
    rel: str
    line: int
    a: RaceAccess
    b: RaceAccess
    candidate_lock: Optional[str]   # lock attr name for the fix-it


@dataclasses.dataclass
class ThreadReport:
    roots: List[ThreadRoot]
    races: List[RaceFinding]


def _is_init_qualname(qualname: str) -> bool:
    leaf = qualname.rsplit(".", 1)[-1]
    return leaf in ("__init__", "__new__")


def _immutable_after_init(graph: CallGraph,
                          all_accesses: Dict[Tuple[str, str],
                                             List[Access]]) -> Set[Loc]:
    """Locations whose every function-level write happens in an
    ``__init__``/``__new__`` (construction happens-before thread start
    and publication)."""
    writes: Dict[Loc, List[str]] = {}
    for fkey, accesses in all_accesses.items():
        for access in accesses:
            if access.write:
                writes.setdefault(access.loc, []).append(fkey[1])
    return {
        loc for loc, quals in writes.items()
        if all(_is_init_qualname(q) for q in quals)
    }


def _lock_attr(lock) -> str:
    return lock[2]


def run_threads(graph: CallGraph,
                declared_locks: Dict[Tuple[str, str], Set[str]],
                declared_attrs: Set[Loc]) -> ThreadReport:
    """The full pass: roots, per-root reachability, accesses, races.

    declared_locks / declared_attrs come from the ``_GUARDED_BY``
    declarations (rules.py parses them): declared attributes are the
    lock-discipline rule's territory and are skipped here.
    """
    lock_cfg = dataflow.LockConfig(
        declared=declared_locks, blocking_attrs=frozenset(),
        blocking_dotted=frozenset(), blocking_funcs=set())
    engine = dataflow._LockEngine(graph, lock_cfg)
    roots = discover_roots(graph)

    skip = _primitive_locs(graph) | set(declared_attrs)
    collector = _AccessCollector(graph, lock_cfg, skip)
    all_accesses: Dict[Tuple[str, str], List[Access]] = {
        info.key: collector.collect(info)
        for info in graph.iter_functions()
    }
    immutable = _immutable_after_init(graph, all_accesses)

    # loc -> [RaceAccess] across every root's cone.
    by_loc: Dict[Loc, List[RaceAccess]] = {}
    ctor_cache: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
    for root in roots:
        entry, paths = _reachable(graph, engine, ctor_cache, root.func)
        for fkey, entry_locks in entry.items():
            for access in all_accesses.get(fkey, ()):
                if access.loc in immutable:
                    continue
                rel, cls, _ = access.loc
                if cls and fkey[1] in (f"{cls}.__init__",
                                       f"{cls}.__new__"):
                    # Own-attr accesses inside the constructor:
                    # construction happens-before thread start and
                    # publication — the same exemption lock-discipline
                    # grants __init__.
                    continue
                owned = bool(cls) and (rel, f"{cls}.__init__") in entry
                by_loc.setdefault(access.loc, []).append(
                    RaceAccess(root=root, access=access,
                               entry_locks=entry_locks,
                               path=paths[fkey], owned=owned))

    races: List[RaceFinding] = []
    for loc, accesses in sorted(by_loc.items()):
        race = _judge_location(loc, accesses)
        if race is not None:
            races.append(race)
    races.sort(key=lambda r: (r.rel, r.line, r.loc))
    return ThreadReport(roots=roots, races=races)


def _judge_location(loc: Loc,
                    accesses: List[RaceAccess]) -> Optional[RaceFinding]:
    n_roots = len({a.root.func for a in accesses})
    has_write = any(a.access.write for a in accesses)
    if n_roots < 2 or not has_write:
        return None

    # The candidate guard: a lock some access already holds (most
    # common first) — the _GUARDED_BY declaration the fix-it names.
    lock_counts: Dict[Tuple, int] = {}
    for a in accesses:
        for lock in a.locks:
            lock_counts[lock] = lock_counts.get(lock, 0) + 1
    candidate = None
    if lock_counts:
        candidate = _lock_attr(sorted(lock_counts.items(),
                                      key=lambda kv: (-kv[1],
                                                      kv[0]))[0][0])

    # Worst unsynchronized cross-root pair: write-write beats
    # write-read; earliest lines win for stable reporting. A pair of
    # OWNED accesses is two roots touching their own instances — never
    # a race at our per-class identity.
    best: Optional[Tuple[int, RaceAccess, RaceAccess]] = None
    saw_shared_pair = False
    order = sorted(accesses,
                   key=lambda a: (not a.access.write, a.access.rel,
                                  a.access.line))
    for i, a in enumerate(order):
        for b in order[i + 1:]:
            if a.root.func == b.root.func:
                continue
            if not (a.access.write or b.access.write):
                continue
            if a.owned and b.owned:
                continue
            saw_shared_pair = True
            if a.locks & b.locks:
                continue
            rank = 0 if (a.access.write and b.access.write) else 1
            if best is None or rank < best[0]:
                best = (rank, a, b)
        if best is not None and best[0] == 0:
            break
    if best is not None:
        rank, a, b = best
        writer = a if a.access.write else b
        return RaceFinding(
            loc=loc, kind="write-write" if rank == 0 else "write-read",
            rel=writer.access.rel, line=writer.access.line, a=a, b=b,
            candidate_lock=candidate)
    if not saw_shared_pair:
        return None

    # Every shared cross-root pair holds a common lock, but the
    # attribute is not declared _GUARDED_BY: emit the fix-it so the
    # lock-discipline rule takes over enforcement (and future unlocked
    # accesses fail there).
    common = frozenset.intersection(*(a.locks for a in accesses
                                      if not a.owned))
    if common:
        writer = next(a for a in accesses if a.access.write)
        other = next((a for a in accesses
                      if a.root.func != writer.root.func), accesses[0])
        return RaceFinding(
            loc=loc, kind="guard-candidate", rel=writer.access.rel,
            line=writer.access.line, a=writer, b=other,
            candidate_lock=_lock_attr(sorted(common)[0]))
    return None
