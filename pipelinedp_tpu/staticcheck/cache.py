"""Content-hash incremental cache for parsed module models.

A full-tree pass costs one ``ast.parse`` + suppression tokenization per
file; as the tree and the rule count grow, re-parsing ~100 unchanged
files per gate run is the dominant fixed cost. The cache maps
``abspath -> (sha256(source), pickled Module)`` in one pickle file:

  * a hit (hash matches) returns the cached :class:`model.Module`
    object — byte-identical analysis inputs, so findings are identical
    to a cold run by construction (asserted in tests);
  * a miss re-parses and updates the entry;
  * ``trusted`` paths (the ``--changed-only`` flow: files git reports
    UNCHANGED) skip even the hash read — the entry is served as-is.

The file is versioned by :data:`CACHE_VERSION` + the analyzer's
RULES_VERSION; any mismatch or unpickling failure degrades to a cold
parse (the cache is an accelerator, never a correctness dependency).
"""

import hashlib
import os
import pickle
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pipelinedp_tpu.staticcheck import core, model

CACHE_VERSION = 1


class ModelCache:
    """Pickle-backed parsed-module cache (see module docstring)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._entries: Dict[str, Tuple[str, model.Module]] = {}
        self.hits = 0
        self.misses = 0
        self.trusted = 0
        if path is not None and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    payload = pickle.load(f)
                # Keyed on BOTH versions: a rules bump (new rule
                # families, changed suppression semantics) must never
                # serve analysis state written under the old rule set —
                # --changed-only trusts entries without re-hashing, so
                # a stale-versioned entry would go entirely unchecked.
                if payload.get("cache_version") == CACHE_VERSION and \
                        payload.get("rules_version") == \
                        core.RULES_VERSION:
                    self._entries = payload.get("entries", {})
            except Exception:  # noqa: BLE001 - a corrupt/stale cache file must degrade to a cold parse, never fail the analysis
                self._entries = {}

    @staticmethod
    def _digest(source: str) -> str:
        return hashlib.sha256(source.encode("utf-8")).hexdigest()

    def get(self, path: str, trust: bool = False) -> model.Module:
        """The parsed Module for ``path``; ``trust=True`` serves a cached
        entry without re-reading the file (the --changed-only contract:
        git vouched the file did not change)."""
        abspath = os.path.abspath(path)
        entry = self._entries.get(abspath)
        if trust and entry is not None:
            self.trusted += 1
            return entry[1]
        with open(path, encoding="utf-8") as f:
            source = f.read()
        digest = self._digest(source)
        if entry is not None and entry[0] == digest:
            self.hits += 1
            return entry[1]
        self.misses += 1
        mod = model.parse_source(model.canonical_rel(path), source)
        self._entries[abspath] = (digest, mod)
        return mod

    def save(self) -> None:
        if self.path is None:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump({"cache_version": CACHE_VERSION,
                         "rules_version": core.RULES_VERSION,
                         "entries": self._entries}, f)
        os.replace(tmp, self.path)


def load_tree_cached(paths: Iterable[str],
                     cache: Optional[ModelCache] = None,
                     trusted_paths: Optional[Set[str]] = None
                     ) -> List[model.Module]:
    """model.load_tree with an optional cache.

    ``trusted_paths``: abspaths that may be served from the cache
    without hashing (files git reports unchanged in --changed-only
    mode). Everything else is hash-checked, so the returned module set
    is byte-equivalent to a cold ``model.load_tree`` whenever the cache
    agrees with the filesystem.
    """
    if cache is None:
        return model.load_tree(paths)
    trusted_paths = trusted_paths or set()
    modules = []
    for path in model.iter_python_files(paths):
        modules.append(cache.get(
            path, trust=os.path.abspath(path) in trusted_paths))
    return modules


def git_unchanged_paths(paths: Iterable[str]) -> Optional[Set[str]]:
    """Abspaths under ``paths`` that git reports UNCHANGED vs HEAD
    (tracked, no diff, not untracked). None when git is unavailable or
    the tree is not a repository — callers then fall back to hashing
    everything, which is still correct, just colder.
    """
    import subprocess
    files = model.iter_python_files(paths)
    if not files:
        return set()
    root_dir = os.path.dirname(os.path.abspath(files[0]))
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=root_dir,
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        repo = top.stdout.strip()
        changed = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=all"],
            cwd=repo, capture_output=True, text=True, timeout=30)
        if changed.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    dirty = set()
    for line in changed.stdout.splitlines():
        if len(line) > 3:
            name = line[3:].strip().strip('"')
            if " -> " in name:  # renames list "old -> new"
                for part in name.split(" -> "):
                    dirty.add(os.path.join(repo, part))
                continue
            dirty.add(os.path.join(repo, name))
    out = set()
    for path in files:
        abspath = os.path.abspath(path)
        if abspath not in dirty:
            out.add(abspath)
    return out
