"""Interprocedural dataflow over the project call graph.

Two engines share the :class:`model.CallGraph`:

  * **Taint** (:func:`run_taint`) — forward value-taint from registered
    *sources* (functions whose return carries raw row-column data) to
    registered *sinks* (export surfaces: trace-span attrs, telemetry
    values, journal payloads, observability exports, driver release
    returns), with registered *sanitizers* (DP noise mechanisms /
    selection kernels) clearing taint. Per-function summaries (which
    params flow to the return, which params reach a sink, which source
    origins escape through the return) are computed to a fixpoint over
    the call graph, so a value that crosses five functions between the
    ingest column and the span attribute is still tracked — and the
    finding message carries the full source→sink call path.
  * **Locks** (:func:`run_locks`) — held-lock propagation: which locks a
    function may acquire (transitively), which blocking operations it
    may perform (transitively), and therefore which lock-order edges
    (L1 held while L2 is acquired) and blocking-while-locked flows the
    project contains. The lock-order rule turns the edge set into a
    deadlock proof (cycle detection) and flags blocking calls under a
    lock with the interprocedural path.

Unknown-callee policy (stated per engine, tested in
tests/test_callgraph.py):

  * taint treats an unresolved call CONSERVATIVELY as pass-through —
    ``f(tainted)`` returns tainted when ``f`` cannot be resolved, so a
    third-party hop never launders a value (declassifiers below are the
    deliberate exception);
  * lock facts are only claimed for resolved callees — an unresolved
    call cannot be proven to acquire or block, so it contributes nothing
    (EXCEPT the syntactic blocking patterns — ``.join()``/``.wait()``/
    ``time.sleep``/… — which are matched on the call expression itself).

Sizes declassify: ``len(x)``, ``.shape``/``.nbytes``/``.n_rows``/… of a
tainted value are cardinality metadata, not row values. Ingest-side
counts are visible to the operator who owns the input bytes anyway; the
invariant this engine guards is that raw VALUES (partition keys,
per-partition aggregates) never reach an export un-noised.
"""

import ast
import dataclasses
from typing import (Callable, Dict, FrozenSet, List, Optional,
                    Sequence, Set, Tuple)

from pipelinedp_tpu.staticcheck.model import (CallGraph, FunctionInfo,
                                              Module)

# Bound on recorded path length / origins per summary: deep pipelines
# stay readable and fixpoints stay small.
_MAX_PATH = 10
_MAX_ORIGINS = 8
_MAX_FIXPOINT_ROUNDS = 12


# ---------------------------------------------------------------------------
# Taint engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Origin:
    """Where a tainted value entered the flow, plus the call path it has
    taken since (outermost hop first)."""
    label: str
    rel: str
    line: int
    path: Tuple[str, ...] = ()

    def hop(self, step: str) -> "Origin":
        if len(self.path) >= _MAX_PATH:
            return self
        return dataclasses.replace(self, path=self.path + (step,))

    def render_path(self) -> str:
        start = f"{self.label} ({self.rel}:{self.line})"
        return " -> ".join((start,) + self.path)


@dataclasses.dataclass(frozen=True)
class ParamTok:
    """Symbolic taint of a function parameter (summary computation)."""
    name: str
    path: Tuple[str, ...] = ()

    def hop(self, step: str) -> "ParamTok":
        if len(self.path) >= _MAX_PATH:
            return self
        return dataclasses.replace(self, path=self.path + (step,))


@dataclasses.dataclass
class TaintConfig:
    """The rule-owned registries the engine runs against."""
    # (rel, qualname) -> source label. A call resolving here returns
    # tainted data.
    sources: Dict[Tuple[str, str], str]
    # Resolved project functions whose return is clean regardless of
    # inputs (DP kernels: noise + threshold before anything escapes).
    sanitizers: Set[Tuple[str, str]]
    # Attribute-call names that sanitize (mechanism methods).
    sanitizer_attrs: FrozenSet[str]
    # Unresolved dotted callees that sanitize.
    sanitizer_dotted: FrozenSet[str]
    # Builtin/unknown callees whose result is size metadata, not values.
    declass_calls: FrozenSet[str]
    # Attribute loads that yield size metadata.
    declass_attrs: FrozenSet[str]
    # (rel, qualname) of driver release functions: a tainted return or
    # yield inside them (or a function nested in them) is a sink.
    release_funcs: Set[Tuple[str, str]]
    # sink detector: (graph, mod, scope, call) -> list of
    # (sink_label, [tainted arg expressions]) — see rules.py.
    sink_args: Callable
    # Unresolved callees whose RESULT is a source, matched by exact
    # canonical dotted name ({"set": ..., "os.listdir": ...}) — the
    # determinism rule's iteration-order sources. Exact-match only:
    # `ev.set()` canonicalizes to "ev.set", never bare "set", so a
    # threading.Event publish can't masquerade as a set constructor.
    source_calls: Dict[str, str] = dataclasses.field(default_factory=dict)
    # When set, `{a, b}` literals and set comprehensions are sources
    # carrying this label (None keeps value-taint configs unchanged).
    literal_set_label: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class TaintFinding:
    rel: str
    line: int
    sink: str
    origin: Origin


class _Summary:
    __slots__ = ("ret_params", "ret_origins", "param_sinks")

    def __init__(self):
        self.ret_params: Set[str] = set()
        self.ret_origins: Dict[Tuple[str, str, int], Origin] = {}
        # (param, sink_label, rel, line, path) — a tainted argument for
        # `param` reaches `sink` inside this function (transitively).
        self.param_sinks: Set[Tuple[str, str, str, int, Tuple[str, ...]]]\
            = set()

    def digest(self) -> Tuple:
        # Paths are presentation metadata and may differ between rounds;
        # the fixpoint compares the path-free facts only.
        return (frozenset(self.ret_params),
                frozenset(self.ret_origins.keys()),
                frozenset((p, s, r, ln) for p, s, r, ln, _
                          in self.param_sinks))


def _is_comprehension(node: ast.AST) -> bool:
    return isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp))


class _FunctionPass:
    """One intraprocedural walk of a function given callee summaries."""

    def __init__(self, engine: "_TaintEngine", info: FunctionInfo):
        self.engine = engine
        self.cfg = engine.cfg
        self.graph = engine.graph
        self.info = info
        self.mod = engine.graph.modules[info.rel]
        self.env: Dict[str, Set] = {}
        self.summary = _Summary()
        self.findings: List[TaintFinding] = []
        self.in_release = (
            info.key in self.cfg.release_funcs or any(
                (info.rel, q) in self.cfg.release_funcs
                for q in info.enclosing))

    # -- expression taint ------------------------------------------------

    def taint_of(self, node: Optional[ast.AST]) -> Set:
        if node is None or isinstance(node, ast.Constant):
            return set()
        if self.cfg.literal_set_label is not None and \
                isinstance(node, (ast.Set, ast.SetComp)):
            out = {Origin(label=self.cfg.literal_set_label,
                          rel=self.info.rel, line=node.lineno)}
            for child in ast.walk(node):
                if child is node:
                    continue
                if isinstance(child, ast.Call):
                    out |= self.taint_of_call(child)
                elif isinstance(child, ast.Name) and \
                        isinstance(child.ctx, ast.Load):
                    out |= set(self.env.get(child.id, ()))
            return out
        if isinstance(node, ast.Name):
            return set(self.env.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            if node.attr in self.cfg.declass_attrs:
                return set()
            return self.taint_of(node.value)
        if isinstance(node, ast.Call):
            return self.taint_of_call(node)
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return set()
        if _is_comprehension(node):
            out: Set = set()
            for child in ast.walk(node):
                if isinstance(child, ast.Call):
                    out |= self.taint_of_call(child)
                elif isinstance(child, ast.Name) and \
                        isinstance(child.ctx, ast.Load):
                    out |= set(self.env.get(child.id, ()))
            return out
        out = set()
        for child in ast.iter_child_nodes(node):
            out |= self.taint_of(child)
        return out

    def _arg_taints(self, call: ast.Call) -> List[Tuple[Optional[str],
                                                        Set]]:
        """[(param-name-or-None, taint)] for every argument."""
        out = []
        for arg in call.args:
            node = arg.value if isinstance(arg, ast.Starred) else arg
            out.append((None, self.taint_of(node)))
        for kw in call.keywords:
            out.append((kw.arg, self.taint_of(kw.value)))
        return out

    def taint_of_call(self, call: ast.Call) -> Set:
        cfg = self.cfg
        dotted = self.mod.dotted(call.func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        callee = self.graph.resolve_call(self.mod, call, self.info)

        # Sink check first: the call's own arguments.
        self._check_sinks(call, callee)

        if callee is not None:
            if callee.key in cfg.sanitizers:
                return set()
            label = cfg.sources.get(callee.key)
            if label is not None:
                return {Origin(label=label, rel=self.info.rel,
                               line=call.lineno)}
            return self._through_summary(call, callee)
        # Unresolved callees.
        if dotted in cfg.sanitizer_dotted or \
                (isinstance(call.func, ast.Attribute) and
                 call.func.attr in cfg.sanitizer_attrs):
            return set()
        if dotted in cfg.declass_calls or leaf in cfg.declass_calls:
            return set()
        src_label = cfg.source_calls.get(dotted)
        if src_label is not None:
            return {Origin(label=src_label, rel=self.info.rel,
                           line=call.lineno)}
        # Conservative pass-through: taint in, taint out.
        out: Set = set()
        for _, taint in self._arg_taints(call):
            out |= taint
        out |= self.taint_of(call.func) if isinstance(
            call.func, ast.Attribute) else set()
        return out

    def _through_summary(self, call: ast.Call,
                         callee: FunctionInfo) -> Set:
        """Substitute the callee's summary at this call site."""
        summary = self.engine.summaries.get(callee.key)
        if summary is None:
            return set()
        hop = (f"{callee.qualname} "
               f"({self.info.rel}:{call.lineno})")
        out: Set = set()
        params = callee.params
        # Map arguments onto parameter names (best effort).
        arg_map: Dict[str, Set] = {}
        pos = 0
        for arg in call.args:
            if isinstance(arg, ast.Starred):
                break
            if pos < len(params):
                arg_map[params[pos]] = self.taint_of(arg)
            pos += 1
        for kw in call.keywords:
            if kw.arg is not None:
                arg_map[kw.arg] = self.taint_of(kw.value)
        # Return taint from params.
        for pname, taint in arg_map.items():
            if pname in summary.ret_params:
                out |= {t.hop(hop) for t in taint}
            # Param-to-sink flows become findings (origins) or summary
            # entries (params of THIS function).
            for p, sink, rel, line, path in summary.param_sinks:
                if p != pname:
                    continue
                for t in taint:
                    inner = (hop,) + path
                    if isinstance(t, Origin):
                        self._emit(rel, line, sink,
                                   dataclasses.replace(
                                       t, path=(t.path + inner)[:_MAX_PATH]))
                    elif isinstance(t, ParamTok):
                        self.summary.param_sinks.add(
                            (t.name, sink, rel, line,
                             (t.path + inner)[:_MAX_PATH]))
        # Origins generated inside the callee that escape its return.
        for origin in summary.ret_origins.values():
            out.add(origin.hop(hop))
        return out

    # -- sinks -----------------------------------------------------------

    def _check_sinks(self, call: ast.Call,
                     callee: Optional[FunctionInfo]) -> None:
        hits = self.cfg.sink_args(self.graph, self.mod, self.info, call,
                                  callee)
        for sink_label, exprs in hits:
            for expr in exprs:
                for t in self.taint_of(expr):
                    self._record_sink_taint(sink_label, call.lineno, t)

    def _record_sink_taint(self, sink: str, line: int, t) -> None:
        if isinstance(t, Origin):
            self._emit(self.info.rel, line, sink, t)
        elif isinstance(t, ParamTok):
            self.summary.param_sinks.add(
                (t.name, sink, self.info.rel, line, t.path))

    def _emit(self, rel: str, line: int, sink: str,
              origin: Origin) -> None:
        self.findings.append(TaintFinding(rel=rel, line=line, sink=sink,
                                          origin=origin))

    # -- statements ------------------------------------------------------

    def _assign(self, target: ast.AST, taint: Set) -> None:
        if isinstance(target, ast.Name):
            if taint:
                self.env[target.id] = set(taint)
            else:
                self.env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(elt, taint)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, taint)
        # Attribute/subscript stores: the container keeps its taint.

    def _note_return(self, value: Optional[ast.AST], line: int) -> None:
        taint = self.taint_of(value)
        # `return generator()` forwarding a nested generator is not
        # itself a release: the generator's own yields are checked at
        # their lines (one finding per actual emit point, not two).
        forwards_nested = False
        if isinstance(value, ast.Call):
            callee = self.graph.resolve_call(self.mod, value, self.info)
            forwards_nested = (callee is not None and
                               callee.rel == self.info.rel and
                               bool(callee.enclosing))
        for t in taint:
            if isinstance(t, ParamTok):
                self.summary.ret_params.add(t.name)
            elif isinstance(t, Origin):
                if len(self.summary.ret_origins) < _MAX_ORIGINS:
                    self.summary.ret_origins.setdefault(
                        (t.label, t.rel, t.line), t)
            if self.in_release and not forwards_nested:
                self._record_sink_taint("driver release value", line, t)

    def _walk_expr_stmts(self, node: ast.AST) -> None:
        """Visit calls for sink/side effects in a bare expression."""
        self.taint_of(node)

    def walk(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # own pass via the function index
            elif isinstance(stmt, ast.Assign):
                taint = self.taint_of(stmt.value)
                for t in stmt.targets:
                    self._assign(t, taint)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    taint = self.taint_of(stmt.value)
                    if isinstance(stmt, ast.AugAssign):
                        taint |= self.taint_of(stmt.target)
                    self._assign(stmt.target, taint)
            elif isinstance(stmt, (ast.Return,)):
                self._note_return(stmt.value, stmt.lineno)
            elif isinstance(stmt, ast.Expr):
                if isinstance(stmt.value, (ast.Yield, ast.YieldFrom)):
                    self._note_return(stmt.value.value, stmt.lineno)
                else:
                    self._walk_expr_stmts(stmt.value)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._assign(stmt.target, self.taint_of(stmt.iter))
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._walk_expr_stmts(stmt.test)
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.If):
                self._walk_expr_stmts(stmt.test)
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    taint = self.taint_of(item.context_expr)
                    if item.optional_vars is not None:
                        self._assign(item.optional_vars, taint)
                self.walk(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body)
                for handler in stmt.handlers:
                    self.walk(handler.body)
                self.walk(stmt.orelse)
                self.walk(stmt.finalbody)
            elif isinstance(stmt, (ast.Raise, ast.Assert, ast.Delete)):
                for child in ast.iter_child_nodes(stmt):
                    self._walk_expr_stmts(child)
            # pass/break/continue/import/global/nonlocal: nothing flows.

    def run(self) -> None:
        # Closure seeding: a nested def reads the enclosing scopes'
        # variables — seed them from the enclosing functions' settled
        # environments (outer-to-inner, so inner shadowing wins; the
        # engine's fixpoint rounds make the outer env available). Own
        # params override last.
        for outer_qual in self.info.enclosing:
            outer_env = self.engine.final_envs.get(
                (self.info.rel, outer_qual))
            if outer_env:
                for name, taint in outer_env.items():
                    self.env[name] = set(taint)
        # Params carry symbolic taint; yields inside expressions (rare)
        # are covered by the statement walk's Expr/Return handling.
        for p in self.info.params:
            self.env[p] = {ParamTok(name=p)}
        # Two passes propagate loop-carried taint (monotone: the second
        # pass starts from the first pass's environment), findings taken
        # from the settled pass only.
        body = self.info.node.body
        self.walk(body)
        self.findings.clear()
        self.walk(body)
        self.engine.final_envs[self.info.key] = self.env


class _TaintEngine:
    def __init__(self, graph: CallGraph, cfg: TaintConfig):
        self.graph = graph
        self.cfg = cfg
        self.summaries: Dict[Tuple[str, str], _Summary] = {}
        # Settled per-function environments, read by nested defs for
        # closure-variable seeding.
        self.final_envs: Dict[Tuple[str, str], Dict[str, Set]] = {}

    def run(self) -> List[TaintFinding]:
        funcs = list(self.graph.iter_functions())
        for _ in range(_MAX_FIXPOINT_ROUNDS):
            changed = False
            for info in funcs:
                fp = _FunctionPass(self, info)
                fp.run()
                prev = self.summaries.get(info.key)
                if prev is None or prev.digest() != fp.summary.digest():
                    changed = True
                self.summaries[info.key] = fp.summary
            if not changed:
                break
        findings: Dict[Tuple[str, int, str, str], TaintFinding] = {}
        for info in funcs:
            fp = _FunctionPass(self, info)
            fp.run()
            for f in fp.findings:
                findings.setdefault(
                    (f.rel, f.line, f.sink, f.origin.label), f)
        return list(findings.values())


def run_taint(graph: CallGraph, cfg: TaintConfig) -> List[TaintFinding]:
    return _TaintEngine(graph, cfg).run()


# ---------------------------------------------------------------------------
# Lock engine
# ---------------------------------------------------------------------------

# Lock identity: (rel, owner-class-or-"", attribute name). Per-class
# identity is the standard approximation — two instances of one class
# share a lock *order* even though they hold distinct lock objects.
LockId = Tuple[str, str, str]


@dataclasses.dataclass(frozen=True)
class BlockingSite:
    desc: str            # e.g. "Thread.start()", "time.sleep"
    rel: str
    line: int
    path: Tuple[str, ...] = ()

    def hop(self, step: str) -> "BlockingSite":
        if len(self.path) >= _MAX_PATH:
            return self
        return dataclasses.replace(self, path=self.path + (step,))


@dataclasses.dataclass(frozen=True)
class AcquireSite:
    lock: LockId
    rel: str
    line: int
    path: Tuple[str, ...] = ()

    def hop(self, step: str) -> "AcquireSite":
        if len(self.path) >= _MAX_PATH:
            return self
        return dataclasses.replace(self, path=self.path + (step,))


@dataclasses.dataclass
class LockConfig:
    # Declared locks per (rel, cls-or-""): lock attribute names from
    # guarded_by declarations. Names containing "lock" are recognized
    # undeclared (conservative: ordering applies to every mutex-looking
    # `with`).
    declared: Dict[Tuple[str, str], Set[str]]
    # Attribute names whose call blocks (receiver must not be a string
    # constant — keeps ",".join() out).
    blocking_attrs: FrozenSet[str]
    # Dotted callee names that block.
    blocking_dotted: FrozenSet[str]
    # Resolved project callees that block (e.g. mesh.host_fetch).
    blocking_funcs: Set[Tuple[str, str]]
    # Dotted prefixes whose attribute calls are never blocking even when
    # the attr name matches (os.path.join is not Thread.join).
    nonblocking_prefixes: Tuple[str, ...] = ("os.path.",)


@dataclasses.dataclass
class LockReport:
    # (held, acquired) -> first witness (rel, line, path-desc)
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]]
    # blocking-while-locked findings: (rel, line, held lock, blocking
    # site with path)
    blocking: List[Tuple[str, int, LockId, BlockingSite]]


def _lock_of_with_item(mod: Module, cfg: LockConfig, item: ast.withitem,
                       info: FunctionInfo) -> Optional[LockId]:
    dotted = mod.dotted(item.context_expr)
    if dotted is None:
        return None
    parts = dotted.split(".")
    if parts[0] == "self" and len(parts) == 2:
        name = parts[1]
        owner_cls = info.cls
        if owner_cls is None:
            return None
        declared = cfg.declared.get((info.rel, owner_cls), set())
        if name in declared or "lock" in name.lower():
            return (info.rel, owner_cls, name)
        return None
    if len(parts) == 1:
        name = parts[0]
        declared = cfg.declared.get((info.rel, ""), set())
        if name in declared or "lock" in name.lower():
            return (info.rel, "", name)
    return None


def _direct_blocking(mod: Module, cfg: LockConfig, graph: CallGraph,
                     info: FunctionInfo,
                     call: ast.Call) -> Optional[str]:
    """Blocking description when the call itself matches a syntactic
    blocking pattern, else None."""
    dotted = mod.dotted(call.func)
    if dotted in cfg.blocking_dotted:
        return dotted
    if dotted is not None and dotted.startswith(
            cfg.nonblocking_prefixes):
        return None
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in cfg.blocking_attrs and \
            not isinstance(call.func.value, ast.Constant):
        return f".{call.func.attr}()"
    return None


class _LockEngine:
    def __init__(self, graph: CallGraph, cfg: LockConfig):
        self.graph = graph
        self.cfg = cfg
        # function key -> facts
        self.may_acquire: Dict[Tuple[str, str], Dict[LockId,
                                                     AcquireSite]] = {}
        # Facts are keyed by the ROOT blocking site (desc, rel, line) —
        # a stable identity, so propagation converges in call-depth
        # rounds even across call cycles; the human-readable via-chain
        # lives in BlockingSite.path (length-capped).
        self.may_block: Dict[Tuple[str, str],
                             Dict[Tuple[str, str, int],
                                  BlockingSite]] = {}
        # Per-function structural events, computed once: the AST walk
        # (with held-lock scoping) is identical every fixpoint round;
        # only the propagated facts change.
        self._events: Dict[Tuple[str, str], List[Tuple]] = {}

    def _function_events(self, info: FunctionInfo) -> List[Tuple]:
        """[("call", call_node, held) | ("acquire", lock, line, held)]
        in syntactic order, held as a tuple of LockIds."""
        cached = self._events.get(info.key)
        if cached is not None:
            return cached
        events: List[Tuple] = []
        self._walk(info,
                   lambda call, held: events.append(("call", call, held)),
                   lambda lock, line, held: events.append(
                       ("acquire", lock, line, held)))
        self._events[info.key] = events
        return events

    # -- per-function structural walk ------------------------------------

    def _walk(self, info: FunctionInfo,
              on_call, on_acquire) -> None:
        """Walks the body tracking the held-lock set; invokes
        ``on_call(call, held)`` for every call and ``on_acquire(lock,
        line, held)`` for every lock acquisition."""
        mod = self.graph.modules[info.rel]

        def visit(node: ast.AST, held: Tuple[LockId, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # nested defs run later, outside these locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    visit(item.context_expr, held)
                    lock = _lock_of_with_item(mod, self.cfg, item, info)
                    if lock is not None:
                        on_acquire(lock, node.lineno, held)
                        acquired.append(lock)
                inner = held + tuple(acquired)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call):
                on_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in info.node.body:
            visit(stmt, ())

    # -- fixpoint facts --------------------------------------------------

    def _compute_facts(self) -> None:
        funcs = list(self.graph.iter_functions())
        for info in funcs:
            self.may_acquire[info.key] = {}
            self.may_block[info.key] = {}
        for _ in range(_MAX_FIXPOINT_ROUNDS):
            changed = False
            for info in funcs:
                acq = dict(self.may_acquire[info.key])
                blk = dict(self.may_block[info.key])
                mod = self.graph.modules[info.rel]

                def on_call(call, held, info=info, mod=mod, acq=acq,
                            blk=blk):
                    direct = _direct_blocking(mod, self.cfg, self.graph,
                                              info, call)
                    if direct is not None:
                        key = (direct, info.rel, call.lineno)
                        if key not in blk:
                            blk[key] = BlockingSite(desc=direct,
                                                    rel=info.rel,
                                                    line=call.lineno)
                    callee = self.graph.resolve_call(mod, call, info)
                    if callee is None:
                        return
                    if callee.key in self.cfg.blocking_funcs:
                        key = (callee.qualname, info.rel, call.lineno)
                        if key not in blk:
                            blk[key] = BlockingSite(desc=callee.qualname,
                                                    rel=info.rel,
                                                    line=call.lineno)
                    hop = (f"{callee.qualname} "
                           f"({info.rel}:{call.lineno})")
                    for lock, site in self.may_acquire.get(
                            callee.key, {}).items():
                        if lock not in acq:
                            acq[lock] = AcquireSite(
                                lock=lock, rel=info.rel,
                                line=call.lineno).hop(site.rel + ":" +
                                                      str(site.line))
                    for key, site in self.may_block.get(
                            callee.key, {}).items():
                        if key not in blk:
                            blk[key] = site.hop(hop)

                def on_acquire(lock, line, held, info=info, acq=acq):
                    if lock not in acq:
                        acq[lock] = AcquireSite(lock=lock, rel=info.rel,
                                                line=line)

                for event in self._function_events(info):
                    if event[0] == "call":
                        on_call(event[1], event[2])
                    else:
                        on_acquire(event[1], event[2], event[3])
                if acq.keys() != self.may_acquire[info.key].keys() or \
                        blk.keys() != self.may_block[info.key].keys():
                    changed = True
                self.may_acquire[info.key] = acq
                self.may_block[info.key] = blk
            if not changed:
                break

    # -- report ----------------------------------------------------------

    def run(self) -> LockReport:
        self._compute_facts()
        edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]] = {}
        blocking: List[Tuple[str, int, LockId, BlockingSite]] = []
        seen_block: Set[Tuple[str, int, str]] = set()
        for info in self.graph.iter_functions():
            mod = self.graph.modules[info.rel]

            def on_call(call, held, info=info, mod=mod):
                if not held:
                    return
                direct = _direct_blocking(mod, self.cfg, self.graph,
                                          info, call)
                callee = self.graph.resolve_call(mod, call, info)
                if direct is not None:
                    key = (info.rel, call.lineno, direct)
                    if key not in seen_block:
                        seen_block.add(key)
                        blocking.append(
                            (info.rel, call.lineno, held[-1],
                             BlockingSite(desc=direct, rel=info.rel,
                                          line=call.lineno)))
                if callee is None:
                    return
                if callee.key in self.cfg.blocking_funcs:
                    key = (info.rel, call.lineno, callee.qualname)
                    if key not in seen_block:
                        seen_block.add(key)
                        blocking.append(
                            (info.rel, call.lineno, held[-1],
                             BlockingSite(desc=callee.qualname,
                                          rel=info.rel,
                                          line=call.lineno)))
                hop = f"{callee.qualname} ({info.rel}:{call.lineno})"
                for _key, site in self.may_block.get(callee.key,
                                                     {}).items():
                    key = (info.rel, call.lineno, site.desc)
                    if key not in seen_block:
                        seen_block.add(key)
                        blocking.append((info.rel, call.lineno, held[-1],
                                         site.hop(hop)))
                for lock in self.may_acquire.get(callee.key, {}):
                    for h in held:
                        edges.setdefault(
                            (h, lock),
                            (info.rel, call.lineno,
                             f"via {callee.qualname}"))

            def on_acquire(lock, line, held, info=info):
                for h in held:
                    edges.setdefault((h, lock), (info.rel, line, "direct"))

            for event in self._function_events(info):
                if event[0] == "call":
                    on_call(event[1], event[2])
                else:
                    on_acquire(event[1], event[2], event[3])
        return LockReport(edges=edges, blocking=blocking)


def run_locks(graph: CallGraph, cfg: LockConfig) -> LockReport:
    return _LockEngine(graph, cfg).run()


def find_lock_cycles(
        edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]]
) -> List[List[LockId]]:
    """Elementary cycles in the lock-order graph (incl. self-loops),
    deduplicated by rotation."""
    adj: Dict[LockId, Set[LockId]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    cycles: Dict[Tuple[LockId, ...], List[LockId]] = {}

    def dfs(start: LockId, node: LockId, path: List[LockId],
            on_path: Set[LockId]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cyc = list(path)
                pivot = min(range(len(cyc)), key=lambda i: cyc[i])
                canon = tuple(cyc[pivot:] + cyc[:pivot])
                cycles.setdefault(canon, cyc)
            elif nxt not in on_path and nxt > start:
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.remove(nxt)
                path.pop()

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return list(cycles.values())
