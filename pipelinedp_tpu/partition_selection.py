"""Native differentially-private partition selection strategies.

The reference delegates to Google's C++ library via PyDP
(/root/reference/pipeline_dp/partition_selection.py:29-44 and
dp_engine.py:345-348). This module implements the three strategies natively:

  * TRUNCATED_GEOMETRIC — the optimal "magic" partition selection of
    Desfontaines, Voss, Gipson & Mandayam (2020), closed-form evaluation of
    the recurrence
        pi_0 = 0,
        pi_n = min(e^eps' pi_{n-1} + delta',
                   1 - e^{-eps'}(1 - pi_{n-1} - delta'), 1)
    with eps' = eps / l0, delta' = delta / l0 (budget split across the l0
    partitions one user may touch). The recurrence is geometric in both
    phases, so pi_n is evaluated in O(1) for any n.
  * LAPLACE_THRESHOLDING — count + Laplace(l0/eps) compared against a
    threshold calibrated so the total delta is respected.
  * GAUSSIAN_THRESHOLDING — count + N(0, sigma^2) with analytic sigma at
    (eps, delta/2) and threshold calibrated with the remaining delta/2.

Every strategy exposes both `should_keep(n)` (sampled decision) and
`probability_of_keep(n)` (exact closed form — required by utility analysis),
plus vectorized numpy versions used to build the device kernels
(ops/selection_ops.py evaluates the same closed forms in jnp).
"""

import abc
import functools
import math
from dataclasses import dataclass
from typing import Optional

import numpy as np
from scipy import special

from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy
from pipelinedp_tpu import dp_computations

# Lazily created with explicit entropy (staticcheck host-rng: no
# module-global RNG instances — the seed must be observable/injectable).
_rng: Optional[np.random.Generator] = None


def seed_selection_rng(seed) -> None:
    """Seeds (or injects a np.random.Generator as) the selection RNG."""
    global _rng
    _rng = (seed if isinstance(seed, np.random.Generator) else
            np.random.default_rng(seed))


def selection_rng() -> np.random.Generator:
    global _rng
    if _rng is None:
        _rng = np.random.default_rng(np.random.SeedSequence())
    return _rng


class PartitionSelector(abc.ABC):
    """DP partition-selection strategy built from privacy-id counts."""

    def __init__(self, epsilon: float, delta: float,
                 max_partitions_contributed: int,
                 pre_threshold: Optional[int]):
        if epsilon <= 0:
            raise ValueError(f"epsilon must be positive, got {epsilon}")
        if delta <= 0 or delta >= 1:
            raise ValueError(
                f"Partition selection requires delta in (0, 1), got {delta}")
        if max_partitions_contributed <= 0:
            raise ValueError("max_partitions_contributed must be positive")
        if pre_threshold is not None and pre_threshold <= 0:
            raise ValueError("pre_threshold must be positive")
        self._epsilon = epsilon
        self._delta = delta
        self._l0 = max_partitions_contributed
        self._pre_threshold = pre_threshold

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def delta(self) -> float:
        return self._delta

    @property
    def max_partitions_contributed(self) -> int:
        return self._l0

    @property
    def pre_threshold(self) -> Optional[int]:
        return self._pre_threshold

    def _apply_pre_threshold(self, n):
        """Shifts counts by the pre-threshold: counts below it never keep;
        the DP decision sees n - (pre_threshold - 1)."""
        if self._pre_threshold is None:
            return n
        return n - (self._pre_threshold - 1)

    def probability_of_keep(self, num_privacy_ids: int) -> float:
        """Exact keep probability for a partition with the given number of
        contributing privacy units."""
        n = self._apply_pre_threshold(num_privacy_ids)
        if n <= 0:
            return 0.0
        return float(self._probability_of_keep_shifted(np.asarray([n]))[0])

    def probability_of_keep_vec(self, counts: np.ndarray) -> np.ndarray:
        """Vectorized probability_of_keep over an int array."""
        n = self._apply_pre_threshold(np.asarray(counts, dtype=np.int64))
        probs = self._probability_of_keep_shifted(np.maximum(n, 1))
        return np.where(n <= 0, 0.0, probs)

    def should_keep(self, num_privacy_ids: int) -> bool:
        """Samples the DP keep decision."""
        return bool(selection_rng().uniform() <
                    self.probability_of_keep(num_privacy_ids))

    @abc.abstractmethod
    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        """probability of keep on pre-threshold-shifted counts n >= 1."""


class TruncatedGeometricPartitionSelector(PartitionSelector):
    """Optimal partition selection (truncated geometric), closed form.

    Phase 1 (n <= n_cross):  pi_n = delta' (e^{n eps'} - 1)/(e^{eps'} - 1)
    Phase 2 (n > n_cross):   1 - pi_n decays geometrically with rate e^{-eps'}
    The crossover is the largest n with pi_{n-1} <= (1 - delta')/(1 + e^{eps'}).
    """

    def __init__(self, epsilon, delta, max_partitions_contributed,
                 pre_threshold=None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        self._eps1 = self._epsilon / self._l0
        self._delta1 = self._delta / self._l0
        d1 = self._delta1
        # Largest n such that phase-1 still applies to step n (i.e.
        # pi_{n-1} <= (1 - d1)/(1 + e^eps1)). The ratio is computed via
        # tanh(eps1/2) = (e-1)/(e+1), which never overflows for huge eps.
        t = math.tanh(self._eps1 / 2)
        self._n_cross = 1 + int(
            math.floor(math.log1p(t * (1.0 - d1) / d1) / self._eps1))
        self._pi_cross = float(self._phase1(self._n_cross))

    def _phase1(self, n):
        # pi_n = d1 * (e^{n eps1} - 1) / (e^{eps1} - 1) evaluated in log
        # space (overflow-safe for huge eps):
        # log pi_n = log d1 + (n-1) eps1 + log1p(-e^{-n eps1})
        #            - log1p(-e^{-eps1}).
        n = np.asarray(n, dtype=np.float64)
        log_pi = (math.log(self._delta1) + (n - 1.0) * self._eps1 +
                  np.log1p(-np.exp(-n * self._eps1)) -
                  math.log1p(-math.exp(-self._eps1)))
        return np.exp(np.minimum(log_pi, 0.0))

    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        n = np.asarray(n, dtype=np.float64)
        pi1 = np.minimum(self._phase1(np.minimum(n, self._n_cross)), 1.0)
        # Phase 2: q_{n_cross + k} = e^{-k eps1} q_cross
        #          - d1 e^{-eps1}(1 - e^{-k eps1})/(1 - e^{-eps1})
        k = np.maximum(n - self._n_cross, 0.0)
        q_cross = 1.0 - self._pi_cross
        decay = np.exp(-k * self._eps1)
        geo = (math.exp(-self._eps1) * (1.0 - decay) /
               (1.0 - math.exp(-self._eps1)))
        q = decay * q_cross - self._delta1 * geo
        pi2 = 1.0 - np.maximum(q, 0.0)
        return np.clip(np.where(n <= self._n_cross, pi1, pi2), 0.0, 1.0)


class LaplaceThresholdingPartitionSelector(PartitionSelector):
    """Laplace noisy-threshold partition selection.

    Noise scale b = l0 / eps (count of one user changes by 1 in each of at
    most l0 partitions). Per-partition delta is 1 - (1 - delta)^(1/l0); the
    threshold t solves P(1 + Lap(b) >= t) = delta_p, giving
    t = 1 - b ln(2 delta_p) for delta_p <= 1/2.
    """

    def __init__(self, epsilon, delta, max_partitions_contributed,
                 pre_threshold=None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        self._b = self._l0 / self._epsilon
        delta_p = -math.expm1(math.log1p(-self._delta) / self._l0)
        if delta_p <= 0.5:
            self._threshold = 1.0 - self._b * math.log(2 * delta_p)
        else:
            self._threshold = 1.0 + self._b * math.log(2 - 2 * delta_p)

    @property
    def threshold(self) -> float:
        return self._threshold

    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        # P(n + Lap(b) >= t) — Laplace survival function. np.where
        # evaluates BOTH branches, so each exp sees only the half-line it
        # is selected on (clipped z): exp of a large positive z in the
        # dead branch would overflow-warn even though its value is never
        # used.
        z = (np.asarray(n, dtype=np.float64) - self._threshold) / self._b
        return np.where(z >= 0, 1.0 - 0.5 * np.exp(-np.maximum(z, 0.0)),
                        0.5 * np.exp(np.minimum(z, 0.0)))


class GaussianThresholdingPartitionSelector(PartitionSelector):
    """Gaussian noisy-threshold partition selection.

    Budget split: delta/2 to calibrate sigma at (eps, delta/2) with l2
    sensitivity sqrt(l0); delta/2 (adjusted per partition) to set the
    threshold t = 1 + sigma * Phi^{-1}(1 - delta_p).
    """

    def __init__(self, epsilon, delta, max_partitions_contributed,
                 pre_threshold=None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        noise_delta = self._delta / 2
        threshold_delta = self._delta - noise_delta
        self._sigma = dp_computations.gaussian_sigma(self._epsilon,
                                                     noise_delta,
                                                     math.sqrt(self._l0))
        delta_p = -math.expm1(math.log1p(-threshold_delta) / self._l0)
        # Phi^{-1}(1 - delta_p) via erfcinv: Phi^{-1}(p)=-sqrt(2)erfcinv(2p).
        quantile = -math.sqrt(2) * special.erfcinv(2 * (1 - delta_p))
        self._threshold = 1.0 + self._sigma * quantile

    @property
    def sigma(self) -> float:
        return self._sigma

    @property
    def threshold(self) -> float:
        return self._threshold

    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        z = (self._threshold - np.asarray(n, dtype=np.float64)) / self._sigma
        return 0.5 * special.erfc(z / math.sqrt(2))


_STRATEGY_TO_CLASS = {
    PartitionSelectionStrategy.TRUNCATED_GEOMETRIC:
        TruncatedGeometricPartitionSelector,
    PartitionSelectionStrategy.LAPLACE_THRESHOLDING:
        LaplaceThresholdingPartitionSelector,
    PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING:
        GaussianThresholdingPartitionSelector,
}


@functools.lru_cache(maxsize=256)
def create_partition_selection_strategy(
        strategy: PartitionSelectionStrategy,
        epsilon: float,
        delta: float,
        max_partitions_contributed: int,
        pre_threshold: Optional[int] = None) -> PartitionSelector:
    """Creates a native partition-selection strategy object
    (reference-parity factory: pipeline_dp/partition_selection.py:29-44).

    Cached: selectors are deterministic in their parameters, and the engine's
    per-partition filter would otherwise re-run the (bisection-heavy)
    calibration once per partition.
    """
    cls = _STRATEGY_TO_CLASS.get(strategy)
    if cls is None:
        raise ValueError(f"Unknown partition selection strategy {strategy}")
    return cls(epsilon, delta, max_partitions_contributed, pre_threshold)
