"""Combiners: per-partition aggregation kernels for DP metrics.

Reference parity: pipeline_dp/combiners.py:32-871. Combiners follow the
Beam-CombineFn-style triad — create_accumulator / merge_accumulators /
compute_metrics — with merge associative, so the same logic runs:

  * element-wise on the generic backends (Local/Beam/Spark), and
  * as dense array columns on the TPU path: executor.build_plan lowers each
    scalar-accumulator combiner to a static MetricPlanEntry evaluated as
    (n_partitions,) dense columns with segment-sums and vectorized noise.

Mechanisms are built lazily from MechanismSpec (dropped from serialized
state), so budget finalization can happen after graph construction.
"""

import abc
import collections
import copy
import threading
from typing import Callable, Iterable, List, Optional, Sized, Tuple, Union

import numpy as np

from pipelinedp_tpu import aggregate_params
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu.aggregate_params import Metrics, NoiseKind
from pipelinedp_tpu.ops import quantile_tree as quantile_tree_ops
from pipelinedp_tpu.runtime.concurrency import guarded_by

ArrayLike = Union[np.ndarray, List[float]]
ExplainComputationReport = Union[Callable, str, List[Union[Callable, str]]]


class Combiner(abc.ABC):
    """Base class for all combiners.

    Combiners hold logic; accumulators hold data. The framework:
      1. calls create_accumulator() per (privacy_id, partition) group,
      2. merges accumulators pairwise per partition (associative),
      3. calls compute_metrics() once per surviving partition.
    """

    @abc.abstractmethod
    def create_accumulator(self, values):
        """Creates an accumulator from `values`."""

    @abc.abstractmethod
    def merge_accumulators(self, accumulator1, accumulator2):
        """Merges two accumulators (associative)."""

    @abc.abstractmethod
    def compute_metrics(self, accumulator):
        """Computes the DP result from the final accumulator."""

    @abc.abstractmethod
    def metrics_names(self) -> List[str]:
        pass

    @abc.abstractmethod
    def explain_computation(self) -> ExplainComputationReport:
        pass

    def expects_per_partition_sampling(self) -> bool:
        """Whether the framework must sample values per partition down to
        max_contributions_per_partition before create_accumulator()."""
        return True


class CustomCombiner(Combiner, abc.ABC):
    """User-provided combiner for custom DP aggregations (experimental).

    The custom combiner implements its own DP mechanism in compute_metrics()
    and, if needed, contribution bounding in create_accumulator().
    """

    @abc.abstractmethod
    def request_budget(self,
                       budget_accountant: budget_accounting.BudgetAccountant):
        """Called during graph construction. Store the returned MechanismSpec
        in self; never store the budget_accountant itself (driver-only)."""

    def set_aggregate_params(self,
                             params: aggregate_params.AggregateParams):
        self._aggregate_params = params

    def metrics_names(self) -> List[str]:
        return [self.__class__.__name__]


class CombinerParams:
    """Budget spec + aggregation params bundled for a combiner."""

    def __init__(self, spec: budget_accounting.MechanismSpec,
                 params: aggregate_params.AggregateParams):
        self._mechanism_spec = spec
        self.aggregate_params = copy.copy(params)

    @property
    def eps(self):
        return self._mechanism_spec.eps

    @property
    def delta(self):
        return self._mechanism_spec.delta

    @property
    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    @property
    def scalar_noise_params(self):
        p = self.aggregate_params
        return dp_computations.ScalarNoiseParams(
            self.eps, self.delta, p.min_value, p.max_value,
            p.min_sum_per_partition, p.max_sum_per_partition,
            p.max_partitions_contributed, p.max_contributions_per_partition,
            p.noise_kind)

    @property
    def additive_vector_noise_params(
            self) -> dp_computations.AdditiveVectorNoiseParams:
        p = self.aggregate_params
        return dp_computations.AdditiveVectorNoiseParams(
            eps_per_coordinate=self.eps / p.vector_size,
            delta_per_coordinate=self.delta / p.vector_size,
            max_norm=p.vector_max_norm,
            l0_sensitivity=p.max_partitions_contributed,
            linf_sensitivity=p.max_contributions_per_partition,
            norm_kind=p.vector_norm_kind,
            noise_kind=p.noise_kind)


class MechanismContainerMixin(abc.ABC):
    """Lazily creates and caches a DP mechanism; drops it on serialization
    (mechanisms are rebuilt from the budget-finalized spec on the worker)."""

    @abc.abstractmethod
    def create_mechanism(
        self
    ) -> Union[dp_computations.AdditiveMechanism,
               dp_computations.MeanMechanism]:
        pass

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_mechanism", None)
        return state

    def get_mechanism(self):
        if not hasattr(self, "_mechanism"):
            self._mechanism = self.create_mechanism()
        return self._mechanism


class AdditiveMechanismMixin(MechanismContainerMixin):
    """MechanismContainerMixin for additive (Laplace/Gaussian) mechanisms."""

    def create_mechanism(self) -> dp_computations.AdditiveMechanism:
        return dp_computations.create_additive_mechanism(
            self.mechanism_spec(), self.sensitivities())

    @abc.abstractmethod
    def sensitivities(self) -> dp_computations.Sensitivities:
        pass

    @abc.abstractmethod
    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        pass

    def noise_std(self) -> float:
        """Noise stddev of the finalized mechanism (TPU path: traced input)."""
        return self.get_mechanism().std


class CountCombiner(Combiner, AdditiveMechanismMixin):
    """DP count. Accumulator: int count of contributions."""
    AccumulatorType = int

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 params: aggregate_params.AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = dp_computations.compute_sensitivities_for_count(
            params)

    def create_accumulator(self, values: Sized) -> AccumulatorType:
        return len(values)

    def merge_accumulators(self, count1, count2):
        return count1 + count2

    def compute_metrics(self, count: AccumulatorType) -> dict:
        return {'count': self.get_mechanism().add_noise(count)}

    def metrics_names(self) -> List[str]:
        return ['count']

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed DP count with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities



class PrivacyIdCountCombiner(Combiner, AdditiveMechanismMixin):
    """DP privacy-id count. Accumulator: int (1 per contributing id)."""
    AccumulatorType = int

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 params: aggregate_params.AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = (
            dp_computations.compute_sensitivities_for_privacy_id_count(params))

    def create_accumulator(self, values: Sized) -> AccumulatorType:
        return 1 if values else 0

    def merge_accumulators(self, count1, count2):
        return count1 + count2

    def compute_metrics(self, count: AccumulatorType) -> dict:
        return {"privacy_id_count": self.get_mechanism().add_noise(count)}

    def metrics_names(self) -> List[str]:
        return ['privacy_id_count']

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed DP privacy_id_count with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities

    def expects_per_partition_sampling(self) -> bool:
        return False



class SumCombiner(Combiner, AdditiveMechanismMixin):
    """DP sum with two clipping regimes (reference :327-379):

      * per-contribution bounds (min_value/max_value): clip each value, sum;
      * per-partition bounds (min_sum_per_partition/...): sum, then clip the
        per-(privacy_id, partition) sum.
    """
    AccumulatorType = float

    def __init__(self, mechanism_spec: budget_accounting.MechanismSpec,
                 params: aggregate_params.AggregateParams):
        self._mechanism_spec = mechanism_spec
        self._sensitivities = dp_computations.compute_sensitivities_for_sum(
            params)
        self._bounding_per_partition = params.bounds_per_partition_are_set
        if self._bounding_per_partition:
            self._min_bound = params.min_sum_per_partition
            self._max_bound = params.max_sum_per_partition
        else:
            self._min_bound = params.min_value
            self._max_bound = params.max_value

    def create_accumulator(self, values: Iterable[float]) -> AccumulatorType:
        if self._bounding_per_partition:
            return float(np.clip(sum(values), self._min_bound,
                                 self._max_bound))
        return float(
            np.clip(np.asarray(list(values), dtype=np.float64),
                    self._min_bound, self._max_bound).sum())

    def merge_accumulators(self, sum1, sum2):
        return sum1 + sum2

    def compute_metrics(self, sum_: AccumulatorType) -> dict:
        return {"sum": self.get_mechanism().add_noise(sum_)}

    def metrics_names(self) -> List[str]:
        return ['sum']

    def expects_per_partition_sampling(self) -> bool:
        return not self._bounding_per_partition

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed DP sum with\n"
                        f"     {self.get_mechanism().describe()}")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._mechanism_spec

    def sensitivities(self) -> dp_computations.Sensitivities:
        return self._sensitivities


    @property
    def bounding_per_partition(self) -> bool:
        return self._bounding_per_partition

    @property
    def bounds(self) -> Tuple[float, float]:
        return self._min_bound, self._max_bound


class MeanCombiner(Combiner, MechanismContainerMixin):
    """DP mean via the normalized-sum trick; optionally also count and sum.

    Accumulator: (count, normalized_sum) with values normalized to the range
    middle so the sum's sensitivity is (max-min)/2 per contribution.
    """
    AccumulatorType = Tuple[int, float]

    def __init__(self, count_spec: budget_accounting.MechanismSpec,
                 sum_spec: budget_accounting.MechanismSpec,
                 params: aggregate_params.AggregateParams,
                 metrics_to_compute: Iterable[str]):
        metrics_to_compute = list(metrics_to_compute)
        if len(metrics_to_compute) != len(set(metrics_to_compute)):
            raise ValueError(f"{metrics_to_compute} cannot contain duplicates")
        for metric in metrics_to_compute:
            if metric not in ('count', 'sum', 'mean'):
                raise ValueError(
                    f"{metric} should be one of ['count', 'sum', 'mean']")
        if 'mean' not in metrics_to_compute:
            raise ValueError(
                f"one of the {metrics_to_compute} should be 'mean'")
        self._count_spec = count_spec
        self._sum_spec = sum_spec
        self._metrics_to_compute = metrics_to_compute
        self._min_value = params.min_value
        self._max_value = params.max_value
        self._count_sensitivities = (
            dp_computations.compute_sensitivities_for_count(params))
        self._sum_sensitivities = (
            dp_computations.compute_sensitivities_for_normalized_sum(params))

    def create_accumulator(self, values: Iterable[float]) -> AccumulatorType:
        values = np.asarray(list(values), dtype=np.float64)
        middle = dp_computations.compute_middle(self._min_value,
                                                self._max_value)
        normalized = np.clip(values, self._min_value, self._max_value) - middle
        return len(values), float(normalized.sum())

    def merge_accumulators(self, accum1, accum2):
        return accum1[0] + accum2[0], accum1[1] + accum2[1]

    def compute_metrics(self, accum: AccumulatorType) -> dict:
        total_count, total_normalized_sum = accum
        noisy_count, noisy_sum, noisy_mean = self.get_mechanism().compute_mean(
            total_count, total_normalized_sum)
        result = {'mean': noisy_mean}
        if 'count' in self._metrics_to_compute:
            result['count'] = noisy_count
        if 'sum' in self._metrics_to_compute:
            result['sum'] = noisy_sum
        return result

    def metrics_names(self) -> List[str]:
        return self._metrics_to_compute

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: "DP mean computation:\n" + self.get_mechanism().describe(
        )

    def create_mechanism(self) -> dp_computations.MeanMechanism:
        middle = dp_computations.compute_middle(self._min_value,
                                                self._max_value)
        return dp_computations.create_mean_mechanism(middle, self._count_spec,
                                                     self._count_sensitivities,
                                                     self._sum_spec,
                                                     self._sum_sensitivities)

    def mechanism_spec(self):
        return (self._count_spec, self._sum_spec)


    @property
    def value_bounds(self) -> Tuple[float, float]:
        return self._min_value, self._max_value


class VarianceCombiner(Combiner):
    """DP variance (+ optionally mean/sum/count).

    Accumulator: (count, normalized_sum, normalized_sum_of_squares).
    """
    AccumulatorType = Tuple[int, float, float]

    def __init__(self, params: CombinerParams,
                 metrics_to_compute: Iterable[str]):
        self._params = params
        metrics_to_compute = list(metrics_to_compute)
        if len(metrics_to_compute) != len(set(metrics_to_compute)):
            raise ValueError(f"{metrics_to_compute} cannot contain duplicates")
        for metric in metrics_to_compute:
            if metric not in ('count', 'sum', 'mean', 'variance'):
                raise ValueError(f"{metric} should be one of "
                                 f"['count', 'sum', 'mean', 'variance']")
        if 'variance' not in metrics_to_compute:
            raise ValueError(
                f"one of the {metrics_to_compute} should be 'variance'")
        self._metrics_to_compute = metrics_to_compute

    def create_accumulator(self, values: Iterable[float]) -> AccumulatorType:
        p = self._params.aggregate_params
        middle = dp_computations.compute_middle(p.min_value, p.max_value)
        values = np.asarray(list(values), dtype=np.float64)
        normalized = np.clip(values, p.min_value, p.max_value) - middle
        return len(values), float(normalized.sum()), float(
            (normalized**2).sum())

    def merge_accumulators(self, accum1, accum2):
        return (accum1[0] + accum2[0], accum1[1] + accum2[1],
                accum1[2] + accum2[2])

    def compute_metrics(self, accum: AccumulatorType) -> dict:
        count, nsum, nsum2 = accum
        noisy_count, noisy_sum, noisy_mean, noisy_variance = (
            dp_computations.compute_dp_var(count, nsum, nsum2,
                                           self._params.scalar_noise_params))
        result = {'variance': noisy_variance}
        if 'count' in self._metrics_to_compute:
            result['count'] = noisy_count
        if 'sum' in self._metrics_to_compute:
            result['sum'] = noisy_sum
        if 'mean' in self._metrics_to_compute:
            result['mean'] = noisy_mean
        return result

    def metrics_names(self) -> List[str]:
        return self._metrics_to_compute

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed variance with (eps={self._params.eps} "
                        f"delta={self._params.delta})")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec



class QuantileCombiner(Combiner):
    """DP percentiles via the dense-array quantile tree (ops/quantile_tree).

    Accumulator: serialized tree bytes (mergeable across workers); on the TPU
    path the tree is a dense per-partition matrix and merge is vector add.
    """
    AccumulatorType = bytes

    def __init__(self,
                 params: CombinerParams,
                 percentiles_to_compute: List[float],
                 tree_height: int = quantile_tree_ops.DEFAULT_TREE_HEIGHT,
                 branching_factor: int = (
                     quantile_tree_ops.DEFAULT_BRANCHING_FACTOR)):
        self._params = params
        self._percentiles = percentiles_to_compute
        self._quantiles_to_compute = [p / 100 for p in percentiles_to_compute]
        self._tree_height = tree_height
        self._branching_factor = branching_factor

    def _empty_tree(self) -> quantile_tree_ops.DenseQuantileTree:
        p = self._params.aggregate_params
        return quantile_tree_ops.DenseQuantileTree(p.min_value, p.max_value,
                                                   self._tree_height,
                                                   self._branching_factor)

    def create_accumulator(self, values) -> AccumulatorType:
        tree = self._empty_tree()
        tree.add_entries(list(values))
        return tree.serialize()

    def merge_accumulators(self, acc1, acc2):
        tree = quantile_tree_ops.DenseQuantileTree.deserialize(acc1)
        tree.merge(quantile_tree_ops.DenseQuantileTree.deserialize(acc2))
        return tree.serialize()

    def compute_metrics(self, accumulator: AccumulatorType) -> dict:
        tree = quantile_tree_ops.DenseQuantileTree.deserialize(accumulator)
        p = self._params.aggregate_params
        quantiles = tree.compute_quantiles(
            self._params.eps, self._params.delta,
            p.max_partitions_contributed, p.max_contributions_per_partition,
            self._quantiles_to_compute, p.noise_kind)
        return dict(zip(self.metrics_names(), quantiles))

    def metrics_names(self) -> List[str]:

        def format_metric_name(p: float):
            int_p = int(round(p))
            p_str = str(int_p) if int_p == p else str(p).replace('.', '_')
            return f"percentile_{p_str}"

        return list(map(format_metric_name, self._percentiles))

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed percentiles {self._percentiles} with "
                        f"(eps={self._params.eps} delta={self._params.delta})")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec


class VectorSumCombiner(Combiner):
    """DP elementwise sum of fixed-size vectors."""
    AccumulatorType = np.ndarray

    def __init__(self, params: CombinerParams):
        self._params = params

    def create_accumulator(self,
                           values: Iterable[ArrayLike]) -> AccumulatorType:
        expected_shape = (self._params.aggregate_params.vector_size,)
        array_sum = None
        for val in values:
            val = np.asarray(val)
            if val.shape != expected_shape:
                raise TypeError(
                    f"Shape mismatch: {val.shape} != {expected_shape}")
            array_sum = val.copy() if array_sum is None else array_sum + val
        if array_sum is None:
            array_sum = np.zeros(expected_shape)
        return array_sum

    def merge_accumulators(self, array_sum1, array_sum2):
        return array_sum1 + array_sum2

    def compute_metrics(self, array_sum: AccumulatorType) -> dict:
        return {
            'vector_sum':
                dp_computations.add_noise_vector(
                    array_sum, self._params.additive_vector_noise_params)
        }

    def metrics_names(self) -> List[str]:
        return ['vector_sum']

    def explain_computation(self) -> ExplainComputationReport:
        return lambda: (f"Computed vector sum with (eps={self._params.eps} "
                        f"delta={self._params.delta})")

    def mechanism_spec(self) -> budget_accounting.MechanismSpec:
        return self._params.mechanism_spec


# Cache for namedtuple result types (Beam-style serialization support).
# Guarded: the service's worker pool builds CompoundCombiners on
# concurrent threads, and an unlocked get-or-create can install TWO
# distinct classes for one key — isinstance and pickle identity then
# differ between jobs that should share the type (thread-escape's
# first-run catch).
_named_tuple_cache_lock = threading.Lock()
_named_tuple_cache = {}
_GUARDED_BY = guarded_by("_named_tuple_cache_lock", "_named_tuple_cache")


def _get_or_create_named_tuple(type_name: str,
                               field_names: tuple) -> 'MetricsTuple':
    cache_key = (type_name, field_names)
    with _named_tuple_cache_lock:
        named_tuple = _named_tuple_cache.get(cache_key)
        if named_tuple is None:
            named_tuple = collections.namedtuple(type_name, field_names)
            named_tuple.__reduce__ = lambda self: (
                _create_named_tuple_instance,
                (type_name, field_names, tuple(self)))
            _named_tuple_cache[cache_key] = named_tuple
    return named_tuple


def _create_named_tuple_instance(type_name: str, field_names: tuple, values):
    return _get_or_create_named_tuple(type_name, field_names)(*values)


class CompoundCombiner(Combiner):
    """Combiner of combiners: computes several metrics in one pass.

    Accumulator: (row_count, (child accumulators...)). row_count equals the
    privacy-id count when rows are grouped per privacy id — private partition
    selection reads it.

    compute_metrics returns a MetricsTuple namedtuple (return_named_tuple) or
    the plain tuple of child results.
    """

    AccumulatorType = Tuple[int, Tuple]

    def __init__(self, combiners: Iterable['Combiner'],
                 return_named_tuple: bool):
        self._combiners = list(combiners)
        self._metrics_to_compute = []
        self._return_named_tuple = return_named_tuple
        if not self._return_named_tuple:
            return
        for combiner in self._combiners:
            self._metrics_to_compute.extend(combiner.metrics_names())
        if len(self._metrics_to_compute) != len(set(self._metrics_to_compute)):
            raise ValueError(
                f"two combiners in {combiners} cannot compute the same metrics")
        self._metrics_to_compute = tuple(self._metrics_to_compute)
        self._MetricsTuple = _get_or_create_named_tuple(
            "MetricsTuple", self._metrics_to_compute)

    @property
    def combiners(self) -> List[Combiner]:
        return self._combiners

    def create_accumulator(self, values) -> AccumulatorType:
        return (1,
                tuple(
                    combiner.create_accumulator(values)
                    for combiner in self._combiners))

    def merge_accumulators(self, acc1: AccumulatorType,
                           acc2: AccumulatorType) -> AccumulatorType:
        row_count1, children1 = acc1
        row_count2, children2 = acc2
        merged = tuple(
            combiner.merge_accumulators(a1, a2)
            for combiner, a1, a2 in zip(self._combiners, children1, children2))
        return (row_count1 + row_count2, merged)

    def compute_metrics(self, compound_accumulator: AccumulatorType):
        _, children = compound_accumulator
        if not self._return_named_tuple:
            return tuple(
                combiner.compute_metrics(acc)
                for combiner, acc in zip(self._combiners, children))

        combined_metrics = {}
        for combiner, acc in zip(self._combiners, children):
            for metric, value in combiner.compute_metrics(acc).items():
                if metric in combined_metrics:
                    raise Exception(
                        f"{metric} computed by {combiner} was already computed "
                        f"by another combiner")
                combined_metrics[metric] = value
        return _create_named_tuple_instance("MetricsTuple",
                                            tuple(combined_metrics.keys()),
                                            tuple(combined_metrics.values()))

    def metrics_names(self) -> List[str]:
        return list(self._metrics_to_compute)

    def explain_computation(self) -> ExplainComputationReport:
        return [combiner.explain_computation() for combiner in self._combiners]

    def expects_per_partition_sampling(self) -> bool:
        return any(c.expects_per_partition_sampling() for c in self._combiners)


def create_compound_combiner(
        params: aggregate_params.AggregateParams,
        budget_accountant: budget_accounting.BudgetAccountant
) -> CompoundCombiner:
    """Builds the CompoundCombiner for the requested metrics, requesting one
    budget per mechanism (reference :791-858).

    Each request is wrapped in observability.mechanism_label so the
    privacy-budget odometer's audit records carry the DP metric the
    mechanism serves (count/sum/...), not just its noise kind.
    """
    # Lazy import: combiners must stay importable without the runtime
    # package (the generic backends use them standalone).
    from pipelinedp_tpu.runtime import observability
    combiners = []
    mechanism_type = params.noise_kind.convert_to_mechanism_type()

    def request(metric_label: str):
        with observability.mechanism_label(metric_label):
            return budget_accountant.request_budget(
                mechanism_type, weight=params.budget_weight)

    if Metrics.VARIANCE in params.metrics:
        budget_variance = request('variance')
        metrics_to_compute = ['variance']
        if Metrics.MEAN in params.metrics:
            metrics_to_compute.append('mean')
        if Metrics.COUNT in params.metrics:
            metrics_to_compute.append('count')
        if Metrics.SUM in params.metrics:
            metrics_to_compute.append('sum')
        combiners.append(
            VarianceCombiner(CombinerParams(budget_variance, params),
                             metrics_to_compute))
    elif Metrics.MEAN in params.metrics:
        budget_count = request('count')
        budget_sum = request('sum')
        metrics_to_compute = ['mean']
        if Metrics.COUNT in params.metrics:
            metrics_to_compute.append('count')
        if Metrics.SUM in params.metrics:
            metrics_to_compute.append('sum')
        combiners.append(
            MeanCombiner(budget_count, budget_sum, params, metrics_to_compute))
    else:
        if Metrics.COUNT in params.metrics:
            combiners.append(CountCombiner(request('count'), params))
        if Metrics.SUM in params.metrics:
            combiners.append(SumCombiner(request('sum'), params))
    if Metrics.PRIVACY_ID_COUNT in params.metrics:
        combiners.append(
            PrivacyIdCountCombiner(request('privacy_id_count'), params))
    if Metrics.VECTOR_SUM in params.metrics:
        combiners.append(
            VectorSumCombiner(
                CombinerParams(request('vector_sum'), params)))

    percentiles_to_compute = [
        metric.parameter for metric in params.metrics if metric.is_percentile
    ]
    if percentiles_to_compute:
        combiners.append(
            QuantileCombiner(
                CombinerParams(request('percentile'), params),
                percentiles_to_compute))

    return CompoundCombiner(combiners, return_named_tuple=True)


def create_compound_combiner_with_custom_combiners(
        params: aggregate_params.AggregateParams,
        budget_accountant: budget_accounting.BudgetAccountant,
        custom_combiners: Iterable[CustomCombiner]) -> CompoundCombiner:
    for combiner in custom_combiners:
        params_copy = copy.copy(params)
        params_copy.custom_combiners = None
        combiner.set_aggregate_params(params_copy)
        combiner.request_budget(budget_accountant)
    return CompoundCombiner(custom_combiners, return_named_tuple=False)
