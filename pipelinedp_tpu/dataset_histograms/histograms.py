"""Histogram dataclasses for dataset contribution statistics.

Capability parity with the reference ``pipeline_dp/dataset_histograms/
histograms.py:21-211``: FrequencyBin / HistogramType / Histogram /
DatasetHistograms, plus ``compute_ratio_dropped``. The quantile and
ratio-dropped computations are vectorized with numpy (the reference loops
over bins in Python, ``histograms.py:126-200``); semantics are identical.
"""

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np


@dataclasses.dataclass
class FrequencyBin:
    """One histogram bin over ``[lower, upper)`` (last float bin is closed).

    Reference semantics: ``histograms.py:21-57``.

    Attributes:
        lower: lower bound of the bin (inclusive).
        upper: upper bound of the bin (exclusive, except the last bin of a
            floating histogram where it is inclusive).
        count: number of elements in the bin.
        sum: sum of elements in the bin.
        max: maximum element in the bin (<= upper).
    """
    lower: Union[int, float]
    upper: Union[int, float]
    count: int
    sum: Union[int, float]
    max: Union[int, float]

    def __add__(self, other: 'FrequencyBin') -> 'FrequencyBin':
        assert self.lower == other.lower
        assert self.upper == other.upper
        return FrequencyBin(self.lower, self.upper, self.count + other.count,
                            self.sum + other.sum, max(self.max, other.max))

    def __eq__(self, other) -> bool:
        return (self.lower == other.lower and self.count == other.count and
                self.sum == other.sum and self.max == other.max)


class HistogramType(enum.Enum):
    """Reference: ``histograms.py:60-75``."""
    # 'count' = number of privacy units contributing to [lower, upper)
    # partitions; 'sum' = total (privacy_unit, partition) pairs for them.
    L0_CONTRIBUTIONS = 'l0_contributions'
    L1_CONTRIBUTIONS = 'l1_contributions'
    # 'count' = number of (privacy_unit, partition) pairs with [lower, upper)
    # contributions; 'sum' = total contributions for those pairs.
    LINF_CONTRIBUTIONS = 'linf_contributions'
    LINF_SUM_CONTRIBUTIONS = 'linf_sum_contributions'
    COUNT_PER_PARTITION = 'count_per_partition'
    COUNT_PRIVACY_ID_PER_PARTITION = 'privacy_id_per_partition_count'


@dataclasses.dataclass
class Histogram:
    """Histogram over numbers; integer (log-binned) or floating (equal bins).

    Reference: ``histograms.py:78-158``.
    """
    name: HistogramType
    bins: List[FrequencyBin]
    lower: Union[None, int, float] = dataclasses.field(init=False)
    upper: Union[None, float] = dataclasses.field(init=False)

    def __post_init__(self):
        if len(self.bins) == 0:
            self.lower = self.upper = None
        else:
            self.lower = 1 if self.is_integer else self.bins[0].lower
            self.upper = None if self.is_integer else self.bins[-1].upper

    @property
    def is_integer(self) -> bool:
        return self.name != HistogramType.LINF_SUM_CONTRIBUTIONS

    def total_count(self) -> int:
        return int(sum(b.count for b in self.bins))

    def total_sum(self):
        return sum(b.sum for b in self.bins)

    def max_value(self):
        return self.bins[-1].max

    def quantiles(self, q: Sequence[float]) -> List[int]:
        """Approximate quantiles: bin lowers such that the mass strictly left
        of the bin is <= q. Vectorized equivalent of ``histograms.py:126-158``.
        """
        assert sorted(q) == list(q), "Quantiles to compute must be sorted."
        counts = np.array([b.count for b in self.bins], dtype=np.float64)
        total = counts.sum()
        if total == 0:
            raise ValueError("Cannot compute quantiles of an empty histogram")
        # ratio of data strictly left of each bin
        left_ratio = (np.cumsum(counts) - counts) / total
        lowers = [b.lower for b in self.bins]
        # for each q: the LAST bin whose left_ratio <= q
        idx = np.searchsorted(left_ratio, np.asarray(q), side='right') - 1
        idx = np.clip(idx, 0, len(lowers) - 1)
        return [lowers[i] for i in idx]


def compute_ratio_dropped(
        contribution_histogram: Histogram) -> Sequence[Tuple[int, float]]:
    """Ratio of data dropped per candidate bounding threshold.

    For each bin lower L of the contribution histogram: the fraction of total
    contributions that would be dropped if L were used as the bounding
    threshold (sum over elements of max(0, x - L) / total_sum). ``(0, 1)`` is
    prepended; the histogram max is appended with ratio 0 when it is not a bin
    lower. Vectorized equivalent of the reference's reverse scan
    (``histograms.py:161-200``).
    """
    bins = contribution_histogram.bins
    if not bins:
        return []
    lowers = np.array([b.lower for b in bins], dtype=np.float64)
    counts = np.array([b.count for b in bins], dtype=np.float64)
    sums = np.array([b.sum for b in bins], dtype=np.float64)
    total_sum = sums.sum()

    thresholds = list(lowers)
    max_value = contribution_histogram.max_value()
    append_max = (max_value != bins[-1].lower)

    # Reverse-cumulative machinery: for threshold t = lowers[i],
    # dropped(t) = sum_{j>=i} (sums[j] - counts[j]*clip_at_t) where elements
    # in bin j are approximated as sitting at their bin values. The reference
    # computes it with an exact reverse scan using bin sums/counts; replicate
    # that recurrence vectorized.
    n = len(bins)
    # elements_larger[i] = count of elements in bins strictly above i
    elements_larger = np.concatenate(
        [np.cumsum(counts[::-1])[::-1][1:], [0.0]])
    # Recurrence (histograms.py:192-198), scanning high→low:
    #   dropped += elements_larger*(previous_value-current) + (bin.sum -
    #              bin.count*current)
    # n is small (log-binned), so a host scan is fine.
    per_bin_term = (sums - counts * lowers)
    acc = 0.0
    out = []
    prev = lowers[-1]
    for i in range(n - 1, -1, -1):
        cur = lowers[i]
        acc += (elements_larger[i] * (prev - cur)) + per_bin_term[i]
        out.append((thresholds[i], acc / total_sum))
        prev = cur
    result = []
    if append_max:
        result.append((max_value, 0.0))
    result.extend(out)
    result.append((0, 1))
    return result[::-1]


@dataclasses.dataclass
class DatasetHistograms:
    """Histograms useful for parameter tuning (``histograms.py:203-211``)."""
    l0_contributions_histogram: Optional[Histogram]
    l1_contributions_histogram: Optional[Histogram]
    linf_contributions_histogram: Optional[Histogram]
    linf_sum_contributions_histogram: Optional[Histogram]
    count_per_partition_histogram: Optional[Histogram]
    count_privacy_id_per_partition: Optional[Histogram]
