"""Estimation of DP-pipeline error from DatasetHistograms.

Capability parity with the reference ``pipeline_dp/dataset_histograms/
histogram_error_estimator.py:22-158`` (COUNT / PRIVACY_ID_COUNT only;
partition-selection error not modeled). The per-bin RMSE average is
vectorized with numpy.
"""

import math
from typing import Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu.dataset_histograms import histograms as hist


class CountErrorEstimator:
    """Estimates contribution-bounding + noise RMSE from histograms.

    Create with :func:`create_error_estimator`.
    """

    def __init__(self, base_std: float, metric: agg.Metric,
                 noise: agg.NoiseKind,
                 l0_ratios_dropped: Sequence[Tuple[int, float]],
                 linf_ratios_dropped: Sequence[Tuple[int, float]],
                 partition_histogram: hist.Histogram):
        self._base_std = base_std
        self._metric = metric
        self._noise = noise
        self._l0_ratios_dropped = l0_ratios_dropped
        self._linf_ratios_dropped = linf_ratios_dropped
        self._partition_histogram = partition_histogram

    def estimate_rmse(self,
                      l0_bound: int,
                      linf_bound: Optional[int] = None) -> float:
        """RMSE estimate for given l0/linf bounds.

        Assumes contribution bounding drops data uniformly over partitions:
        per partition of size n, rmse = sqrt((n*ratio_dropped)^2 + std^2),
        averaged over partitions (reference ``:44-81``).
        """
        if self._metric == agg.Metrics.COUNT and linf_bound is None:
            raise ValueError("linf must be given for COUNT")
        ratio_dropped_l0 = self.get_ratio_dropped_l0(l0_bound)
        ratio_dropped_linf = 0.0
        if self._metric == agg.Metrics.COUNT:
            ratio_dropped_linf = self.get_ratio_dropped_linf(linf_bound)
        ratio_dropped = 1 - (1 - ratio_dropped_l0) * (1 - ratio_dropped_linf)
        stddev = self._get_stddev(l0_bound, linf_bound)
        return _estimate_rmse_impl(ratio_dropped, stddev,
                                   self._partition_histogram)

    def get_ratio_dropped_l0(self, l0_bound: int) -> float:
        return self._get_ratio_dropped(self._l0_ratios_dropped, l0_bound)

    def get_ratio_dropped_linf(self, linf_bound: int) -> float:
        return self._get_ratio_dropped(self._linf_ratios_dropped, linf_bound)

    def _get_ratio_dropped(self, ratios_dropped: Sequence[Tuple[int, float]],
                           bound: int) -> float:
        """Linear interpolation in the (threshold, ratio) table."""
        if bound <= 0:
            return 1.0
        xs = np.array([x for x, _ in ratios_dropped], dtype=np.float64)
        ys = np.array([y for _, y in ratios_dropped], dtype=np.float64)
        if bound > xs[-1]:
            return 0.0
        return float(np.interp(bound, xs, ys))

    def _get_stddev(self,
                    l0_bound: int,
                    linf_bound: Optional[int] = None) -> float:
        if self._metric == agg.Metrics.PRIVACY_ID_COUNT:
            linf_bound = 1
        if self._noise == agg.NoiseKind.LAPLACE:
            return self._base_std * l0_bound * linf_bound
        return self._base_std * math.sqrt(l0_bound) * linf_bound


def create_error_estimator(histograms: hist.DatasetHistograms, base_std: float,
                           metric: agg.Metric,
                           noise: agg.NoiseKind) -> CountErrorEstimator:
    """Creates the estimator for COUNT or PRIVACY_ID_COUNT.

    base_std: noise std when l0 = linf = 1.
    """
    if metric not in [agg.Metrics.COUNT, agg.Metrics.PRIVACY_ID_COUNT]:
        raise ValueError("Only COUNT and PRIVACY_ID_COUNT are supported, "
                         f"but metric={metric}")
    l0_ratios_dropped = hist.compute_ratio_dropped(
        histograms.l0_contributions_histogram)
    linf_ratios_dropped = hist.compute_ratio_dropped(
        histograms.linf_contributions_histogram)
    if metric == agg.Metrics.COUNT:
        partition_histogram = histograms.count_per_partition_histogram
    else:
        partition_histogram = histograms.count_privacy_id_per_partition
    return CountErrorEstimator(base_std, metric, noise, l0_ratios_dropped,
                               linf_ratios_dropped, partition_histogram)


def _estimate_rmse_impl(ratio_dropped: float, std: float,
                        partition_histogram: hist.Histogram) -> float:
    counts = np.array([b.count for b in partition_histogram.bins],
                      dtype=np.float64)
    sums = np.array([b.sum for b in partition_histogram.bins],
                    dtype=np.float64)
    avg_sizes = sums / counts
    rmse = np.sqrt((ratio_dropped * avg_sizes)**2 + std**2)
    return float(np.sum(counts * rmse) / counts.sum())
