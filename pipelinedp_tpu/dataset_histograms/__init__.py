"""Dataset histograms: contribution-distribution statistics for tuning.

Capability parity with the reference package
``pipeline_dp/dataset_histograms/`` (histograms.py, computing_histograms.py,
histogram_error_estimator.py), re-designed for columnar/vectorized
computation: binning is a numpy ufunc over whole columns instead of a
per-element lambda chain, and ``device_histograms`` computes all six
histograms on device (sort + segment scans, bins reduced and compacted on
device) for encoded columnar datasets.
"""

from pipelinedp_tpu.dataset_histograms import histograms
from pipelinedp_tpu.dataset_histograms import computing_histograms
from pipelinedp_tpu.dataset_histograms import device_histograms
from pipelinedp_tpu.dataset_histograms import histogram_error_estimator
