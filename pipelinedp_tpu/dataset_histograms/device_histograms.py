"""Dataset contribution histograms computed ON DEVICE.

TPU-first counterpart of ``compute_dataset_histograms_columnar``: the
grouped statistics (per-privacy-id, per-pair, per-partition counts and
sums) come from the same sort + segment-scan machinery as the aggregation
kernel, and the log-binned frequency histograms are reduced and compacted
on device too, so only O(bins) values cross the device->host boundary.
Capability parity with the reference's histogram pipeline
(``pipeline_dp/dataset_histograms/computing_histograms.py:420-474``), whose
shuffles become two row sorts plus one small sort per histogram here.

Semantics match the host path bit-for-bit (asserted by parity tests): the
log binning keeps 3 leading decimal digits and is computed in pure integer
arithmetic (digit counts by comparison against a power-of-ten table), so no
float rounding can move a value across a decade boundary.

Scope: single device invocation — rows must fit one HBM-sized chunk
(~10^8). Larger datasets should fall back to the host columnar path or
pre-aggregate per shard; per-partition statistics are not mergeable across
arbitrary row chunks. Bin `sum` fields accumulate in f32 on device (the
host path uses int64/f64): exact below 2^24 per bin, ~1e-7 relative beyond
— histogram sums feed tuning heuristics, not releases, so the drift is
immaterial there.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import executor
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch
from pipelinedp_tpu.dataset_histograms import histograms as hist
from pipelinedp_tpu.ops import segment_ops
from pipelinedp_tpu.runtime import trace as rt_trace

_I32_MAX = np.iinfo(np.int32).max
# pow10[d] = 10^d for d in 0..9 (10^10 exceeds int32; values above 10^9
# never compare equal to their bound, so the table never needs it).
_POW10 = tuple(10**d for d in range(10))


def _log_bin_bounds(value: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(lower, upper) of the 3-leading-digit log bin, pure int32 math.

    Mirrors ``computing_histograms._to_bin_lower_upper_logarithmic``:
    bound = the smallest power of ten >= max(value, 1000); round_base =
    bound/1000; lower = value rounded down to round_base; the bin at an
    exact bound is one decade wider.
    """
    pow10 = jnp.asarray(_POW10, dtype=jnp.int32)
    # Number of decimal digits d: value >= 10^k for k = 0..9.
    d = jnp.sum(value[..., None] >= pow10[None, :], axis=-1)  # 1..10
    is_pow10 = value == pow10[jnp.minimum(d - 1, 9)]
    exp = jnp.where(is_pow10, d - 1, d)
    exp = jnp.maximum(exp, 3)
    round_base = pow10[jnp.minimum(exp - 3, 7)]
    lower = value // round_base * round_base
    at_bound = (exp <= 9) & (value == pow10[jnp.minimum(exp, 9)])
    size = jnp.where(at_bound, round_base * 10, round_base)
    return lower, lower + size


def _bin_int_kernel(values: jnp.ndarray, valid: jnp.ndarray):
    """Log-binned frequency histogram of an int stat array, on device.

    Returns (lowers, uppers, counts, sums, maxes, n_bins): compacted to the
    front, one entry per non-empty bin; rows beyond n_bins are padding.
    """
    values = values.astype(jnp.int32)
    lower, upper = _log_bin_bounds(jnp.maximum(values, 1))
    key = jnp.where(valid, lower, _I32_MAX)
    (skey,), pay = executor._sort_rows(
        [key], [jnp.where(valid, values, 0),
                jnp.where(valid, upper, 0)])
    svals, supper = pay
    new_bin = segment_ops.boundary_mask(skey)
    starts = segment_ops.segment_start_positions(new_bin)
    nxt = segment_ops.next_segment_start(new_bin)
    seg_len = (nxt - starts).astype(jnp.int32)
    cs = jnp.concatenate(
        [jnp.zeros(1, jnp.float32),
         segment_ops.chunked_cumsum(svals.astype(jnp.float32))])
    seg_sum = cs[nxt] - cs[starts]
    # Per-segment max via reverse cummax within segments: values sorted by
    # bin, so the segment max is the max of a suffix limited to the segment.
    # Simpler exact route: segment_sum of one-hot maxima is overkill; use
    # sorted order: within a bin, rows are NOT value-sorted, so compute via
    # jax.ops.segment_max over dense segment ids.
    seg_id, _ = segment_ops.segment_starts_and_ids(new_bin)
    n = values.shape[0]
    seg_max = jax.ops.segment_max(svals, seg_id, num_segments=n,
                                  indices_are_sorted=True)
    seg_upper = jax.ops.segment_max(supper, seg_id, num_segments=n,
                                    indices_are_sorted=True)
    # One output slot per segment start; compact bins to the front.
    # seg_len / seg_sum are per-ROW (valid at any row of the segment);
    # seg_max / seg_upper are per-SEGMENT (indexed via seg_id).
    is_real = new_bin & (skey != _I32_MAX)
    order = jnp.argsort(~is_real, stable=True)
    gather_id = seg_id[order]
    return (skey[order], seg_upper[gather_id], seg_len[order],
            seg_sum[order], seg_max[gather_id], is_real.sum())


def _bin_float_kernel(values: jnp.ndarray, valid: jnp.ndarray,
                      n_buckets: int):
    """Equal-width float histogram (reference 10k-bucket binning)."""
    values = values.astype(jnp.float32)
    big = jnp.float32(np.finfo(np.float32).max)
    lo = jnp.min(jnp.where(valid, values, big))
    hi = jnp.max(jnp.where(valid, values, -big))
    # searchsorted over the linspace edges, exactly like the host path
    # (division-based indexing can land one bin off at edge values).
    edges = jnp.linspace(lo, hi, n_buckets + 1)
    idx = jnp.searchsorted(edges, values, side="right") - 1
    idx = jnp.clip(idx, 0, n_buckets - 1)
    idx = jnp.where(valid, idx, n_buckets)
    counts = jnp.zeros(n_buckets + 1, jnp.int32).at[idx].add(1)
    sums = jnp.zeros(n_buckets + 1, jnp.float32).at[idx].add(
        jnp.where(valid, values, 0.0))
    maxes = jnp.full(n_buckets + 1, -big).at[idx].max(
        jnp.where(valid, values, -big))
    return lo, hi, counts[:-1], sums[:-1], maxes[:-1]


@functools.partial(jax.jit, static_argnames=("has_values",))
def _group_stats_kernel(pid, pk, values, valid, has_values: bool):
    """All six grouped stat arrays in one program.

    Returns per-row-slot stat arrays with validity masks: stats live at
    group-start slots of their respective sort orders.
    """
    i32 = jnp.int32
    pid_s = jnp.where(valid, pid, _I32_MAX).astype(i32)
    pk_s = jnp.where(valid, pk, _I32_MAX).astype(i32)

    # Sort rows by (pid, pk); invalid rows sink to the tail.
    (spid, spk), pay = executor._sort_rows(
        [pid_s, pk_s], [values.astype(jnp.float32), valid])
    svals, svalid = pay
    new_pair = segment_ops.boundary_mask(spid, spk) & svalid
    new_pid = segment_ops.boundary_mask(spid) & svalid

    starts = segment_ops.segment_start_positions(new_pair | ~svalid)
    nxt = segment_ops.next_segment_start(new_pair | ~svalid)
    pair_len = (nxt - starts).astype(i32)
    # Pair sums via per-segment tree reduction, not cumsum differences:
    # a cumsum over the whole column carries O(total) f32 cancellation
    # (~1e-4 here) into every pair sum, which visibly shifts the
    # 10k-bucket float histogram grid; per-segment sums only accumulate
    # the pair's own few rows.
    pair_seg_id, _ = segment_ops.segment_starts_and_ids(new_pair | ~svalid)
    n_rows = svals.shape[0]
    pair_sum_per_seg = jax.ops.segment_sum(jnp.where(svalid, svals, 0.0),
                                           pair_seg_id,
                                           num_segments=n_rows,
                                           indices_are_sorted=True)
    pair_sum = pair_sum_per_seg[pair_seg_id]

    pid_starts = segment_ops.segment_start_positions(new_pid | ~svalid)
    pid_nxt = segment_ops.next_segment_start(new_pid | ~svalid)
    l1_per_pid = (pid_nxt - pid_starts).astype(i32)
    # L0 = #pairs per pid: count pair starts within the pid segment.
    # int32 accumulation: exact to 2^31 pairs (a f32 cumsum loses +1
    # increments past 2^24, silently corrupting l0 at ~16.7M pairs).
    cp = jnp.concatenate(
        [jnp.zeros(1, i32), jnp.cumsum(new_pair.astype(i32))])
    l0_per_pid = cp[pid_nxt] - cp[pid_starts]

    # Per-partition stats: rows re-sorted by pk.
    (spk2,), pay2 = executor._sort_rows([pk_s], [valid])
    svalid2 = pay2[0]
    new_pk = segment_ops.boundary_mask(spk2) & svalid2
    pk_starts = segment_ops.segment_start_positions(new_pk | ~svalid2)
    pk_nxt = segment_ops.next_segment_start(new_pk | ~svalid2)
    count_per_pk = (pk_nxt - pk_starts).astype(i32)

    # Privacy ids per partition: pair-start rows re-keyed by pk.
    pair_pk = jnp.where(new_pair, spk, _I32_MAX)
    (spk3,), pay3 = executor._sort_rows([pair_pk], [new_pair])
    is_pair3 = pay3[0]
    new_pk3 = segment_ops.boundary_mask(spk3) & is_pair3
    pk3_starts = segment_ops.segment_start_positions(new_pk3 | ~is_pair3)
    pk3_nxt = segment_ops.next_segment_start(new_pk3 | ~is_pair3)
    pids_per_pk = (pk3_nxt - pk3_starts).astype(i32)

    out = {
        "l0": _bin_int_kernel(l0_per_pid, new_pid),
        "l1": _bin_int_kernel(l1_per_pid, new_pid),
        "linf": _bin_int_kernel(pair_len, new_pair),
        "count_per_pk": _bin_int_kernel(count_per_pk, new_pk),
        "pids_per_pk": _bin_int_kernel(pids_per_pk, new_pk3),
    }
    if has_values:
        out["linf_sum"] = _bin_float_kernel(
            pair_sum, new_pair,
            ch.NUMBER_OF_BUCKETS_IN_LINF_SUM_CONTRIBUTIONS_HISTOGRAM)
    return out


# Compile/dispatch attribution (runtime/trace.probe_jit, enforced by
# staticcheck's jit-boundary rule).
_group_stats_kernel = rt_trace.probe_jit("group_stats_kernel",
                                         _group_stats_kernel)


def _int_bins_to_histogram(binned, name: hist.HistogramType) -> hist.Histogram:
    lowers, uppers, counts, sums, maxes, n_bins = binned
    k = int(n_bins)
    # Bin bounds are computed in int32 on device; a stat value within one
    # round_base of 2^31 would wrap its upper bound negative. All binned
    # stats are row counts (<= the documented ~1e8-row scope) so this is
    # unreachable today — fail loudly rather than emit a corrupt bound if a
    # future caller bins larger stats.
    uppers_np = np.asarray(uppers[:k])
    if k and int(uppers_np.min()) <= 0:
        raise OverflowError(
            f"{name}: log-bin upper bound overflowed int32; stat values "
            "must stay below 2^31 - round_base on the device path")
    bins = [
        hist.FrequencyBin(lower=int(l), upper=int(u), count=int(c),
                          sum=int(s), max=int(m))
        for l, u, c, s, m in zip(
            np.asarray(lowers[:k]), uppers_np,
            np.asarray(counts[:k]), np.asarray(sums[:k]).round().astype(
                np.int64), np.asarray(maxes[:k]))
    ]
    return hist.Histogram(name, bins)


def _float_bins_to_histogram(binned,
                             name: hist.HistogramType) -> hist.Histogram:
    lo, hi, counts, sums, maxes = (np.asarray(x) for x in binned)
    n_buckets = len(counts)
    lowers = np.linspace(float(lo), float(hi), n_buckets + 1)
    nz = np.nonzero(counts)[0]
    bins = [
        hist.FrequencyBin(lower=float(lowers[i]), upper=float(lowers[i + 1]),
                          count=int(counts[i]), sum=float(sums[i]),
                          max=float(maxes[i])) for i in nz
    ]
    return hist.Histogram(name, bins)


def compute_dataset_histograms_device(
        pids: np.ndarray,
        pks: np.ndarray,
        values: Optional[np.ndarray] = None) -> hist.DatasetHistograms:
    """All six dataset histograms from integer-encoded columns, on device.

    Same semantics as :func:`computing_histograms.
    compute_dataset_histograms_columnar`; rows must fit one device chunk.
    """
    pids = np.asarray(pids)
    pks = np.asarray(pks)
    has_values = values is not None
    n = len(pids)
    cap = max(8, 1 << (n - 1).bit_length()) if n else 8
    pad = cap - n

    def padded(a, fill=0):
        return np.pad(np.asarray(a), (0, pad), constant_values=fill)

    vals = (np.asarray(values, dtype=np.float32)
            if has_values else np.zeros(n, np.float32))
    out = _group_stats_kernel(padded(pids).astype(np.int32),
                              padded(pks).astype(np.int32), padded(vals),
                              padded(np.ones(n, bool), False), has_values)
    return hist.DatasetHistograms(
        _int_bins_to_histogram(out["l0"], hist.HistogramType.L0_CONTRIBUTIONS),
        _int_bins_to_histogram(out["l1"], hist.HistogramType.L1_CONTRIBUTIONS),
        _int_bins_to_histogram(out["linf"],
                               hist.HistogramType.LINF_CONTRIBUTIONS),
        _float_bins_to_histogram(out["linf_sum"],
                                 hist.HistogramType.LINF_SUM_CONTRIBUTIONS)
        if has_values else None,
        _int_bins_to_histogram(out["count_per_pk"],
                               hist.HistogramType.COUNT_PER_PARTITION),
        _int_bins_to_histogram(
            out["pids_per_pk"],
            hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION),
    )
