"""Computing dataset histograms.

Capability parity with the reference ``pipeline_dp/dataset_histograms/
computing_histograms.py`` (log binning ``:28-47``, frequency histograms
``:62-195``, raw-dataset histograms ``:236-474``, pre-aggregated variants
``:477-684``), re-designed vectorized: the per-element binning lambda chain
of the reference is replaced by numpy ufuncs over whole frequency columns,
and there is an additional pure-columnar entry point
(:func:`compute_dataset_histograms_columnar`) that computes all six
histograms from ``(pid, pk, value)`` arrays in a handful of ``np.unique`` /
``bincount`` passes — the shape the TPU ingest path already has.
"""

from typing import List, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import data_extractors as extractors
from pipelinedp_tpu import pipeline_backend, pipeline_functions
from pipelinedp_tpu.dataset_histograms import histograms as hist

NUMBER_OF_BUCKETS_IN_LINF_SUM_CONTRIBUTIONS_HISTOGRAM = 10000


def _to_bin_lower_upper_logarithmic(value: int) -> Tuple[int, int]:
    """Log-ish binning keeping 3 leading digits (reference ``:28-47``).

    123 -> [123,124), 1234 -> [1230,1240), 12345 -> [12300,12400); exact
    powers-of-10 boundary values get a bin of the next width. Keep in sync
    with private_contribution_bounds.generate_possible_contribution_bounds.
    """
    bound = 1000
    while value > bound:
        bound *= 10
    round_base = bound // 1000
    lower = value // round_base * round_base
    bin_size = round_base if value != bound else round_base * 10
    return lower, lower + bin_size


def _bin_lowers_log_vectorized(
        values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized _to_bin_lower_upper_logarithmic over an int array."""
    values = np.asarray(values, dtype=np.int64)
    # bound = smallest power-of-10 multiple of 1000 that is >= value
    # i.e. bound = 1000 * 10^max(0, ceil(log10(value/1000)))
    safe = np.maximum(values, 1).astype(np.float64)
    exp = np.ceil(np.log10(safe / 1000.0))
    exp = np.maximum(exp, 0).astype(np.int64)
    bound = 1000 * np.power(10, exp)
    # float log10 can land one decade off at exact boundaries; correct it.
    bound = np.where(bound < values, bound * 10, bound)
    bound_down = bound // 10
    bound = np.where((bound_down >= 1000) & (bound_down >= values),
                     bound_down, bound)
    round_base = bound // 1000
    lower = values // round_base * round_base
    bin_size = np.where(values != bound, round_base, round_base * 10)
    return lower, lower + bin_size


def _frequencies_to_histogram(values: np.ndarray,
                              frequencies: np.ndarray,
                              name: hist.HistogramType) -> hist.Histogram:
    """Builds a log-binned integer Histogram from (value, frequency) columns.

    Vectorized equivalent of the reference's map→reduce_per_key chain
    (``computing_histograms.py:105-195``).
    """
    values = np.asarray(values, dtype=np.int64)
    frequencies = np.asarray(frequencies, dtype=np.int64)
    if values.size == 0:
        return hist.Histogram(name, [])
    lowers, uppers = _bin_lowers_log_vectorized(values)
    uniq_lowers, inverse = np.unique(lowers, return_inverse=True)
    counts = np.bincount(inverse, weights=frequencies)
    sums = np.bincount(inverse, weights=frequencies * values)
    # per-bin max of values and the bin upper
    maxes = np.zeros(uniq_lowers.size, dtype=np.int64)
    np.maximum.at(maxes, inverse, values)
    bin_uppers = np.zeros(uniq_lowers.size, dtype=np.int64)
    np.maximum.at(bin_uppers, inverse, uppers)
    bins = [
        hist.FrequencyBin(lower=int(l), upper=int(u), count=int(c),
                          sum=int(s), max=int(m))
        for l, u, c, s, m in zip(uniq_lowers, bin_uppers, counts, sums, maxes)
    ]
    return hist.Histogram(name, bins)


def _float_values_to_histogram(values: np.ndarray,
                               name: hist.HistogramType,
                               number_of_buckets: int = None
                               ) -> hist.Histogram:
    """Equal-width float histogram between min and max (reference ``:314-362``)."""
    if number_of_buckets is None:
        number_of_buckets = (
            NUMBER_OF_BUCKETS_IN_LINF_SUM_CONTRIBUTIONS_HISTOGRAM)
    values = np.asarray(values, dtype=np.float64)
    if values.size == 0:
        return hist.Histogram(name, [])
    lo, hi = float(values.min()), float(values.max())
    lowers = np.linspace(lo, hi, number_of_buckets + 1)
    idx = np.searchsorted(lowers, values, side='right') - 1
    idx = np.clip(idx, 0, number_of_buckets - 1)
    uniq_idx, inverse = np.unique(idx, return_inverse=True)
    counts = np.bincount(inverse)
    sums = np.bincount(inverse, weights=values)
    maxes = np.full(uniq_idx.size, -np.inf)
    np.maximum.at(maxes, inverse, values)
    bins = [
        hist.FrequencyBin(lower=float(lowers[i]), upper=float(lowers[i + 1]),
                          count=int(c), sum=float(s), max=float(m))
        for i, c, s, m in zip(uniq_idx, counts, sums, maxes)
    ]
    return hist.Histogram(name, bins)


def _compute_frequency_histogram(col,
                                 backend: pipeline_backend.PipelineBackend,
                                 name: hist.HistogramType):
    """Histogram of element frequencies (collection of positive ints).

    Returns a 1-element collection with hist.Histogram. The count-per-element
    shuffle stays a backend op; binning happens vectorized on the collected
    (value, frequency) columns.
    """
    col = backend.count_per_element(col, "Frequency of elements")
    col = backend.to_list(col, "To 1 element collection")

    def build(value_freq_pairs):
        if not value_freq_pairs:
            return hist.Histogram(name, [])
        values, freqs = zip(*value_freq_pairs)
        return _frequencies_to_histogram(np.array(values), np.array(freqs),
                                         name)

    return backend.map(col, build, "To histogram")


def _compute_weighted_frequency_histogram(
        col, backend: pipeline_backend.PipelineBackend,
        name: hist.HistogramType):
    """Histogram from (value:int, weight:float) pairs (reference ``:81-102``)."""
    col = backend.sum_per_key(col, "Frequency of elements")
    col = backend.to_list(col, "To 1 element collection")

    def build(value_weight_pairs):
        if not value_weight_pairs:
            return hist.Histogram(name, [])
        values, weights = zip(*value_weight_pairs)
        freqs = np.rint(np.array(weights)).astype(np.int64)
        return _frequencies_to_histogram(np.array(values), freqs, name)

    return backend.map(col, build, "To histogram")


def _compute_float_histogram(col, backend: pipeline_backend.PipelineBackend,
                             name: hist.HistogramType):
    """Equal-width histogram of a collection of floats (reference ``:135-173``)."""
    col = backend.to_list(col, "To 1 element collection")
    return backend.map(col, lambda vals: _float_values_to_histogram(
        np.array(vals, dtype=np.float64), name), "To histogram")


def _list_to_contribution_histograms(
        histograms: List[hist.Histogram]) -> hist.DatasetHistograms:
    """Packs a list of named histograms into DatasetHistograms (ref ``:198-220``)."""
    by_type = {h.name: h for h in histograms}
    return hist.DatasetHistograms(
        by_type.get(hist.HistogramType.L0_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.L1_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.LINF_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.LINF_SUM_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.COUNT_PER_PARTITION),
        by_type.get(hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION),
    )


def _to_dataset_histograms(histogram_list,
                           backend: pipeline_backend.PipelineBackend):
    """Combines 1-element histogram collections into DatasetHistograms."""
    col = backend.flatten(histogram_list, "Histograms to one collection")
    col = backend.to_list(col, "Histograms to List")
    return backend.map(col, _list_to_contribution_histograms,
                       "To DatasetHistograms")


############## Raw datasets ##################################################


def _compute_l0_contributions_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """#distinct partitions per privacy id (col: distinct (pid, pk))."""
    col = backend.keys(col, "Drop partition id")
    col = backend.count_per_element(col, "Compute partitions per privacy id")
    col = backend.values(col, "Drop privacy id")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.L0_CONTRIBUTIONS)


def _compute_l1_contributions_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """#records per privacy id (col: (pid, pk) with duplicates)."""
    col = backend.keys(col, "Drop partition id")
    col = backend.count_per_element(col, "Compute records per privacy id")
    col = backend.values(col, "Drop privacy id")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.L1_CONTRIBUTIONS)


def _compute_linf_contributions_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """#rows per (pid, pk) pair."""
    col = backend.count_per_element(
        col, "Contributions per (privacy_id, partition)")
    col = backend.values(col, "Drop privacy id")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.LINF_CONTRIBUTIONS)


def _compute_linf_sum_contributions_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """Sum of values per (pid, pk) pair, equal-width float bins."""
    col = backend.sum_per_key(
        col, "Sum of contributions per (privacy_id, partition)")
    col = backend.values(col, "Drop keys")
    return _compute_float_histogram(col, backend,
                                    hist.HistogramType.LINF_SUM_CONTRIBUTIONS)


def _compute_partition_count_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """Total contribution count per partition."""
    col = backend.values(col, "Drop privacy keys")
    col = backend.count_per_element(col, "Count per partition")
    col = backend.values(col, "Drop partition key")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.COUNT_PER_PARTITION)


def _compute_partition_privacy_id_count_histogram(
        col, backend: pipeline_backend.PipelineBackend):
    """#privacy ids per partition (col: distinct (pid, pk))."""
    col = backend.values(col, "Drop privacy key")
    col = backend.count_per_element(col, "Privacy ids per partition")
    col = backend.values(col, "Drop partition key")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION)


def compute_dataset_histograms(col,
                               data_extractors: extractors.DataExtractors,
                               backend: pipeline_backend.PipelineBackend):
    """Computes all six dataset histograms (reference ``:420-474``).

    Returns a 1-element collection containing DatasetHistograms.
    """
    col_with_values = backend.map(
        col, lambda row: ((data_extractors.privacy_id_extractor(row),
                           data_extractors.partition_extractor(row)),
                          data_extractors.value_extractor(row)),
        "Extract ((privacy_id, partition_key), value)")
    col_with_values = backend.to_multi_transformable_collection(
        col_with_values)
    col = backend.keys(col_with_values, "Drop values")
    col = backend.to_multi_transformable_collection(col)
    col_distinct = backend.distinct(col, "Distinct (privacy_id, partition)")
    col_distinct = backend.to_multi_transformable_collection(col_distinct)

    return _to_dataset_histograms([
        _compute_l0_contributions_histogram(col_distinct, backend),
        _compute_l1_contributions_histogram(col, backend),
        _compute_linf_contributions_histogram(col, backend),
        _compute_linf_sum_contributions_histogram(col_with_values, backend),
        _compute_partition_count_histogram(col, backend),
        _compute_partition_privacy_id_count_histogram(col_distinct, backend),
    ], backend)


############## Pre-aggregated datasets #######################################
# Pre-aggregated rows are (partition_key, (count, sum, n_partitions,
# n_contributions)); see pre_aggregation.preaggregate.


def _compute_l0_contributions_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: (x[2], 1.0 / x[2]),
                            "Extract n_partitions")
    return _compute_weighted_frequency_histogram(
        col, backend, hist.HistogramType.L0_CONTRIBUTIONS)


def _compute_l1_contributions_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: (x[3], 1.0 / x[2]),
                            "Extract n_contributions")
    return _compute_weighted_frequency_histogram(
        col, backend, hist.HistogramType.L1_CONTRIBUTIONS)


def _compute_linf_contributions_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: x[0],
                            "Extract count per partition contribution")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.LINF_CONTRIBUTIONS)


def _compute_linf_sum_contributions_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: x[1],
                            "Extract sum per partition contribution")
    return _compute_float_histogram(col, backend,
                                    hist.HistogramType.LINF_SUM_CONTRIBUTIONS)


def _compute_partition_count_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.map_values(col, lambda x: x[0], "Extract count")
    col = backend.sum_per_key(col, "Sum per partition")
    col = backend.values(col, "Drop partition keys")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.COUNT_PER_PARTITION)


def _compute_partition_privacy_id_count_histogram_on_preaggregated_data(
        col, backend: pipeline_backend.PipelineBackend):
    col = backend.keys(col, "Extract partition keys")
    col = backend.count_per_element(col, "Count privacy IDs per partition")
    col = backend.values(col, "Drop partition keys")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION)


def compute_dataset_histograms_on_preaggregated_data(
        col, data_extractors: extractors.PreAggregateExtractors,
        backend: pipeline_backend.PipelineBackend):
    """All six histograms from pre-aggregated rows (reference ``:642-684``)."""
    col = backend.map(
        col, lambda row: (data_extractors.partition_extractor(row),
                          data_extractors.preaggregate_extractor(row)),
        "Extract (partition_key, preaggregate_data)")
    col = backend.to_multi_transformable_collection(col)

    return _to_dataset_histograms([
        _compute_l0_contributions_histogram_on_preaggregated_data(
            col, backend),
        _compute_l1_contributions_histogram_on_preaggregated_data(
            col, backend),
        _compute_linf_contributions_histogram_on_preaggregated_data(
            col, backend),
        _compute_linf_sum_contributions_histogram_on_preaggregated_data(
            col, backend),
        _compute_partition_count_histogram_on_preaggregated_data(
            col, backend),
        _compute_partition_privacy_id_count_histogram_on_preaggregated_data(
            col, backend),
    ], backend)


############## Columnar fast path ############################################


def compute_dataset_histograms_columnar(
        pids: np.ndarray,
        pks: np.ndarray,
        values: Optional[np.ndarray] = None) -> hist.DatasetHistograms:
    """All six histograms from columnar (pid, pk, value) arrays in one pass.

    TPU-first alternative to the collection pipeline: the ingestion path
    already has integer-encoded columns (columnar.encode), so the grouped
    counts reduce to np.unique/bincount over whole columns with no
    per-element Python. Semantics match compute_dataset_histograms.
    """
    pids = np.asarray(pids)
    pks = np.asarray(pks)
    has_values = values is not None
    if not has_values:
        values = np.zeros(pids.shape[0], dtype=np.float64)
    values = np.asarray(values, dtype=np.float64)

    # group by (pid, pk): contributions count + sum per pair
    pair_codes, pair_inverse = np.unique(
        np.stack([pids, pks], axis=1), axis=0, return_inverse=True)
    pair_counts = np.bincount(pair_inverse)
    pair_sums = np.bincount(pair_inverse, weights=values)
    pair_pids = pair_codes[:, 0]
    pair_pks = pair_codes[:, 1]

    # L0: #distinct partitions per pid
    _, l0_per_pid = np.unique(pair_pids, return_counts=True)
    # L1: #records per pid
    _, l1_per_pid = np.unique(pids, return_counts=True)
    # partition stats
    _, count_per_pk = np.unique(pks, return_counts=True)
    _, pid_count_per_pk = np.unique(pair_pks, return_counts=True)

    def int_hist(values_, name):
        uniq, freq = np.unique(values_, return_counts=True)
        return _frequencies_to_histogram(uniq, freq, name)

    return hist.DatasetHistograms(
        int_hist(l0_per_pid, hist.HistogramType.L0_CONTRIBUTIONS),
        int_hist(l1_per_pid, hist.HistogramType.L1_CONTRIBUTIONS),
        int_hist(pair_counts, hist.HistogramType.LINF_CONTRIBUTIONS),
        _float_values_to_histogram(
            pair_sums, hist.HistogramType.LINF_SUM_CONTRIBUTIONS)
        if has_values else None,
        int_hist(count_per_pk, hist.HistogramType.COUNT_PER_PARTITION),
        int_hist(pid_count_per_pk,
                 hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION),
    )
