"""Data extractors: how to pull (privacy_id, partition_key, value) out of rows.

Reference parity: pipeline_dp/data_extractors.py:5-37. In the TPU build these
callables run host-side during columnar encoding (see columnar.py); on device
the data is already struct-of-arrays.
"""

from dataclasses import dataclass
from typing import Callable, Optional


@dataclass
class DataExtractors:
    """Functions that extract the needed pieces of information from a row."""
    privacy_id_extractor: Optional[Callable] = None
    partition_extractor: Optional[Callable] = None
    value_extractor: Optional[Callable] = None


@dataclass
class PreAggregateExtractors:
    """Extractors for pre-aggregated data.

    Pre-aggregated rows have form (partition_key, preaggregate_data), where
    preaggregate_data = (count, sum, n_partitions, n_contributions) describes
    one privacy unit's contributions to that partition.
    """
    partition_extractor: Callable
    preaggregate_extractor: Callable


@dataclass
class MultiValueDataExtractors(DataExtractors):
    """Extractors with multiple value columns (each row yields a tuple of
    values); used for multi-column aggregations."""
    value_extractors: Optional[tuple] = None

    def __post_init__(self):
        if self.value_extractors is not None:
            self.value_extractor = lambda row: tuple(
                e(row) for e in self.value_extractors)
