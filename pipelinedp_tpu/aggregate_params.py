"""Declarative DP aggregation parameters, metric registry and enums.

Mirrors the semantic surface of the reference parameter layer
(/root/reference/pipeline_dp/aggregate_params.py:29-625): the same metrics,
noise kinds, mechanism types, partition-selection strategies, parameter
dataclasses and `__post_init__` validation rules — re-written for this
TPU-native framework (parameters here additionally feed static shapes /
traced scalars of the XLA aggregation kernels).
"""

import logging
import math
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from pipelinedp_tpu import input_validators


@dataclass
class Metric:
    """A DP metric, optionally parameterized (e.g. PERCENTILE(90)).

    Reference parity: pipeline_dp/aggregate_params.py:29-58.
    """
    name: str
    parameter: Optional[float] = None

    def __eq__(self, other: 'Metric') -> bool:
        if not isinstance(other, Metric):
            return False
        return self.name == other.name and self.parameter == other.parameter

    def __str__(self):
        if self.parameter is None:
            return self.name
        return f'{self.name}({self.parameter})'

    def __repr__(self):
        return self.__str__()

    def __hash__(self):
        return hash(str(self))

    @property
    def is_percentile(self):
        return self.name == 'PERCENTILE'


class Metrics:
    """Registry of the supported DP metrics (reference :61-72)."""
    COUNT = Metric('COUNT')
    PRIVACY_ID_COUNT = Metric('PRIVACY_ID_COUNT')
    SUM = Metric('SUM')
    MEAN = Metric('MEAN')
    VARIANCE = Metric('VARIANCE')
    VECTOR_SUM = Metric('VECTOR_SUM')

    @classmethod
    def PERCENTILE(cls, percentile_to_compute: float):
        return Metric('PERCENTILE', percentile_to_compute)


class NoiseKind(Enum):
    LAPLACE = 'laplace'
    GAUSSIAN = 'gaussian'

    def convert_to_mechanism_type(self) -> 'MechanismType':
        if self == NoiseKind.LAPLACE:
            return MechanismType.LAPLACE
        return MechanismType.GAUSSIAN


class MechanismType(Enum):
    LAPLACE = 'Laplace'
    GAUSSIAN = 'Gaussian'
    GENERIC = 'Generic'

    def to_noise_kind(self) -> NoiseKind:
        if self == MechanismType.LAPLACE:
            return NoiseKind.LAPLACE
        if self == MechanismType.GAUSSIAN:
            return NoiseKind.GAUSSIAN
        raise ValueError(f"MechanismType {self.value} can not be converted to "
                         f"NoiseKind")


class NormKind(Enum):
    Linf = "linf"
    L0 = "l0"
    L1 = "l1"
    L2 = "l2"


class PartitionSelectionStrategy(Enum):
    TRUNCATED_GEOMETRIC = 'Truncated Geometric'
    LAPLACE_THRESHOLDING = 'Laplace Thresholding'
    GAUSSIAN_THRESHOLDING = 'Gaussian Thresholding'


@dataclass
class CalculatePrivateContributionBoundsParams:
    """Parameters for DPEngine.calculate_private_contribution_bounds().

    Only COUNT / PRIVACY_ID_COUNT aggregations are supported downstream.
    Reference parity: pipeline_dp/aggregate_params.py:113-150.
    """
    aggregation_noise_kind: NoiseKind
    aggregation_eps: float
    aggregation_delta: float
    calculation_eps: float
    max_partitions_contributed_upper_bound: int

    def __post_init__(self):
        input_validators.validate_epsilon_delta(
            self.aggregation_eps, self.aggregation_delta,
            "CalculatePrivateContributionBoundsParams")
        if self.aggregation_noise_kind is None:
            raise ValueError("aggregation_noise_kind must be set.")
        if (self.aggregation_noise_kind == NoiseKind.GAUSSIAN and
                self.aggregation_delta == 0):
            raise ValueError(
                "The Gaussian noise requires that the aggregation_delta is "
                "greater than 0.")
        input_validators.validate_epsilon_delta(
            self.calculation_eps, 0, "CalculatePrivateContributionBoundsParams")
        _check_is_positive_int(self.max_partitions_contributed_upper_bound,
                               "max_partitions_contributed_upper_bound")


@dataclass
class PrivateContributionBounds:
    """DP-computed contribution bounds (reference :153-163)."""
    max_partitions_contributed: int


@dataclass
class AggregateParams:
    """Parameters of DPEngine.aggregate().

    Validation rules replicate the reference semantics
    (pipeline_dp/aggregate_params.py:166-365):
      - min_value/max_value and min_sum_per_partition/max_sum_per_partition
        must each be both-set-or-both-unset, and are mutually exclusive;
      - metrics requiring value bounds are rejected without them;
      - VECTOR_SUM is incompatible with scalar value metrics;
      - either max_contributions XOR both (max_partitions_contributed,
        max_contributions_per_partition) must be set.
    """
    metrics: List[Metric]
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    max_partitions_contributed: Optional[int] = None
    max_contributions_per_partition: Optional[int] = None
    max_contributions: Optional[int] = None
    budget_weight: float = 1
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    min_sum_per_partition: Optional[float] = None
    max_sum_per_partition: Optional[float] = None
    custom_combiners: Sequence['CustomCombiner'] = None
    vector_norm_kind: Optional[NormKind] = None
    vector_max_norm: Optional[float] = None
    vector_size: Optional[int] = None
    contribution_bounds_already_enforced: bool = False
    public_partitions_already_filtered: bool = False
    partition_selection_strategy: PartitionSelectionStrategy = (
        PartitionSelectionStrategy.TRUNCATED_GEOMETRIC)
    pre_threshold: Optional[int] = None

    @property
    def metrics_str(self) -> str:
        if self.custom_combiners:
            return (f"custom combiners="
                    f"{[c.metrics_names() for c in self.custom_combiners]}")
        if self.metrics:
            return f"metrics={[str(m) for m in self.metrics]}"
        return "metrics=[]"

    @property
    def bounds_per_contribution_are_set(self) -> bool:
        return self.min_value is not None and self.max_value is not None

    @property
    def bounds_per_partition_are_set(self) -> bool:
        return (self.min_sum_per_partition is not None and
                self.max_sum_per_partition is not None)

    def __post_init__(self):
        self._check_both_set_or_unset("min_value", "max_value")
        self._check_both_set_or_unset("min_sum_per_partition",
                                      "max_sum_per_partition")

        value_bound = self.min_value is not None
        partition_bound = self.min_sum_per_partition is not None

        if value_bound and partition_bound:
            raise ValueError(
                "min_value and min_sum_per_partition can not be both set.")

        if value_bound:
            self._check_range("min_value", "max_value")
        if partition_bound:
            self._check_range("min_sum_per_partition", "max_sum_per_partition")

        if self.metrics:
            if Metrics.VECTOR_SUM in self.metrics:
                if (Metrics.SUM in self.metrics or
                        Metrics.MEAN in self.metrics or
                        Metrics.VARIANCE in self.metrics):
                    raise ValueError(
                        "AggregateParams: vector sum can not be computed "
                        "together with scalar metrics such as sum, mean etc")
            elif partition_bound:
                allowed = {Metrics.SUM, Metrics.PRIVACY_ID_COUNT,
                           Metrics.COUNT}
                not_allowed = set(self.metrics).difference(allowed)
                if not_allowed:
                    raise ValueError(
                        f"AggregateParams: min_sum_per_partition is not "
                        f"compatible with metrics {not_allowed}. Please"
                        f"use min_value/max_value.")
            elif not partition_bound and not value_bound:
                allowed = {Metrics.PRIVACY_ID_COUNT, Metrics.COUNT}
                not_allowed = set(self.metrics).difference(allowed)
                if not_allowed:
                    raise ValueError(
                        f"AggregateParams: for metrics {not_allowed} "
                        f"bounds per partition are required (e.g. min_value,"
                        f"max_value).")

            if (self.contribution_bounds_already_enforced and
                    Metrics.PRIVACY_ID_COUNT in self.metrics):
                raise ValueError(
                    "AggregateParams: Cannot calculate PRIVACY_ID_COUNT when "
                    "contribution_bounds_already_enforced is set to True.")
        if self.custom_combiners:
            logging.warning("Warning: custom combiners are used. This is an "
                            "experimental feature. It might not work properly "
                            "and it might be changed or removed without any "
                            "notifications.")
        if self.metrics and self.custom_combiners:
            raise ValueError(
                "Custom combiners can not be used with standard metrics")
        if self.max_contributions is not None:
            _check_is_positive_int(self.max_contributions, "max_contributions")
            if ((self.max_partitions_contributed is not None) or
                    (self.max_contributions_per_partition is not None)):
                raise ValueError(
                    "AggregateParams: only one in max_contributions or "
                    "both max_partitions_contributed and "
                    "max_contributions_per_partition must be set")
        else:
            n_set = _count_not_none(self.max_partitions_contributed,
                                    self.max_contributions_per_partition)
            if n_set == 0:
                raise ValueError(
                    "AggregateParams: either max_contributions must be set or "
                    "both max_partitions_contributed and "
                    "max_contributions_per_partition must be set.")
            elif n_set == 1:
                raise ValueError("AggregateParams: either none or both "
                                 "max_partitions_contributed and "
                                 "max_contributions_per_partition must be set.")
            _check_is_positive_int(self.max_partitions_contributed,
                                   "max_partitions_contributed")
            _check_is_positive_int(self.max_contributions_per_partition,
                                   "max_contributions_per_partition")
        if self.pre_threshold is not None:
            _check_is_positive_int(self.pre_threshold, "pre_threshold")

    def _check_both_set_or_unset(self, name1: str, name2: str):
        v1, v2 = getattr(self, name1), getattr(self, name2)
        if (v1 is None) != (v2 is None):
            raise ValueError(
                f"AggregateParams: {name1} and {name2} should"
                f" be both set or both None.")

    def _check_range(self, min_name: str, max_name: str):
        for name in (min_name, max_name):
            value = getattr(self, name)
            if _not_a_proper_number(value):
                raise ValueError(
                    f"AggregateParams: {name} must be a finite number")
        if getattr(self, min_name) > getattr(self, max_name):
            raise ValueError(
                f"AggregateParams: {max_name} must be equal to or "
                f"greater than {min_name}")

    def __str__(self):
        return parameters_to_readable_string(self)


@dataclass
class SelectPartitionsParams:
    """Parameters of DPEngine.select_partitions() (reference :368-395)."""
    max_partitions_contributed: int
    budget_weight: float = 1
    partition_selection_strategy: PartitionSelectionStrategy = (
        PartitionSelectionStrategy.TRUNCATED_GEOMETRIC)
    pre_threshold: Optional[int] = None

    def __post_init__(self):
        if self.pre_threshold is not None:
            _check_is_positive_int(self.pre_threshold, "pre_threshold")

    def __str__(self):
        return "Private Partitions"


@dataclass
class SumParams:
    """Convenience params for DP sum (reference :398-430)."""
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False


@dataclass
class VarianceParams:
    """Convenience params for DP variance (reference :433-468)."""
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False


@dataclass
class MeanParams:
    """Convenience params for DP mean (reference :471-504)."""
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False


@dataclass
class CountParams:
    """Convenience params for DP count (reference :507-533)."""
    noise_kind: NoiseKind
    max_partitions_contributed: int
    max_contributions_per_partition: int
    partition_extractor: Callable
    budget_weight: float = 1
    contribution_bounds_already_enforced: bool = False


@dataclass
class PrivacyIdCountParams:
    """Convenience params for DP privacy-id count (reference :536-562)."""
    noise_kind: NoiseKind
    max_partitions_contributed: int
    partition_extractor: Callable
    budget_weight: float = 1
    contribution_bounds_already_enforced: bool = False


def _not_a_proper_number(num: Any) -> bool:
    return math.isnan(num) or math.isinf(num)


def _check_is_positive_int(num: Any, field_name: str) -> None:
    if not (_is_int(num) and num > 0):
        raise ValueError(
            f"{field_name} has to be positive integer, but {num} given.")


def _count_not_none(*args):
    return sum(1 for arg in args if arg is not None)


def _is_int(value: Any) -> bool:
    return isinstance(value, (int, np.integer))


def _append_if_present(obj: Any, property_name: str, n_spaces: int,
                       res: List[str]):
    if not hasattr(obj, property_name):
        return
    value = getattr(obj, property_name)
    if value is None:
        return
    res.append(" " * n_spaces + f"{property_name}={value}")


def parameters_to_readable_string(params,
                                  is_public_partition: Optional[bool] = None
                                 ) -> str:
    """Human-readable rendering used in Explain Computation reports
    (reference :594-625)."""
    result = [f"{type(params).__name__}:"]
    if hasattr(params, "metrics_str"):
        result.append(f" {params.metrics_str}")
    if hasattr(params, "noise_kind"):
        result.append(f" noise_kind={params.noise_kind.value}")
    if hasattr(params, "budget_weight"):
        result.append(f" budget_weight={params.budget_weight}")
    result.append(" Contribution bounding:")
    for name in ("max_partitions_contributed",
                 "max_contributions_per_partition", "max_contributions",
                 "min_value", "max_value", "min_sum_per_partition",
                 "max_sum_per_partition"):
        _append_if_present(params, name, 2, result)
    if getattr(params, "contribution_bounds_already_enforced", False):
        result.append("  contribution_bounds_already_enforced=True")
    for name in ("vector_max_norm", "vector_size", "vector_norm_kind"):
        _append_if_present(params, name, 2, result)

    if is_public_partition is not None:
        type_str = ("public"
                    if is_public_partition else "private") + " partitions"
        result.append(f" Partition selection: {type_str}")

    return "\n".join(result)
