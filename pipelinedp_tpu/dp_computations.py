"""DP numerics: sensitivity calculus, additive mechanisms, mean/variance.

Reference parity: pipeline_dp/dp_computations.py:29-761. The reference wraps
Google's C++ mechanisms via PyDP; here the numerics are native:

  * Gaussian calibration uses the *analytic Gaussian mechanism* (Balle & Wang
    2018): the exact delta(sigma) formula inverted by bisection — the same
    algorithm Google's library implements.
  * Host-side sampling uses numpy Generator; device-side sampling (the hot
    path) is fused into the XLA aggregation kernel (ops/noise.py) with
    counter-based per-partition keys.
  * The optional native C++ secure sampler (snapped geometric Laplace,
    native/dpcore) guards against floating-point attacks where required;
    distributional equivalence is validated by KS tests.
"""

import abc
import math
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple, Union

import numpy as np
from scipy.special import log_ndtr

from pipelinedp_tpu import aggregate_params
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu.aggregate_params import NoiseKind, NormKind

# Host-side mechanism RNG: created lazily with explicit entropy, never a
# module-import side effect — staticcheck's host-rng rule forbids
# module-global default_rng() instances because their seed is
# unobservable (a resumed job could not replay the same release and
# nothing would say so). Seedable AND injectable for tests.
_rng: Optional[np.random.Generator] = None


def seed_mechanism_rng(
        seed: "Union[None, int, np.random.Generator]") -> None:
    """Seeds (or injects) the host-side mechanism RNG."""
    global _rng
    _rng = (seed if isinstance(seed, np.random.Generator) else
            np.random.default_rng(seed))


def mechanism_rng() -> np.random.Generator:
    """The host-side mechanism generator, created on first use from an
    explicit fresh SeedSequence when no seed was injected."""
    global _rng
    if _rng is None:
        _rng = np.random.default_rng(np.random.SeedSequence())
    return _rng


# Secure-noise mode: host-side mechanisms sample snapped discrete noise from
# the native integer-only samplers (pipelinedp_tpu/native) instead of numpy
# floating-point draws — the counterpart of the reference's PyDP secure
# noise (SURVEY.md §2.4 row 1). Off by default: distributionally identical,
# but slower, and unavailable if the C++ library cannot be built.
_secure_noise = False


def use_secure_noise(enable: bool = True) -> None:
    """Enables snapped secure noise for host-side additive mechanisms.

    Raises RuntimeError if the native library is unavailable."""
    global _secure_noise
    if enable:
        from pipelinedp_tpu import native
        if not native.available():
            raise RuntimeError(
                "Secure noise requires the native DP primitives library "
                "(pipelinedp_tpu/native), which failed to build/load.")
    _secure_noise = enable


def secure_noise_enabled() -> bool:
    return _secure_noise


@dataclass
class ScalarNoiseParams:
    """Parameters for computing DP sum, count, mean, variance."""

    eps: float
    delta: float
    min_value: Optional[float]
    max_value: Optional[float]
    min_sum_per_partition: Optional[float]
    max_sum_per_partition: Optional[float]
    max_partitions_contributed: int
    max_contributions_per_partition: Optional[int]
    noise_kind: NoiseKind

    def __post_init__(self):
        assert (self.min_value is None) == (
            self.max_value is None
        ), "min_value and max_value should be both set or both None."
        assert (self.min_sum_per_partition is None) == (
            self.max_sum_per_partition is None
        ), ("min_sum_per_partition and max_sum_per_partition should be both "
            "set or both None.")

    def l0_sensitivity(self) -> int:
        return self.max_partitions_contributed

    @property
    def bounds_per_contribution_are_set(self) -> bool:
        return self.min_value is not None and self.max_value is not None

    @property
    def bounds_per_partition_are_set(self) -> bool:
        return (self.min_sum_per_partition is not None and
                self.max_sum_per_partition is not None)


def compute_squares_interval(min_value: float,
                             max_value: float) -> Tuple[float, float]:
    """Bounds of {x^2 : x in [min_value, max_value]}."""
    if min_value < 0 < max_value:
        return 0, max(min_value**2, max_value**2)
    return min_value**2, max_value**2


def compute_middle(min_value: float, max_value: float) -> float:
    """Overflow-safe midpoint of [min_value, max_value]."""
    return min_value + (max_value - min_value) / 2


def compute_l1_sensitivity(l0_sensitivity: float,
                           linf_sensitivity: float) -> float:
    return l0_sensitivity * linf_sensitivity


def compute_l2_sensitivity(l0_sensitivity: float,
                           linf_sensitivity: float) -> float:
    return math.sqrt(l0_sensitivity) * linf_sensitivity


def _norm_cdf(z: float) -> float:
    return 0.5 * math.erfc(-z / math.sqrt(2))


def gaussian_delta(sigma: float, eps: float, l2_sensitivity: float) -> float:
    """Exact delta of the Gaussian mechanism (Balle & Wang 2018, Thm. 8).

    delta = Phi(D/(2 sigma) - eps sigma/D) - e^eps Phi(-D/(2 sigma) - eps
    sigma/D) with D = l2_sensitivity.
    """
    d = l2_sensitivity
    a = d / (2 * sigma) - eps * sigma / d
    b = -d / (2 * sigma) - eps * sigma / d
    # The second term is e^eps * Phi(b) with Phi(b) astronomically small for
    # large eps — evaluate in log space to avoid math.exp overflow.
    log_term = eps + log_ndtr(b)
    second = math.exp(log_term) if log_term < 700 else math.inf
    return _norm_cdf(a) - second


def gaussian_sigma(eps: float,
                   delta: float,
                   l2_sensitivity: float,
                   tol: float = 1e-12) -> float:
    """Minimal sigma s.t. the Gaussian mechanism is (eps, delta)-DP.

    Analytic (exact) calibration: bisection on the monotone-decreasing
    gaussian_delta. Replaces PyDP GaussianMechanism.std
    (reference dp_computations.py:107-117).
    """
    if delta <= 0:
        raise ValueError("Gaussian mechanism requires delta > 0.")
    if delta >= 1:
        raise ValueError("delta must be < 1.")
    # Bracket sigma: start from the classic sqrt(2 ln(1.25/delta))/eps guess.
    hi = l2_sensitivity * math.sqrt(2 * math.log(1.25 / delta)) / eps + 1e-12
    while gaussian_delta(hi, eps, l2_sensitivity) > delta:
        hi *= 2
    lo = hi
    while gaussian_delta(lo, eps, l2_sensitivity) < delta and lo > 1e-300:
        lo /= 2
    for _ in range(200):
        mid = (lo + hi) / 2
        if gaussian_delta(mid, eps, l2_sensitivity) > delta:
            lo = mid
        else:
            hi = mid
        if hi - lo <= tol * hi:
            break
    return hi


def compute_sigma(eps: float, delta: float, l2_sensitivity: float) -> float:
    """Optimal Gaussian sigma (reference-parity alias of gaussian_sigma)."""
    return gaussian_sigma(eps, delta, l2_sensitivity)


def apply_laplace_mechanism(value: float, eps: float, l1_sensitivity: float):
    """value + Laplace(b = l1_sensitivity / eps) (reference :120-133)."""
    if _secure_noise:
        from pipelinedp_tpu import native
        return float(
            native.secure_laplace_add(np.asarray([float(value)]),
                                      l1_sensitivity / eps)[0])
    return value + mechanism_rng().laplace(0, l1_sensitivity / eps)


def apply_gaussian_mechanism(value: float, eps: float, delta: float,
                             l2_sensitivity: float):
    """value + N(0, sigma^2) with analytic sigma (reference :136-152)."""
    sigma = gaussian_sigma(eps, delta, l2_sensitivity)
    if _secure_noise:
        from pipelinedp_tpu import native
        return float(
            native.secure_gaussian_add(np.asarray([float(value)]), sigma)[0])
    return value + mechanism_rng().normal(0, sigma)


def _add_random_noise(value: float, eps: float, delta: float,
                      l0_sensitivity: float, linf_sensitivity: float,
                      noise_kind: NoiseKind) -> float:
    if noise_kind == NoiseKind.LAPLACE:
        return apply_laplace_mechanism(
            value, eps, compute_l1_sensitivity(l0_sensitivity,
                                               linf_sensitivity))
    if noise_kind == NoiseKind.GAUSSIAN:
        return apply_gaussian_mechanism(
            value, eps, delta,
            compute_l2_sensitivity(l0_sensitivity, linf_sensitivity))
    raise ValueError("Noise kind must be either Laplace or Gaussian.")


@dataclass
class AdditiveVectorNoiseParams:
    eps_per_coordinate: float
    delta_per_coordinate: float
    max_norm: float
    l0_sensitivity: float
    linf_sensitivity: float
    norm_kind: NormKind
    noise_kind: NoiseKind


def _clip_vector(vec: np.ndarray, max_norm: float, norm_kind: NormKind):
    kind = norm_kind.value
    if kind == "linf":
        return np.clip(vec, -max_norm, max_norm)
    if kind in ("l1", "l2"):
        order = int(kind[-1])
        vec_norm = np.linalg.norm(vec, ord=order)
        return vec * min(1, max_norm / vec_norm)
    raise NotImplementedError(
        f"Vector Norm of kind '{kind}' is not supported.")


def vector_noise_std(noise_params: AdditiveVectorNoiseParams) -> float:
    """Per-coordinate noise stddev of add_noise_vector.

    Shared by the host combiner path and the fused TPU kernel
    (executor.compute_noise_stds) so the two can never diverge on
    calibration.
    """
    if noise_params.noise_kind == NoiseKind.LAPLACE:
        l1 = compute_l1_sensitivity(noise_params.l0_sensitivity,
                                    noise_params.linf_sensitivity)
        return math.sqrt(2.0) * l1 / noise_params.eps_per_coordinate
    if noise_params.noise_kind == NoiseKind.GAUSSIAN:
        l2 = compute_l2_sensitivity(noise_params.l0_sensitivity,
                                    noise_params.linf_sensitivity)
        return gaussian_sigma(noise_params.eps_per_coordinate,
                              noise_params.delta_per_coordinate, l2)
    raise ValueError("Noise kind must be either Laplace or Gaussian.")


def add_noise_vector(vec: np.ndarray, noise_params: AdditiveVectorNoiseParams):
    """Clips `vec` to the norm ball and noises each coordinate
    (reference :198-230)."""
    vec = _clip_vector(vec, noise_params.max_norm, noise_params.norm_kind)
    return np.array([
        _add_random_noise(s, noise_params.eps_per_coordinate,
                          noise_params.delta_per_coordinate,
                          noise_params.l0_sensitivity,
                          noise_params.linf_sensitivity,
                          noise_params.noise_kind) for s in vec
    ])


def equally_split_budget(eps: float, delta: float, no_mechanisms: int):
    """Splits (eps, delta) into no_mechanisms shares that sum exactly
    (reference :233-261)."""
    if no_mechanisms <= 0:
        raise ValueError("The number of mechanisms must be a positive integer.")
    eps_used = delta_used = 0
    budgets = []
    for _ in range(no_mechanisms - 1):
        budget = (eps / no_mechanisms, delta / no_mechanisms)
        eps_used += budget[0]
        delta_used += budget[1]
        budgets.append(budget)
    budgets.append((eps - eps_used, delta - delta_used))
    return budgets


def _compute_mean_for_normalized_sum(dp_count: float, sum_: float,
                                     min_value: float, max_value: float,
                                     eps: float, delta: float,
                                     l0_sensitivity: float,
                                     max_contributions_per_partition: float,
                                     noise_kind: NoiseKind):
    """DP mean of a normalized sum via the DP count (reference :264-304)."""
    if min_value == max_value:
        return min_value
    middle = compute_middle(min_value, max_value)
    linf_sensitivity = max_contributions_per_partition * abs(middle - min_value)
    dp_normalized_sum = _add_random_noise(sum_, eps, delta, l0_sensitivity,
                                          linf_sensitivity, noise_kind)
    dp_count_clamped = max(1.0, dp_count)
    return dp_normalized_sum / dp_count_clamped


def compute_dp_var(count: int, normalized_sum: float,
                   normalized_sum_squares: float,
                   dp_params: ScalarNoiseParams):
    """DP (count, sum, mean, variance) from normalized moments
    (reference :307-366)."""
    ((count_eps, count_delta), (sum_eps, sum_delta),
     (sum_squares_eps,
      sum_squares_delta)) = equally_split_budget(dp_params.eps,
                                                 dp_params.delta, 3)
    l0_sensitivity = dp_params.l0_sensitivity()

    dp_count = _add_random_noise(count, count_eps, count_delta, l0_sensitivity,
                                 dp_params.max_contributions_per_partition,
                                 dp_params.noise_kind)

    dp_mean = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum, dp_params.min_value, dp_params.max_value,
        sum_eps, sum_delta, l0_sensitivity,
        dp_params.max_contributions_per_partition, dp_params.noise_kind)

    squares_min_value, squares_max_value = compute_squares_interval(
        dp_params.min_value, dp_params.max_value)

    dp_mean_squares = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum_squares, squares_min_value, squares_max_value,
        sum_squares_eps, sum_squares_delta, l0_sensitivity,
        dp_params.max_contributions_per_partition, dp_params.noise_kind)

    dp_var = dp_mean_squares - dp_mean**2
    if dp_params.min_value != dp_params.max_value:
        dp_mean += compute_middle(dp_params.min_value, dp_params.max_value)

    return dp_count, dp_mean * dp_count, dp_mean, dp_var


def noise_std(eps: float, delta: float, l0_sensitivity: float,
              linf_sensitivity: float, noise_kind: NoiseKind) -> float:
    """Noise stddev of the additive mechanism with the given budget and
    (l0, linf) sensitivities. Single source of truth for both the host
    mechanisms and the TPU executor's vectorized noise."""
    if linf_sensitivity == 0:
        return 0.0
    if noise_kind == NoiseKind.LAPLACE:
        b = compute_l1_sensitivity(l0_sensitivity, linf_sensitivity) / eps
        return b * math.sqrt(2)
    if noise_kind == NoiseKind.GAUSSIAN:
        l2 = compute_l2_sensitivity(l0_sensitivity, linf_sensitivity)
        return gaussian_sigma(eps, delta, l2)
    raise ValueError("Only Laplace and Gaussian noise is supported.")


def _compute_noise_std(linf_sensitivity: float,
                       dp_params: ScalarNoiseParams) -> float:
    """Noise std for the given linf sensitivity (reference :369-382)."""
    return noise_std(dp_params.eps, dp_params.delta,
                     dp_params.l0_sensitivity(), linf_sensitivity,
                     dp_params.noise_kind)


def compute_dp_var_noise_stds(eps: float, delta: float, l0: int, linf: int,
                              min_value: float, max_value: float,
                              noise_kind: NoiseKind) -> Tuple[float, float,
                                                              float]:
    """The three noise stddevs used by compute_dp_var's budget split
    (count, normalized sum, normalized sum of squares) — shared by the host
    path and the TPU executor."""
    (e1, d1), (e2, d2), (e3, d3) = equally_split_budget(eps, delta, 3)
    count_std = noise_std(e1, d1, l0, linf, noise_kind)
    mid = compute_middle(min_value, max_value)
    nsum_std = noise_std(e2, d2, l0, linf * abs(mid - min_value), noise_kind)
    sq_lo, sq_hi = compute_squares_interval(min_value, max_value)
    mid2 = compute_middle(sq_lo, sq_hi)
    nsum2_std = noise_std(e3, d3, l0, linf * abs(mid2 - sq_lo), noise_kind)
    return count_std, nsum_std, nsum2_std


def noise_sensitivity(l0_sensitivity: float, linf_sensitivity: float,
                      noise_kind: NoiseKind) -> float:
    """The norm sensitivity matching `noise_std`'s mechanism: l1 for
    Laplace, l2 for Gaussian (used for secure-noise grid calibration)."""
    if noise_kind == NoiseKind.LAPLACE:
        return compute_l1_sensitivity(l0_sensitivity, linf_sensitivity)
    if noise_kind == NoiseKind.GAUSSIAN:
        return compute_l2_sensitivity(l0_sensitivity, linf_sensitivity)
    raise ValueError("Only Laplace and Gaussian noise is supported.")


def compute_dp_var_noise_sensitivities(
        l0: int, linf: int, min_value: float, max_value: float,
        noise_kind: NoiseKind) -> Tuple[float, float, float]:
    """Per-slot norm sensitivities matching compute_dp_var_noise_stds."""
    mid = compute_middle(min_value, max_value)
    sq_lo, sq_hi = compute_squares_interval(min_value, max_value)
    mid2 = compute_middle(sq_lo, sq_hi)
    return (noise_sensitivity(l0, linf, noise_kind),
            noise_sensitivity(l0, linf * abs(mid - min_value), noise_kind),
            noise_sensitivity(l0, linf * abs(mid2 - sq_lo), noise_kind))


def vector_noise_sensitivity(
        noise_params: AdditiveVectorNoiseParams) -> float:
    """Per-coordinate norm sensitivity matching vector_noise_std."""
    return noise_sensitivity(noise_params.l0_sensitivity,
                             noise_params.linf_sensitivity,
                             noise_params.noise_kind)


def compute_dp_count_noise_std(dp_params: ScalarNoiseParams) -> float:
    return _compute_noise_std(dp_params.max_contributions_per_partition,
                              dp_params)


def compute_dp_sum_noise_std(dp_params: ScalarNoiseParams) -> float:
    linf = max(abs(dp_params.min_sum_per_partition),
               abs(dp_params.max_sum_per_partition))
    return _compute_noise_std(linf, dp_params)


class AdditiveMechanism(abc.ABC):
    """Base class for additive DP mechanisms (Laplace, Gaussian)."""

    @abc.abstractmethod
    def add_noise(self, value: Union[int, float]) -> float:
        """Anonymizes value by adding noise."""

    @property
    @abc.abstractmethod
    def noise_kind(self) -> NoiseKind:
        pass

    @property
    @abc.abstractmethod
    def noise_parameter(self) -> float:
        """Noise distribution parameter (b for Laplace, sigma for Gauss)."""

    @property
    @abc.abstractmethod
    def std(self) -> float:
        """Noise standard deviation."""

    @property
    @abc.abstractmethod
    def sensitivity(self) -> float:
        """Mechanism sensitivity."""

    @abc.abstractmethod
    def describe(self) -> str:
        """Description for explain computation reports."""


class LaplaceMechanism(AdditiveMechanism):
    """Laplace mechanism: noise b = l1_sensitivity / eps."""

    def __init__(self, epsilon: float, l1_sensitivity: float):
        self._epsilon = epsilon
        self._l1_sensitivity = l1_sensitivity

    @classmethod
    def create_from_epsilon(cls, epsilon: float,
                            l1_sensitivity: float) -> 'LaplaceMechanism':
        return LaplaceMechanism(epsilon, l1_sensitivity)

    @classmethod
    def create_from_std_deviation(cls, normalized_stddev: float,
                                  l1_sensitivity: float) -> 'LaplaceMechanism':
        """normalized_stddev = stddev / l1_sensitivity (PLD accounting)."""
        b = normalized_stddev / math.sqrt(2)
        return LaplaceMechanism(1 / b, l1_sensitivity)

    def add_noise(self, value: Union[int, float]) -> float:
        if _secure_noise:
            from pipelinedp_tpu import native
            return float(
                native.secure_laplace_add(np.asarray([float(value)]),
                                          self.noise_parameter)[0])
        return float(value) + mechanism_rng().laplace(0, self.noise_parameter)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def noise_parameter(self) -> float:
        return self._l1_sensitivity / self._epsilon

    @property
    def std(self) -> float:
        return self.noise_parameter * math.sqrt(2)

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.LAPLACE

    @property
    def sensitivity(self) -> float:
        return self._l1_sensitivity

    def describe(self) -> str:
        return (f"Laplace mechanism:  parameter={self.noise_parameter}  eps="
                f"{self._epsilon}  l1_sensitivity={self.sensitivity}")


class GaussianMechanism(AdditiveMechanism):
    """Gaussian mechanism with analytic (optimal) sigma calibration."""

    def __init__(self,
                 sigma: float,
                 l2_sensitivity: float,
                 epsilon: float = 0.0,
                 delta: float = 0.0):
        self._sigma = sigma
        self._l2_sensitivity = l2_sensitivity
        self._epsilon = epsilon
        self._delta = delta

    @classmethod
    def create_from_epsilon_delta(cls, epsilon: float, delta: float,
                                  l2_sensitivity: float) -> 'GaussianMechanism':
        sigma = gaussian_sigma(epsilon, delta, l2_sensitivity)
        return GaussianMechanism(sigma,
                                 l2_sensitivity,
                                 epsilon=epsilon,
                                 delta=delta)

    @classmethod
    def create_from_std_deviation(cls, normalized_stddev: float,
                                  l2_sensitivity: float) -> 'GaussianMechanism':
        """normalized_stddev = stddev / l2_sensitivity (PLD accounting)."""
        return GaussianMechanism(normalized_stddev * l2_sensitivity,
                                 l2_sensitivity)

    def add_noise(self, value: Union[int, float]) -> float:
        if _secure_noise:
            from pipelinedp_tpu import native
            return float(
                native.secure_gaussian_add(np.asarray([float(value)]),
                                           self._sigma)[0])
        return float(value) + mechanism_rng().normal(0, self._sigma)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def delta(self) -> float:
        return self._delta

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.GAUSSIAN

    @property
    def noise_parameter(self) -> float:
        return self._sigma

    @property
    def std(self) -> float:
        return self._sigma

    @property
    def sensitivity(self) -> float:
        return self._l2_sensitivity

    def describe(self) -> str:
        if self._epsilon > 0:
            eps_delta_str = f"eps={self._epsilon}  delta={self._delta}  "
        else:
            eps_delta_str = ""
        return (f"Gaussian mechanism:  parameter={self.noise_parameter}"
                f"  {eps_delta_str}l2_sensitivity={self.sensitivity}")


class MeanMechanism:
    """DP mean as DP(normalized sum) / DP(count) + mid (reference :541-576).

    normalized_sum = sum(x_i - mid) has linf sensitivity
    (max_value - min_value)/2 * max_contributions_per_partition, smaller than
    the raw sum's max(|min|, |max|) — a strict utility win.
    """

    def __init__(self, range_middle: float, count_mechanism: AdditiveMechanism,
                 sum_mechanism: AdditiveMechanism):
        self._range_middle = range_middle
        self._count_mechanism = count_mechanism
        self._sum_mechanism = sum_mechanism

    def compute_mean(self, count: int, normalized_sum: float):
        dp_count = self._count_mechanism.add_noise(count)
        denominator = max(1.0, dp_count)
        dp_normalized_sum = self._sum_mechanism.add_noise(normalized_sum)
        dp_mean = self._range_middle + dp_normalized_sum / denominator
        dp_sum = dp_mean * dp_count
        return dp_count, dp_sum, dp_mean

    @property
    def count_mechanism(self) -> AdditiveMechanism:
        return self._count_mechanism

    @property
    def sum_mechanism(self) -> AdditiveMechanism:
        return self._sum_mechanism

    @property
    def range_middle(self) -> float:
        return self._range_middle

    def describe(self) -> str:
        return (f"    a. Computed 'normalized_sum' = sum of (value - "
                f"{self._range_middle})\n"
                f"    b. Applied to 'count' {self._count_mechanism.describe()}\n"
                f"    c. Applied to 'normalized_sum' "
                f"{self._sum_mechanism.describe()}")


@dataclass
class Sensitivities:
    """Sensitivities of an additive DP mechanism, with consistency checks
    (reference :579-619)."""
    l0: Optional[int] = None
    linf: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None

    def __post_init__(self):

        def check_is_positive(num: Any, name: str):
            if num is not None and num <= 0:
                raise ValueError(f"{name} must be positive, but {num} given.")

        check_is_positive(self.l0, "L0")
        check_is_positive(self.linf, "Linf")
        check_is_positive(self.l1, "L1")
        check_is_positive(self.l2, "L2")

        if (self.l0 is None) != (self.linf is None):
            raise ValueError("l0 and linf sensitivities must be either both set"
                             " or both unset.")

        if self.l0 is not None and self.linf is not None:
            l1 = compute_l1_sensitivity(self.l0, self.linf)
            if self.l1 is None:
                self.l1 = l1
            elif abs(l1 - self.l1) > 1e-12:
                raise ValueError(f"L1={self.l1} != L0*Linf={l1}")

            l2 = compute_l2_sensitivity(self.l0, self.linf)
            if self.l2 is None:
                self.l2 = l2
            elif abs(l2 - self.l2) > 1e-12:
                raise ValueError(f"L2={self.l2} != sqrt(L0)*Linf={l2}")


def create_additive_mechanism(mechanism_spec: budget_accounting.MechanismSpec,
                              sensitivities: Sensitivities
                             ) -> AdditiveMechanism:
    """AdditiveMechanism from a (budget-finalized) spec (reference :622-647)."""
    noise_kind = mechanism_spec.mechanism_type.to_noise_kind()
    if noise_kind == NoiseKind.LAPLACE:
        if sensitivities.l1 is None:
            raise ValueError("L1 or (L0 and Linf) sensitivities must be set for"
                             " Laplace mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            return LaplaceMechanism.create_from_std_deviation(
                mechanism_spec.noise_standard_deviation, sensitivities.l1)
        return LaplaceMechanism.create_from_epsilon(mechanism_spec.eps,
                                                    sensitivities.l1)

    if noise_kind == NoiseKind.GAUSSIAN:
        if sensitivities.l2 is None:
            raise ValueError("L2 or (L0 and Linf) sensitivities must be set for"
                             " Gaussian mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            return GaussianMechanism.create_from_std_deviation(
                mechanism_spec.noise_standard_deviation, sensitivities.l2)
        return GaussianMechanism.create_from_epsilon_delta(
            mechanism_spec.eps, mechanism_spec.delta, sensitivities.l2)

    raise AssertionError(f"{noise_kind} not supported.")


def create_mean_mechanism(
        range_middle: float, count_spec: budget_accounting.MechanismSpec,
        count_sensitivities: Sensitivities,
        normalized_sum_spec: budget_accounting.MechanismSpec,
        normalized_sum_sensitivities: Sensitivities) -> MeanMechanism:
    return MeanMechanism(
        range_middle,
        create_additive_mechanism(count_spec, count_sensitivities),
        create_additive_mechanism(normalized_sum_spec,
                                  normalized_sum_sensitivities))


# ---------------------------------------------------------------------------
# Discrete / snapped mechanisms: floating-point-safe noise.
#
# The continuous mechanisms above sample IEEE doubles, whose uneven value
# grid leaks information (Mironov, CCS 2012): the set of reachable outputs
# depends on the true value, so an attacker observing the low-order bits of
# a release can distinguish neighbors the epsilon claims are indistinguishable.
# The mechanisms below release ONLY values on a declared grid:
#
#   * GeometricMechanism — integer-valued two-sided geometric noise (the
#     discrete Laplace) for counts; every release is an exact integer.
#   * SnappedLaplaceMechanism / SnappedGaussianMechanism — clamp -> noise ->
#     round to a power-of-two grid g for real-valued sums. Snapping moves a
#     release by at most g/2, so two neighbors' snapped outputs can differ
#     by up to Delta + g; calibration therefore widens the sensitivity to
#     Delta + g (the same conservative accounting ops/secure_noise.py applies
#     to the on-device tables), so the MechanismSpec's granted epsilon stays
#     a sound upper bound — the snap costs a ~g/Delta utility factor, never
#     budget.
#
# Determinism: bound to a threefry key (the same key family the device
# kernels use, executor.make_noise_key), draws come from counter-folded
# jax.random.bits u32 words assembled to 64-bit uniforms on the host —
# bit-identical per (seed, job, draw index) with or without jax_enable_x64,
# replayable after resume. Unbound mechanisms fall back to mechanism_rng().
# ---------------------------------------------------------------------------

# Default snapping grid: pow2_ceil(noise scale) * 2**-_SNAP_FRACTION_BITS —
# a relative snap displacement of ~2**-17 of the noise scale, so the
# Delta + g widening is invisible at common budgets unless snap_grid_bits
# explicitly coarsens the grid.
_SNAP_FRACTION_BITS = 16

# Clamp bound for snapped releases: the largest magnitude at which
# round-to-grid is still exact in float64 (53-bit significand). Releases
# beyond it would leave the declared grid silently; clamping is the
# fail-closed alternative.
_SNAP_CLAMP_GRID_UNITS = float(1 << 52)


def _pow2_round_up(x: float) -> float:
    return 2.0 ** math.ceil(math.log2(x))


def _threefry_uniforms(key, n: int, draw_index: int) -> np.ndarray:
    """n uniforms in (0, 1) from a threefry key and a draw counter.

    64 bits per uniform, assembled from two u32 words on the host so the
    stream is identical whether or not jax_enable_x64 is on. The +0.5
    offset keeps draws strictly inside (0, 1) — the inverse CDFs below
    take logs.
    """
    import jax
    sub = jax.random.fold_in(key, draw_index)
    words = np.asarray(jax.random.bits(sub, (2 * n,), np.uint32)).astype(
        np.uint64)  # staticcheck: disable=host-transfer — O(draws) scalar noise words, the host mechanism path
    u64 = (words[0::2] << np.uint64(32)) | words[1::2]
    return (u64.astype(np.float64) + 0.5) * (2.0 ** -64)


class _KeyedDrawMixin:
    """Counter-folded deterministic uniforms shared by the discrete
    mechanisms. bind_key() makes every later draw a pure function of
    (key, draw index); unbound, draws come from mechanism_rng()."""

    _key = None
    _draws = 0

    def bind_key(self, key) -> None:
        self._key = key
        self._draws = 0

    def _uniforms(self, n: int) -> np.ndarray:
        if self._key is not None:
            u = _threefry_uniforms(self._key, n, self._draws)
            self._draws += 1
            return u
        return mechanism_rng().random(n)


class GeometricMechanism(_KeyedDrawMixin, AdditiveMechanism):
    """Two-sided geometric (discrete Laplace) mechanism for counts.

    P(Z = z) proportional to alpha**|z| with alpha = exp(-eps / Delta):
    the integer-valued analogue of Laplace, eps-DP for integer-valued
    queries with (integer) l1 sensitivity Delta. Sampled as the
    difference of two iid geometric variables on {0, 1, ...} via exact
    inverse CDF — every release is an exact integer, grid step 1.
    """

    def __init__(self, epsilon: float, l1_sensitivity: float, key=None):
        self._epsilon = epsilon
        # A fractional l1 is rounded UP: alpha = exp(-eps/ceil(Delta))
        # over-noises rather than under-noises.
        self._l1_sensitivity = float(math.ceil(l1_sensitivity))
        if key is not None:
            self.bind_key(key)

    @classmethod
    def create_from_epsilon(cls, epsilon: float, l1_sensitivity: float,
                            key=None) -> 'GeometricMechanism':
        return GeometricMechanism(epsilon, l1_sensitivity, key=key)

    @property
    def alpha(self) -> float:
        return math.exp(-self._epsilon / self._l1_sensitivity)

    def add_noise(self, value: Union[int, float]) -> float:
        a = self.alpha
        u1, u2 = self._uniforms(2)
        if a <= 0.0:
            g1 = g2 = 0  # eps/Delta past exp underflow: noise is 0 w.p. ~1
        else:
            log_a = math.log(a)
            g1 = int(math.floor(math.log(u1) / log_a))
            g2 = int(math.floor(math.log(u2) / log_a))
        from pipelinedp_tpu.runtime import telemetry as rt_telemetry
        rt_telemetry.record("snapped_releases")
        return float(int(round(value)) + g1 - g2)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def grid(self) -> float:
        return 1.0

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.LAPLACE

    @property
    def noise_parameter(self) -> float:
        return self.alpha

    @property
    def std(self) -> float:
        a = self.alpha
        return math.sqrt(2.0 * a) / (1.0 - a)

    @property
    def sensitivity(self) -> float:
        return self._l1_sensitivity

    def describe(self) -> str:
        return (f"Geometric (discrete Laplace) mechanism:  alpha="
                f"{self.alpha}  eps={self._epsilon}  l1_sensitivity="
                f"{self.sensitivity}  grid=1")


class _SnappedMechanism(_KeyedDrawMixin, AdditiveMechanism):
    """Shared clamp -> noise -> round-to-grid release path."""

    _grid: float

    def _snap(self, noisy: float) -> float:
        g = self._grid
        bound = _SNAP_CLAMP_GRID_UNITS * g
        clamped = min(max(noisy, -bound), bound)
        # g is a power of two, so x/g and the re-multiply are exact: the
        # release lands EXACTLY on the declared grid.
        snapped = round(clamped / g) * g
        from pipelinedp_tpu.runtime import telemetry as rt_telemetry
        rt_telemetry.record("snapped_releases")
        return snapped

    @property
    def grid(self) -> float:
        return self._grid


class SnappedLaplaceMechanism(_SnappedMechanism):
    """Snapped Laplace: clamp -> Laplace noise -> round to power-of-two grid.

    The grid g = pow2_ceil(b) * 2**-16 (floored at 2**snap_grid_bits when
    given); the scale is calibrated against the widened sensitivity
    Delta + g, so the granted epsilon bounds the snapped release's
    privacy loss.
    """

    def __init__(self, epsilon: float, l1_sensitivity: float,
                 snap_grid_bits: Optional[int] = None, key=None):
        self._epsilon = epsilon
        self._raw_sensitivity = l1_sensitivity
        base_b = l1_sensitivity / epsilon
        g = _pow2_round_up(base_b) * 2.0 ** -_SNAP_FRACTION_BITS
        if snap_grid_bits is not None:
            g = max(g, 2.0 ** int(snap_grid_bits))
        self._grid = g
        self._l1_sensitivity = l1_sensitivity + g  # snap widening
        self._b = self._l1_sensitivity / epsilon
        if key is not None:
            self.bind_key(key)

    def add_noise(self, value: Union[int, float]) -> float:
        (u,) = self._uniforms(1)
        # Laplace inverse CDF on one uniform in (0, 1).
        if u < 0.5:
            noise = self._b * math.log(2.0 * u)
        else:
            noise = -self._b * math.log(2.0 * (1.0 - u))
        return self._snap(float(value) + noise)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.LAPLACE

    @property
    def noise_parameter(self) -> float:
        return self._b

    @property
    def std(self) -> float:
        return self._b * math.sqrt(2)

    @property
    def sensitivity(self) -> float:
        return self._l1_sensitivity

    def describe(self) -> str:
        return (f"Snapped Laplace mechanism:  parameter={self._b}  eps="
                f"{self._epsilon}  l1_sensitivity={self._l1_sensitivity} "
                f"(raw {self._raw_sensitivity} + grid)  grid={self._grid}")


class SnappedGaussianMechanism(_SnappedMechanism):
    """Snapped Gaussian: clamp -> Gaussian noise -> round to power-of-two
    grid, sigma calibrated (analytic Gaussian mechanism) against the
    widened sensitivity Delta + g."""

    def __init__(self, epsilon: float, delta: float, l2_sensitivity: float,
                 snap_grid_bits: Optional[int] = None, key=None):
        self._epsilon = epsilon
        self._delta = delta
        self._raw_sensitivity = l2_sensitivity
        base_sigma = gaussian_sigma(epsilon, delta, l2_sensitivity)
        g = _pow2_round_up(base_sigma) * 2.0 ** -_SNAP_FRACTION_BITS
        if snap_grid_bits is not None:
            g = max(g, 2.0 ** int(snap_grid_bits))
        self._grid = g
        self._l2_sensitivity = l2_sensitivity + g  # snap widening
        self._sigma = gaussian_sigma(epsilon, delta, self._l2_sensitivity)
        if key is not None:
            self.bind_key(key)

    @classmethod
    def create_from_std_deviation(cls, normalized_stddev: float,
                                  l2_sensitivity: float,
                                  snap_grid_bits: Optional[int] = None,
                                  key=None) -> 'SnappedGaussianMechanism':
        """normalized_stddev = stddev / l2_sensitivity (PLD accounting).

        Sigma is widened by the same Delta -> Delta + g factor the
        eps/delta path gets from recalibration, so the PLD-accounted
        noise-to-sensitivity ratio is preserved for the snapped query.
        """
        sigma = normalized_stddev * l2_sensitivity
        mech = cls.__new__(cls)
        mech._epsilon = 0.0
        mech._delta = 0.0
        mech._raw_sensitivity = l2_sensitivity
        g = _pow2_round_up(sigma) * 2.0 ** -_SNAP_FRACTION_BITS
        if snap_grid_bits is not None:
            g = max(g, 2.0 ** int(snap_grid_bits))
        mech._grid = g
        mech._l2_sensitivity = l2_sensitivity + g
        mech._sigma = sigma * mech._l2_sensitivity / l2_sensitivity
        if key is not None:
            mech.bind_key(key)
        return mech

    def add_noise(self, value: Union[int, float]) -> float:
        u1, u2 = self._uniforms(2)
        # Box-Muller on two uniforms in (0, 1): exact standard normal.
        z = math.sqrt(-2.0 * math.log(u1)) * math.cos(2.0 * math.pi * u2)
        return self._snap(float(value) + self._sigma * z)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def delta(self) -> float:
        return self._delta

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.GAUSSIAN

    @property
    def noise_parameter(self) -> float:
        return self._sigma

    @property
    def std(self) -> float:
        return self._sigma

    @property
    def sensitivity(self) -> float:
        return self._l2_sensitivity

    def describe(self) -> str:
        return (f"Snapped Gaussian mechanism:  parameter={self._sigma}  eps="
                f"{self._epsilon}  delta={self._delta}  l2_sensitivity="
                f"{self._l2_sensitivity} (raw {self._raw_sensitivity} + "
                f"grid)  grid={self._grid}")


def create_discrete_mechanism(mechanism_spec: budget_accounting.MechanismSpec,
                              sensitivities: Sensitivities,
                              *,
                              value_is_integer: bool = False,
                              snap_grid_bits: Optional[int] = None,
                              key=None) -> AdditiveMechanism:
    """Floating-point-safe AdditiveMechanism from a budget-finalized spec.

    The discrete counterpart of create_additive_mechanism: same
    MechanismSpec/Sensitivities inputs, same budget accounting (the
    spec's granted epsilon/delta remain sound upper bounds — the snap
    widening is absorbed into the noise scale, not charged as extra
    budget), but every release lands on a declared grid. Integer-valued
    Laplace queries (value_is_integer=True, e.g. COUNT) get the
    geometric mechanism on grid 1; real-valued queries get the snapped
    mechanism of the spec's noise kind. `key` (a threefry PRNGKey) makes
    the draw stream deterministic per (seed, job); snap_grid_bits floors
    the snapping grid at 2**snap_grid_bits.
    """
    noise_kind = mechanism_spec.mechanism_type.to_noise_kind()
    if noise_kind == NoiseKind.LAPLACE:
        if sensitivities.l1 is None:
            raise ValueError("L1 or (L0 and Linf) sensitivities must be set "
                             "for the geometric/snapped Laplace mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            # normalized_stddev = std / Delta and b = Delta/eps, so
            # eps = sqrt(2) / normalized_stddev (same inversion as
            # LaplaceMechanism.create_from_std_deviation).
            eps = math.sqrt(2.0) / mechanism_spec.noise_standard_deviation
        else:
            eps = mechanism_spec.eps
        if value_is_integer:
            return GeometricMechanism(eps, sensitivities.l1, key=key)
        return SnappedLaplaceMechanism(eps, sensitivities.l1,
                                       snap_grid_bits=snap_grid_bits, key=key)

    if noise_kind == NoiseKind.GAUSSIAN:
        if sensitivities.l2 is None:
            raise ValueError("L2 or (L0 and Linf) sensitivities must be set "
                             "for the snapped Gaussian mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            return SnappedGaussianMechanism.create_from_std_deviation(
                mechanism_spec.noise_standard_deviation, sensitivities.l2,
                snap_grid_bits=snap_grid_bits, key=key)
        return SnappedGaussianMechanism(mechanism_spec.eps,
                                        mechanism_spec.delta,
                                        sensitivities.l2,
                                        snap_grid_bits=snap_grid_bits,
                                        key=key)

    raise AssertionError(f"{noise_kind} not supported.")


class ExponentialMechanism:
    """Exponential mechanism for DP parameter choice (reference :662-716)."""

    class ScoringFunction(abc.ABC):
        """Scoring function for the exponential mechanism."""

        @abc.abstractmethod
        def score(self, k) -> float:
            """The higher the score, the likelier `k` is chosen."""

        @property
        @abc.abstractmethod
        def global_sensitivity(self) -> float:
            pass

        @property
        @abc.abstractmethod
        def is_monotonic(self) -> bool:
            """Whether score(D, k) is monotonic in the dataset D."""

    def __init__(self, scoring_function: 'ScoringFunction') -> None:
        self._scoring_function = scoring_function

    def apply(self,
              eps: float,
              inputs_to_score_col: List[Any],
              scores: Optional[np.ndarray] = None) -> Any:
        """Samples one input with probability proportional to
        exp(eps*score/sensitivity) for monotonic scoring functions, and
        exp(eps*score/(2*sensitivity)) otherwise. `scores` may carry
        precomputed (vectorized) scores for all inputs; otherwise score()
        is called per input."""
        probs = self._calculate_probabilities(eps, inputs_to_score_col, scores)
        index = mechanism_rng().choice(len(inputs_to_score_col), p=probs)
        return inputs_to_score_col[index]

    def _calculate_probabilities(self,
                                 eps: float,
                                 inputs_to_score_col: List[Any],
                                 scores: Optional[np.ndarray] = None):
        if scores is None:
            scores = np.array(
                [self._scoring_function.score(k) for k in inputs_to_score_col],
                dtype=np.float64)
        else:
            scores = np.asarray(scores, dtype=np.float64)
        denominator = self._scoring_function.global_sensitivity
        if not self._scoring_function.is_monotonic:
            denominator *= 2
        # Stabilized softmax.
        logits = scores * eps / denominator
        logits -= logits.max()
        weights = np.exp(logits)
        return weights / weights.sum()


def compute_sensitivities_for_count(
        params: aggregate_params.AggregateParams) -> Sensitivities:
    if params.max_contributions is not None:
        return Sensitivities(l1=params.max_contributions,
                             l2=params.max_contributions)
    return Sensitivities(l0=params.max_partitions_contributed,
                         linf=params.max_contributions_per_partition)


def compute_sensitivities_for_privacy_id_count(
        params: aggregate_params.AggregateParams) -> Sensitivities:
    if params.max_contributions is not None:
        return Sensitivities(l1=params.max_contributions,
                             l2=math.sqrt(params.max_contributions))
    return Sensitivities(l0=params.max_partitions_contributed, linf=1)


def compute_sensitivities_for_sum(
        params: aggregate_params.AggregateParams) -> Sensitivities:
    l0_sensitivity = params.max_partitions_contributed
    if params.bounds_per_contribution_are_set:
        max_abs_val = max(abs(params.min_value), abs(params.max_value))
        if params.max_contributions:
            l1_l2 = max_abs_val * params.max_contributions
            return Sensitivities(l1=l1_l2, l2=l1_l2)
        linf_sensitivity = max_abs_val * params.max_contributions_per_partition
    else:
        linf_sensitivity = max(abs(params.min_sum_per_partition),
                               abs(params.max_sum_per_partition))
    return Sensitivities(l0=l0_sensitivity, linf=linf_sensitivity)


def compute_sensitivities_for_normalized_sum(
        params: aggregate_params.AggregateParams) -> Sensitivities:
    max_abs_value = (params.max_value - params.min_value) / 2
    if params.max_contributions:
        l1_l2 = max_abs_value * params.max_contributions
        return Sensitivities(l1=l1_l2, l2=l1_l2)
    return Sensitivities(l0=params.max_partitions_contributed,
                         linf=max_abs_value *
                         params.max_contributions_per_partition)
