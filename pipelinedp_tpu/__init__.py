"""pipelinedp-tpu: a TPU-native framework for differentially-private
aggregation over large keyed datasets.

Same capability surface as PipelineDP (reference: pipeline_dp/__init__.py),
re-designed TPU-first: the aggregation hot path (contribution bounding,
per-partition combining, partition selection, noise) runs as one fused
JAX/XLA program over columnar sharded arrays; budget accounting and report
generation stay host-side.
"""

from pipelinedp_tpu.aggregate_params import (
    AggregateParams,
    CalculatePrivateContributionBoundsParams,
    CountParams,
    MeanParams,
    Metric,
    Metrics,
    MechanismType,
    NoiseKind,
    NormKind,
    PartitionSelectionStrategy,
    PrivacyIdCountParams,
    PrivateContributionBounds,
    SelectPartitionsParams,
    SumParams,
    VarianceParams,
)
from pipelinedp_tpu.budget_accounting import (
    Budget,
    BudgetAccountant,
    MechanismSpec,
    NaiveBudgetAccountant,
    PLDBudgetAccountant,
)
from pipelinedp_tpu.data_extractors import (
    DataExtractors,
    MultiValueDataExtractors,
    PreAggregateExtractors,
)
from pipelinedp_tpu.report_generator import ExplainComputationReport
from pipelinedp_tpu.combiners import Combiner, CustomCombiner
from pipelinedp_tpu.dp_engine import DPEngine
from pipelinedp_tpu.private_collection import (
    CombinePerKeyParams,
    PrivateCollection,
    PrivateCombineFn,
    make_private,
)
from pipelinedp_tpu.pipeline_backend import (
    LocalBackend,
    MultiProcLocalBackend,
    PipelineBackend,
    TPUBackend,
    register_annotator,
    Annotator,
)
# The chunked streaming entry for DPEngine.aggregate/select_partitions:
# wrap an iterable of (pid_raw, pk_raw, values) column chunks and the
# executor streams it through the device-resident pipeline
# (runtime/pipeline.py) under the backend's encode_threads /
# pipeline_depth knobs.
from pipelinedp_tpu.runtime.pipeline import ChunkSource
# Raised (instead of silently merging two partitions) when the
# hash-device encode mode detects a 64-bit key-hash collision and the
# chunk source cannot be re-iterated for the exact-encoder fallback.
from pipelinedp_tpu.device_encode import HashCollisionError

# Beam/Spark backends exist only when the corresponding framework is
# importable (reference exports them unconditionally from
# pipeline_dp/__init__.py:36-39 because it hard-depends on both).
from pipelinedp_tpu import pipeline_backend as _pb

if hasattr(_pb, 'BeamBackend'):
    from pipelinedp_tpu.pipeline_backend import BeamBackend
if hasattr(_pb, 'SparkRDDBackend'):
    from pipelinedp_tpu.pipeline_backend import SparkRDDBackend
del _pb

__version__ = '0.1.0'
