"""Device-mesh helpers.

The framework's parallelism model (SURVEY.md §2.5): rows are data-sharded by
privacy-unit hash over a 1-D mesh axis "shards"; per-partition partial
accumulators are combined with lax.psum over ICI. DCN-reachable multi-host
meshes work the same way — jax.devices() spans all hosts under jax.distributed,
and make_mesh over that global list is the multi-controller entry point:
every process runs the same driver code over the same mesh, each owning only
its locally-addressable slice of the row data. The process-topology helpers
(process_index / process_count / is_fully_addressable / local_devices) are
what the runtime layers key per-process state on (journal file names, health
snapshots, the evacuation decision after a whole-host loss), and
initialize_distributed is the one place the jax.distributed bring-up (with
the CPU gloo collectives the 2-process dryrun rides) is spelled.

This module also owns the shape/padding arithmetic shared by every meshed
stage (round_capacity, per-shard capacities) and the two seams the
collective-reshard transfer discipline rests on:

  * shard_map: version-portable wrapper (jax.shard_map on new jax,
    jax.experimental.shard_map on older releases) used by every meshed
    kernel in the package.
  * host_fetch: the ONE sanctioned device->host fetch for small control
    tables (O(D^2) reshard counts, O(n_blocks) block offsets — never
    O(rows)). Routing all control-plane fetches through it lets the
    transfer-guard test (tests/test_reshard.py) forbid every other
    device->host materialization and so prove device-resident rows never
    stage through the host.
"""

import contextlib
import logging
import os
import random
import threading
import time
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec

SHARD_AXIS = "shards"


def make_mesh(devices: Optional[Sequence] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name "shards".

    Under jax.distributed (initialize_distributed), jax.devices() is the
    GLOBAL device list spanning every process, so the default mesh of a
    multi-controller job is already the pod-wide mesh: the same sharded
    drivers run unchanged, each process addressing only its local slice.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))  # staticcheck: disable=host-transfer — O(D) device HANDLES at mesh build, not array data


def process_index() -> int:
    """This controller's process index (0 on a single-process mesh)."""
    return int(jax.process_index())


def process_count() -> int:
    """Number of controller processes in the job (1 unless
    jax.distributed is initialized)."""
    return int(jax.process_count())


def device_process(device) -> int:
    """Owning process of a device (0 for objects without the attribute —
    test fakes and single-process CPU devices alike)."""
    return int(getattr(device, "process_index", 0))


def local_devices(mesh: Mesh) -> List:
    """The mesh devices this process can address, in mesh order."""
    me = process_index()
    return [d for d in mesh.devices.flat if device_process(d) == me]


def is_fully_addressable(mesh: Mesh) -> bool:
    """Whether every mesh device belongs to this process (i.e. the mesh
    is single-controller). Multi-controller meshes flip the runtime into
    per-process coordination: journal records gain a process suffix, the
    reshard count exchange stays on device, and a whole-host loss can
    evacuate this controller (runtime/retry.HostEvacuatedError)."""
    return len(local_devices(mesh)) == mesh.devices.size


def mesh_processes(mesh: Mesh) -> List[int]:
    """Sorted process indices participating in the mesh."""
    return sorted({device_process(d) for d in mesh.devices.flat})


def cross_process_fraction(mesh: Mesh) -> float:
    """Fraction of ordered shard pairs whose all_to_all traffic crosses
    processes (DCN rather than ICI) — the geometry factor bench receipts
    multiply into exchange byte counts to estimate cross-host volume."""
    devs = list(mesh.devices.flat)
    d = len(devs)
    if d <= 1:
        return 0.0
    pairs = sum(1 for a in devs for b in devs
                if device_process(a) != device_process(b))
    return pairs / float(d * (d - 1))


def initialize_distributed(coordinator_address: str,
                           num_processes: int,
                           process_id: Optional[int] = None) -> None:
    """Brings up the multi-controller runtime (idempotent).

    Wraps jax.distributed.initialize with the one platform quirk the CPU
    dryrun needs spelled out: the CPU backend's cross-process collectives
    ride the gloo implementation, which must be selected BEFORE the
    backend initializes. process_id=None falls back to the
    JAX_PROCESS_INDEX environment variable (set by the 2-process spawn
    helper) or cluster auto-detection.
    """
    try:
        from jax._src import distributed as _jax_distributed
        if getattr(_jax_distributed.global_state, "client", None) is not None:
            return  # already initialized (a re-init would raise) — NB:
            # checked via the distributed global state, not
            # jax.process_count(), which would initialize the backend as
            # a side effect and make the real initialize below illegal.
    except ImportError:
        pass
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_INDEX")
        process_id = int(env) if env is not None else None
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        # Older jaxlib without the knob: single-host CPU jobs still work;
        # cross-process CPU collectives would fail loudly downstream.
        logging.warning("jax_cpu_collectives_implementation unavailable; "
                        "cross-process CPU collectives may be unsupported "
                        "on this jax build.")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=int(num_processes),
                               process_id=process_id)


def collective_heartbeat(devices: Sequence) -> set:
    """Default remote-liveness oracle of probe_live_devices: one tiny
    replicated psum over a mesh of the candidate devices. Every surviving
    controller reaches the probe at the same point of the same failure
    (they all observed the same device-fatal dispatch), so the collective
    completes iff the candidate set is live end to end; any failure means
    remote liveness cannot be established and the probe falls back to the
    locally-provable subset."""
    import jax.numpy as jnp
    mesh = make_mesh(devices=list(devices))
    ones = jax.device_put(
        np.ones((len(devices),), np.int32),
        NamedSharding(mesh, PartitionSpec(SHARD_AXIS)))

    def per_shard(x):
        return jax.lax.psum(jnp.sum(x, dtype=x.dtype), SHARD_AXIS)

    fn = shard_map(per_shard, mesh=mesh,
                   in_specs=PartitionSpec(SHARD_AXIS),
                   out_specs=PartitionSpec())
    total = int(host_fetch(fn(ones), max_retries=0))
    if total != len(devices):
        raise RuntimeError(
            f"heartbeat psum returned {total}, expected {len(devices)}")
    return set(devices)


def probe_live_devices(devices: Sequence, heartbeat=None) -> List:
    """Liveness probe backing elastic mesh degradation
    (runtime/retry.run_with_mesh_degradation): which of `devices` can
    still be trusted to carry a rebuilt mesh.

    Locally-addressable devices get the direct proof — a trivial
    put-and-fetch scalar round trip (a dead chip fails it with a runtime
    error). Devices owned by ANOTHER process cannot be probed that way
    (device_put to a non-addressable device is not a thing), so remote
    liveness is learned indirectly: an active fault-injection schedule is
    authoritative (CPU test devices never really die — injected losses,
    including whole-host losses, are exactly what it tracks), and
    otherwise a collective heartbeat over the candidate set
    (collective_heartbeat, injectable for tests) must complete; if it
    cannot, every remote device is conservatively treated as lost and
    the mesh rebuilds over the locally-provable survivors.

    Returns the live devices in their original order, so the rebuilt
    mesh keeps a stable device ordering across shrinks.
    """
    from pipelinedp_tpu.runtime import faults as rt_faults
    lost_ids = rt_faults.injected_lost_device_ids(devices)
    me = process_index()
    remote = [d for d in devices if device_process(d) != me]
    remote_live = set()
    if remote:
        candidates = [d for d in remote
                      if getattr(d, "id", None) not in lost_ids]
        if rt_faults.active() is not None:
            # The schedule is the oracle: whatever it has not marked lost
            # is alive (the dryrun's simulated hosts cannot really die).
            remote_live = set(candidates)
        elif candidates:
            hb = heartbeat if heartbeat is not None else collective_heartbeat
            try:
                remote_live = set(hb(list(devices))) & set(candidates)
            except Exception as e:  # noqa: BLE001 - any heartbeat failure = remote liveness unprovable
                logging.warning(
                    "liveness probe: collective heartbeat over %d devices "
                    "failed (%s: %s) — remote liveness cannot be "
                    "established, treating all %d non-addressable devices "
                    "as lost.", len(devices), type(e).__name__,
                    str(e).splitlines()[0][:160], len(remote))
                remote_live = set()
    live = []
    for d in devices:
        if getattr(d, "id", None) in lost_ids:
            logging.warning(
                "liveness probe: device %s marked lost by the active "
                "fault schedule.", d)
            continue
        if device_process(d) != me:
            if d in remote_live:
                live.append(d)
            continue
        try:
            # max_retries=0: the probe must answer fast — a chip that
            # cannot ack one scalar round trip without retries is not a
            # chip to rebuild the mesh on.
            host_fetch(jax.device_put(np.zeros((1,), np.int32), d),
                       max_retries=0)
        except Exception as e:  # noqa: BLE001 - any failure = dead chip
            logging.warning(
                "liveness probe: device %s failed its probe round trip "
                "(%s: %s) — treating it as lost.", d,
                type(e).__name__, str(e).splitlines()[0][:160])
            continue
        live.append(d)
    return live


def join_candidates(mesh: Mesh, devices: Optional[Sequence] = None,
                    n_devices: Optional[int] = None) -> List:
    """Devices eligible to JOIN `mesh` in an elastic scale-UP.

    Resolves a join announcement (runtime/retry.announce_join) against
    the live mesh: either an explicit device list (devices already in
    the mesh are dropped — re-admitting them is a no-op), or a TARGET
    total of `n_devices`, filled from jax.devices() in enumeration order
    (the stable order every controller of a pod agrees on, so all of
    them resolve the same candidate set from the same announcement).
    Candidates are only nominated here; the elastic runtime still
    probes them (probe_live_devices) before rebuilding the mesh.
    """
    current = {getattr(d, "id", d) for d in mesh.devices.flat}
    if devices is not None:
        return [d for d in devices if getattr(d, "id", d) not in current]
    if n_devices is None:
        return []
    out = []
    for d in jax.devices():
        if len(current) + len(out) >= int(n_devices):
            break
        if getattr(d, "id", d) not in current:
            out.append(d)
    return out


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off.

    jax >= 0.6 exposes jax.shard_map (check_vma); older releases only have
    jax.experimental.shard_map.shard_map (check_rep). Every meshed kernel
    in the package goes through this wrapper so the whole multi-chip path
    works on both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """The leading-axis row split every meshed kernel consumes."""
    return NamedSharding(mesh, PartitionSpec(SHARD_AXIS))


def round_capacity(x: int, min_cap: int = 8) -> int:
    """Round up keeping 4 significant bits (<= 1/16 ~ 6.25% slack, 12.5%
    worst-case just above a power of two).

    Bounds the number of distinct padded shapes (so the jit cache stays
    small) without the up-to-2x waste of next-power-of-two padding.
    """
    x = max(int(x), min_cap)
    step = 1 << max((x - 1).bit_length() - 4, 3)
    return -(-x // step) * step


def rows_per_shard(n: int, n_shards: int) -> int:
    """Padded per-shard capacity for an even leading-axis split of n rows:
    ceil(n / n_shards) rounded to a bounded-shape capacity."""
    return round_capacity(-(-max(int(n), 1) // n_shards))


# Thread-local marker read by reshard.forbid_row_fetches so the guard can
# tell a sanctioned control-table fetch from a smuggled row download.
_sanctioned_fetch = threading.local()

# Thread-local override of host_fetch's retry budget, scoped by the
# drivers' runtime entry from the backend's RetryPolicy — so the retry=
# knob governs control-plane fetches too, not just block dispatch.
_fetch_policy = threading.local()
_DEFAULT_FETCH_RETRIES = 2

# Backoff jitter source. Multi-host jobs retry control-plane fetches from
# every host at once; a pure 0.05 * 2**attempt schedule would re-collide
# all of them on the exact same instant, so each delay is scaled by an
# independent uniform [0.5, 1) draw.
_jitter = random.Random()  # staticcheck: disable=host-rng — backoff jitter only: per-process independent seeding is the POINT (de-collides multi-host retries); never touches DP noise or sampling


@contextlib.contextmanager
def fetch_retry_scope(max_retries: Optional[int]):
    """Scopes a retry budget onto every host_fetch on this thread (the
    runtime entry passes the backend RetryPolicy's max_retries; None
    leaves the default in place)."""
    if max_retries is None:
        yield
        return
    prev = getattr(_fetch_policy, "max_retries", None)
    _fetch_policy.max_retries = int(max_retries)
    try:
        yield
    finally:
        _fetch_policy.max_retries = prev


def host_fetch(arr, max_retries: Optional[int] = None) -> np.ndarray:
    """Sanctioned small device->host fetch for meshed control tables.

    Only O(D^2) / O(n_blocks) tables may cross here — never row data. The
    transfer-guard test forbids all other device->host materialization on
    the device-resident path, so any new fetch added outside this helper
    fails that test instead of silently re-introducing host staging.

    Control-table fetches are sync points, so transient runtime failures
    (a tunnel hiccup on a remote-attached chip) surface here; they are
    retried a couple of times before propagating — the table is tiny, the
    re-fetch is cheap, and losing a whole blocked run to one dropped
    control-plane round trip is exactly the failure mode the runtime
    package exists to remove.

    Multi-controller discipline: on a mesh spanning processes, a control
    table is only fetchable when it is fully REPLICATED (every meshed
    kernel producing one reduces it on device — psum/all_gather — before
    it reaches here), because each process can then read its local
    replica without touching another host's memory. A sharded,
    non-addressable array is rejected up front with an actionable
    message instead of np.asarray's generic failure.
    """
    # Imported lazily: mesh is a leaf module most of the package imports.
    from pipelinedp_tpu.runtime import retry as rt_retry
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime import trace as rt_trace
    from pipelinedp_tpu.runtime import watchdog as rt_watchdog

    # Control-table fetches are sync points the blocked drivers pass
    # through between dispatch windows: heartbeat the active watchdog so
    # health can report seconds-since-progress even between block guards.
    wd = rt_watchdog.active()
    if wd is not None:
        wd.beat("host_fetch")

    if max_retries is None:
        max_retries = getattr(_fetch_policy, "max_retries", None)
        if max_retries is None:
            max_retries = _DEFAULT_FETCH_RETRIES

    if (isinstance(arr, jax.Array) and not arr.is_fully_addressable and
            not arr.is_fully_replicated):
        raise ValueError(
            f"host_fetch of a sharded, non-addressable array (shape "
            f"{arr.shape}) on a multi-controller mesh — reduce the control "
            f"table on device (psum/all_gather to a replicated layout) so "
            f"each process reads its own replica; this process cannot "
            f"address another host's shards.")

    _sanctioned_fetch.active = True
    try:
        attempt = 0
        while True:
            try:
                # The span carries the transferred byte count so trace
                # summaries can attribute control-plane transfer volume
                # (transfer_bytes) separately from compute.
                with rt_trace.span("host_fetch") as sp:
                    out = np.asarray(arr)
                    sp.set(bytes=int(out.nbytes))
                    return out
            except Exception as e:  # noqa: BLE001 - classified below
                if not rt_retry.is_transient(e) or attempt >= max_retries:
                    raise
                # Spend the job-wide retry budget (threaded by the entry
                # wrapper): composed faults must not turn N cheap
                # re-fetches per seam into an unbounded storm.
                rt_retry.consume_retry_budget("host_fetch")
                # Jittered bounded backoff: the exponential cap keeps the
                # worst case at 1 s, the uniform scale decorrelates the
                # lockstep retries of N hosts re-fetching the same table.
                delay = min(0.05 * 2**attempt, 1.0) * (0.5 +
                                                       0.5 * _jitter.random())
                attempt += 1
                rt_telemetry.record("host_fetch_retries")
                logging.warning(
                    "control-table host fetch failed transiently (%s); "
                    "retry %d/%d in %.2fs", type(e).__name__, attempt,
                    max_retries, delay)
                time.sleep(delay)
    finally:
        _sanctioned_fetch.active = False
