"""Device-mesh helpers.

The framework's parallelism model (SURVEY.md §2.5): rows are data-sharded by
privacy-unit hash over a 1-D mesh axis "shards"; per-partition partial
accumulators are combined with lax.psum over ICI. DCN-reachable multi-host
meshes work the same way — jax.devices() spans all hosts under jax.distributed.

This module also owns the shape/padding arithmetic shared by every meshed
stage (round_capacity, per-shard capacities) and the two seams the
collective-reshard transfer discipline rests on:

  * shard_map: version-portable wrapper (jax.shard_map on new jax,
    jax.experimental.shard_map on older releases) used by every meshed
    kernel in the package.
  * host_fetch: the ONE sanctioned device->host fetch for small control
    tables (O(D^2) reshard counts, O(n_blocks) block offsets — never
    O(rows)). Routing all control-plane fetches through it lets the
    transfer-guard test (tests/test_reshard.py) forbid every other
    device->host materialization and so prove device-resident rows never
    stage through the host.
"""

import contextlib
import logging
import random
import threading
import time
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec

SHARD_AXIS = "shards"


def make_mesh(devices: Optional[Sequence] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name "shards"."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))  # staticcheck: disable=host-transfer — O(D) device HANDLES at mesh build, not array data


def probe_live_devices(devices: Sequence) -> List:
    """Liveness probe backing elastic mesh degradation
    (runtime/retry.run_with_mesh_degradation): which of `devices` can
    still complete a trivial put-and-fetch round trip.

    A dead chip fails the round trip with a runtime error; devices an
    active fault-injection schedule has marked lost (the CPU test
    devices never really die) are excluded up front. Returns the live
    devices in their original order, so the rebuilt mesh keeps a stable
    device ordering across shrinks.
    """
    from pipelinedp_tpu.runtime import faults as rt_faults
    lost_ids = rt_faults.injected_lost_device_ids(devices)
    live = []
    for d in devices:
        if getattr(d, "id", None) in lost_ids:
            logging.warning(
                "liveness probe: device %s marked lost by the active "
                "fault schedule.", d)
            continue
        try:
            # max_retries=0: the probe must answer fast — a chip that
            # cannot ack one scalar round trip without retries is not a
            # chip to rebuild the mesh on.
            host_fetch(jax.device_put(np.zeros((1,), np.int32), d),
                       max_retries=0)
        except Exception as e:  # noqa: BLE001 - any failure = dead chip
            logging.warning(
                "liveness probe: device %s failed its probe round trip "
                "(%s: %s) — treating it as lost.", d,
                type(e).__name__, str(e).splitlines()[0][:160])
            continue
        live.append(d)
    return live


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off.

    jax >= 0.6 exposes jax.shard_map (check_vma); older releases only have
    jax.experimental.shard_map.shard_map (check_rep). Every meshed kernel
    in the package goes through this wrapper so the whole multi-chip path
    works on both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """The leading-axis row split every meshed kernel consumes."""
    return NamedSharding(mesh, PartitionSpec(SHARD_AXIS))


def round_capacity(x: int, min_cap: int = 8) -> int:
    """Round up keeping 4 significant bits (<= 1/16 ~ 6.25% slack, 12.5%
    worst-case just above a power of two).

    Bounds the number of distinct padded shapes (so the jit cache stays
    small) without the up-to-2x waste of next-power-of-two padding.
    """
    x = max(int(x), min_cap)
    step = 1 << max((x - 1).bit_length() - 4, 3)
    return -(-x // step) * step


def rows_per_shard(n: int, n_shards: int) -> int:
    """Padded per-shard capacity for an even leading-axis split of n rows:
    ceil(n / n_shards) rounded to a bounded-shape capacity."""
    return round_capacity(-(-max(int(n), 1) // n_shards))


# Thread-local marker read by reshard.forbid_row_fetches so the guard can
# tell a sanctioned control-table fetch from a smuggled row download.
_sanctioned_fetch = threading.local()

# Thread-local override of host_fetch's retry budget, scoped by the
# drivers' runtime entry from the backend's RetryPolicy — so the retry=
# knob governs control-plane fetches too, not just block dispatch.
_fetch_policy = threading.local()
_DEFAULT_FETCH_RETRIES = 2

# Backoff jitter source. Multi-host jobs retry control-plane fetches from
# every host at once; a pure 0.05 * 2**attempt schedule would re-collide
# all of them on the exact same instant, so each delay is scaled by an
# independent uniform [0.5, 1) draw.
_jitter = random.Random()  # staticcheck: disable=host-rng — backoff jitter only: per-process independent seeding is the POINT (de-collides multi-host retries); never touches DP noise or sampling


@contextlib.contextmanager
def fetch_retry_scope(max_retries: Optional[int]):
    """Scopes a retry budget onto every host_fetch on this thread (the
    runtime entry passes the backend RetryPolicy's max_retries; None
    leaves the default in place)."""
    if max_retries is None:
        yield
        return
    prev = getattr(_fetch_policy, "max_retries", None)
    _fetch_policy.max_retries = int(max_retries)
    try:
        yield
    finally:
        _fetch_policy.max_retries = prev


def host_fetch(arr, max_retries: Optional[int] = None) -> np.ndarray:
    """Sanctioned small device->host fetch for meshed control tables.

    Only O(D^2) / O(n_blocks) tables may cross here — never row data. The
    transfer-guard test forbids all other device->host materialization on
    the device-resident path, so any new fetch added outside this helper
    fails that test instead of silently re-introducing host staging.

    Control-table fetches are sync points, so transient runtime failures
    (a tunnel hiccup on a remote-attached chip) surface here; they are
    retried a couple of times before propagating — the table is tiny, the
    re-fetch is cheap, and losing a whole blocked run to one dropped
    control-plane round trip is exactly the failure mode the runtime
    package exists to remove.
    """
    # Imported lazily: mesh is a leaf module most of the package imports.
    from pipelinedp_tpu.runtime import retry as rt_retry
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime import trace as rt_trace
    from pipelinedp_tpu.runtime import watchdog as rt_watchdog

    # Control-table fetches are sync points the blocked drivers pass
    # through between dispatch windows: heartbeat the active watchdog so
    # health can report seconds-since-progress even between block guards.
    wd = rt_watchdog.active()
    if wd is not None:
        wd.beat("host_fetch")

    if max_retries is None:
        max_retries = getattr(_fetch_policy, "max_retries", None)
        if max_retries is None:
            max_retries = _DEFAULT_FETCH_RETRIES

    _sanctioned_fetch.active = True
    try:
        attempt = 0
        while True:
            try:
                # The span carries the transferred byte count so trace
                # summaries can attribute control-plane transfer volume
                # (transfer_bytes) separately from compute.
                with rt_trace.span("host_fetch") as sp:
                    out = np.asarray(arr)
                    sp.set(bytes=int(out.nbytes))
                    return out
            except Exception as e:  # noqa: BLE001 - classified below
                if not rt_retry.is_transient(e) or attempt >= max_retries:
                    raise
                # Jittered bounded backoff: the exponential cap keeps the
                # worst case at 1 s, the uniform scale decorrelates the
                # lockstep retries of N hosts re-fetching the same table.
                delay = min(0.05 * 2**attempt, 1.0) * (0.5 +
                                                       0.5 * _jitter.random())
                attempt += 1
                rt_telemetry.record("host_fetch_retries")
                logging.warning(
                    "control-table host fetch failed transiently (%s); "
                    "retry %d/%d in %.2fs", type(e).__name__, attempt,
                    max_retries, delay)
                time.sleep(delay)
    finally:
        _sanctioned_fetch.active = False
