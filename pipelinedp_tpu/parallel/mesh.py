"""Device-mesh helpers.

The framework's parallelism model (SURVEY.md §2.5): rows are data-sharded by
privacy-unit hash over a 1-D mesh axis "shards"; per-partition partial
accumulators are combined with lax.psum over ICI. DCN-reachable multi-host
meshes work the same way — jax.devices() spans all hosts under jax.distributed.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"


def make_mesh(devices: Optional[Sequence] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the given (or all) devices, axis name "shards"."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
