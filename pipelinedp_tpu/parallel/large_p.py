"""Very large partition spaces: blocked partition-axis execution.

The dense fused kernel materializes [0, P) columns — ideal up to P ~ 10^6,
but at P = 10^7..10^9 (the reference's unbounded-key shuffle regime,
``pipeline_dp/pipeline_backend.py:339-352``) a replicated dense partition
axis no longer fits. This module shards the PARTITION axis instead:

  1. **Bound once** (device): contribution bounding is a row-space
     computation (executor.bounded_row_columns) independent of P; the same
     kernel then compacts (drops bounded-away rows) and orders the
     survivors by partition id — all on device, one extra payload sort.
  2. **Bin by partition block**: block b owns partitions [b*C, (b+1)*C);
     block row ranges come from one searchsorted over the compacted stream.
  3. **Finalize per block** (device): each block segment-sums its own rows
     into a dense [C] slice and runs DP selection + noise on just that
     slice (selection and noise are pointwise over partitions, so blocks
     are independent — no collective, no rescans: total work is
     O(n log n + P)).
  4. **Compact**: kept partitions are sorted to the front ON DEVICE, so
     only O(kept) values ever cross the device->host boundary — the
     dominant cost under a remote-attached chip, where transferring dense
     [C] outputs per block costs more than all device compute combined.

Two row-staging regimes, switched on whether the rows fit one device chunk:

  * **Device-resident** (n <= row_chunk, the common case): rows never
    return to the host between passes; per-block inputs are device-side
    gathers at host-known offsets. Host traffic = block offsets + kept
    results.
  * **Host-staged** (n > row_chunk): row chunks split on privacy-id
    boundaries are bounded+compacted on device, the compacted survivors
    staged back to host, merged, and re-uploaded per block — preserving
    the O(row_chunk + C) device-memory bound at any n.

The meshed variants (aggregate_blocked_sharded /
select_partitions_blocked_sharded) scale both passes D-way: rows shard by
privacy id — device-resident inputs through the on-device all_to_all
reshard (parallel/reshard.py; rows never touch the host), host inputs
through the exact LPT permutation — and each block costs one [C]-sized
psum over ICI.

Failure semantics (pipelinedp_tpu/runtime, README "Failure semantics"):
every driver takes retry= (transient dispatch/sync failures re-dispatch
under the SAME fold_in(final_key, b) key — bit-identical noise, no second
release), journal=/job_id= (consumed blocks' drained results recorded
with CRC32 integrity checks for resume; replayed blocks never
re-dispatch, corrupt records quarantine and recompute),
timeout_s=/watchdog= (per-operation deadlines: a timed-out dispatch or
drain retries same-key, repeated timeouts degrade like OOM, a timed-out
reshard collective falls back to the host permutation), and degrades on
OOM by halving the partition block capacity and re-planning the
remaining range (run_with_degradation; re-planned blocks draw fresh
keys — nothing was released for them). The meshed drivers additionally
take elastic=/min_devices= (device-loss tolerance: a device-fatal
failure rebuilds a smaller mesh from the surviving devices and
re-enters the driver — block keys are geometry-independent, so the
degraded run replays the same release; the one-device floor falls back
to the unsharded driver, and losses past min_devices raise
MeshDegradationError with a resume pointer). Each run executes inside
its job's health scope (runtime/health.py), so retries, timeouts,
fallbacks, quarantines and mesh degradations surface in
TPUBackend.health().
"""

import dataclasses
import functools
import logging
import threading
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import executor
from pipelinedp_tpu import numeric as rt_numeric
from pipelinedp_tpu.ops import segment_ops
# Canonical shape arithmetic lives with the mesh helpers; re-exported here
# because the blocked path made the name public first.
from pipelinedp_tpu.parallel.mesh import host_fetch, round_capacity
from pipelinedp_tpu.runtime import aot as rt_aot
from pipelinedp_tpu.runtime import entry as rt_entry
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import journal as rt_journal
from pipelinedp_tpu.runtime import pipeline as rt_pipeline
from pipelinedp_tpu.runtime import retry as rt_retry
from pipelinedp_tpu.runtime import telemetry as rt_telemetry
from pipelinedp_tpu.runtime import trace as rt_trace
from pipelinedp_tpu.runtime import watchdog as rt_watchdog

# One shared depth for the async block pipeline: _dispatch_blocks keeps at
# most this many block kernels in flight, and _StagedDrain keeps at most
# this many blocks' O(kept) result buffers staged. The residency reasoning
# (in-flight outputs + staged drains both bounded by the same window, so
# HBM holds O(depth * C), never O(P)) only holds while these agree. The
# constant itself moved to runtime/pipeline.py — the streaming ingest
# executor bounds its staging window with the SAME depth — and is
# re-exported here because the blocked path made the name public first.
PIPELINE_DEPTH = rt_pipeline.PIPELINE_DEPTH

# Key lane for OOM-re-planned block generations: block keys must be a pure
# function of (final_key, plan generation, block index) so that a RETRIED
# block redraws bit-identical noise while a RE-PLANNED block (different
# partition geometry after a capacity halving) can never collide with a
# key an earlier-generation block already consumed.
_REPLAN_KEY_LANE = 0x7265706C  # 'repl'


def _block_noise_key(final_key, generation: int, block: int):
    if generation == 0:
        # Generation 0 preserves the historical fold_in(final_key, b)
        # derivation: fault-free runs (and retries within them) are
        # bit-compatible with pre-runtime releases.
        return jax.random.fold_in(final_key, block)
    return jax.random.fold_in(
        jax.random.fold_in(final_key, _REPLAN_KEY_LANE + generation), block)


def _bound_compact_trace(pid, pk, values, valid, min_v, max_v, min_s, max_s,
                         mid, key, cfg: executor.KernelConfig):
    """Traceable body shared by the single-device kernel and the per-shard
    function of the meshed path: bound contributions, drop bounded-away
    rows, order survivors by partition id (dropped rows carry an int32-max
    sentinel and sort to the tail)."""
    spk, keep_row, pair_start, reduce_cols, qrows = \
        executor.bounded_row_columns(pid, pk, values, valid, min_v, max_v,
                                     min_s, max_s, mid, key, cfg)
    names = list(reduce_cols)
    sort_key = jnp.where(keep_row, spk, jnp.iinfo(jnp.int32).max)
    payloads = ([pair_start.astype(jnp.int32)] +
                [reduce_cols[m] for m in names])
    if cfg.quantiles:
        payloads.append(qrows[1])  # per-row leaf index
    (spk_s,), pay = executor._sort_rows([sort_key], payloads)
    cols_s = {m: pay[1 + j] for j, m in enumerate(names)}
    leaf_s = pay[-1] if cfg.quantiles else None
    return spk_s, pay[0].astype(bool), cols_s, leaf_s, keep_row.sum()


@functools.partial(jax.jit, static_argnames=("cfg",))
def _bounded_compact_kernel(pid, pk, values, valid, min_v, max_v, min_s,
                            max_s, mid, key, cfg: executor.KernelConfig):
    """Single-device bound+compact. Returns (spk, pair_start, reduce_cols,
    leaf, n_kept); with percentiles, `leaf` carries each row's
    quantile-tree leaf index through the same compaction sort."""
    return _bound_compact_trace(pid, pk, values, valid, min_v, max_v, min_s,
                                max_s, mid, key, cfg)


_bounded_compact_kernel = rt_aot.aot_probe("blocked_bound_compact",
                                           _bounded_compact_kernel,
                                           static_argnames=("cfg",))


def _block_trace(spk_s, pair_s, cols_s, leaf_s, lo, length, base, min_v,
                 max_v, mid, stds, key, cfg: executor.KernelConfig,
                 cap: int, secure_tables=None, psum_axis=None):
    """Traceable body shared by the single-device block kernel and the
    per-shard function of the meshed path: finalize one partition block
    from the (shard-local) compacted row stream.

    Gathers `cap` rows at host-known offset `lo` (rows beyond `length` are
    masked), reduces them onto the block's dense [C] slice — psum'd over
    `psum_axis` when running under shard_map, the meshed path's one
    collective per block — then runs selection + noise (and, with
    percentiles, the block's quantile descent) and sorts kept partitions
    to the front so the host can fetch exactly n_kept results.
    """
    idx = jnp.arange(cap, dtype=jnp.int32)
    valid = idx < length
    take = lambda a: jnp.take(a, lo + idx, mode="clip")
    spk_rel = jnp.where(valid, take(spk_s) - base, cfg.n_partitions)
    spk_rel = spk_rel.astype(jnp.int32)
    pair = take(pair_s) & valid
    cols = {
        name: jnp.where(valid, take(col), jnp.zeros((), col.dtype))
        for name, col in cols_s.items()
    }
    # Rows were compacted into (kept-first, spk-ascending) order by
    # _bound_compact_trace; the block slice preserves it, and masked
    # tail rows carry the cfg.n_partitions sentinel — still sorted.
    dense = executor.reduce_rows_to_partitions(spk_rel, valid, pair, cols,
                                               cfg.n_partitions,
                                               cfg.vector_size,
                                               presorted=True,
                                               numeric_mode=cfg.numeric_mode)
    if psum_axis is not None:
        if cfg.numeric_mode == "safe":
            # Compensated cross-shard combine: a plain psum would re-round
            # away what the compensated segment sums just preserved.
            dense = jax.tree.map(
                lambda x: segment_ops.compensated_psum(x, psum_axis), dense)
        else:
            dense = jax.tree.map(lambda x: jax.lax.psum(x, psum_axis), dense)
    outputs, keep, _ = executor.finalize(dense, min_v, mid, stds, key, cfg,
                                         secure_tables)
    if cfg.quantiles:
        # Per-block quantile trees over just the block's rows: relative
        # partition ids index trees [0, C); quantile_outputs picks the lazy
        # descent whenever the block exceeds one dense histogram chunk, so
        # peak memory stays O(C * branching), never O(C * leaves).
        qkey = jax.random.fold_in(key, 7919)
        outputs.update(
            executor.quantile_outputs((spk_rel, take(leaf_s), valid), min_v,
                                      max_v, stds, qkey, cfg,
                                      psum_axis=psum_axis,
                                      secure_tables=secure_tables))
    order = jnp.argsort(~keep, stable=True)  # kept partitions first
    ids_sorted = order.astype(jnp.int32)
    outputs_sorted = {name: col[order] for name, col in outputs.items()}
    return keep.sum(), ids_sorted, outputs_sorted


@functools.partial(jax.jit, static_argnames=("cfg", "cap"))
def _block_kernel_dev(spk_s, pair_s, cols_s, leaf_s, lo, length, base, min_v,
                      max_v, mid, stds, key, cfg: executor.KernelConfig,
                      cap: int, secure_tables=None):
    """Single-device finalize of one partition block (see _block_trace)."""
    return _block_trace(spk_s, pair_s, cols_s, leaf_s, lo, length, base,
                        min_v, max_v, mid, stds, key, cfg, cap,
                        secure_tables)


_block_kernel_dev = rt_aot.aot_probe("blocked_block_kernel",
                                     _block_kernel_dev,
                                     static_argnames=("cfg", "cap"))


def _chunk_ends(pid_sorted: np.ndarray, row_chunk: int) -> np.ndarray:
    """Chunk end offsets, each extended to the next privacy-id boundary.

    A privacy id's rows must stay in one chunk (L0 bounding is global per
    id), so a single id with more rows than row_chunk forces an oversized
    chunk — the one irreducible violation of the O(row_chunk) memory bound;
    it is logged so the operator knows which workload property caused it.
    """
    import logging
    n = len(pid_sorted)
    ends = []
    start = 0
    while start < n:
        end = min(start + row_chunk, n)
        if end < n:
            end = int(
                np.searchsorted(pid_sorted, pid_sorted[end - 1],
                                side="right"))
        if end - start > 2 * row_chunk:
            logging.warning(
                "large_p: a single privacy id spans %d rows (> 2x row_chunk="
                "%d); its chunk cannot be split without breaking per-id "
                "contribution bounding. Device memory for this chunk scales "
                "with that id's row count.", end - start, row_chunk)
        ends.append(end)
        start = end
    return np.asarray(ends)


class _Replay:
    """A block whose results come from the journal instead of a dispatch."""

    __slots__ = ("record",)

    def __init__(self, record: rt_journal.BlockRecord):
        self.record = record


# The shared runtime-entry discipline (knob validation, health scope,
# watchdog activation, elastic mesh degradation) moved to
# runtime/entry.py so the dense sharded drivers share it; the historical
# name stays importable from here.
_runtime_entry = rt_entry.runtime_entry


def _fallback_blocked_aggregate(args, kwargs, job):
    """Elastic floor of aggregate_blocked_sharded: the unsharded blocked
    driver on the surviving device. Bit-compatible by construction — both
    drivers split rng_key the same way and derive the same
    fold_in(final_key, b) block keys, and the D=1 pass-1 sampling key
    (fold_in(rows_key, 0)) matches the single-chunk unsharded one."""
    kw = {k: v for k, v in kwargs.items() if k != "reshard"}
    return aggregate_blocked(*args[1:], job_id=job, **kw)


def _fallback_blocked_select(args, kwargs, job):
    """Elastic floor of select_partitions_blocked_sharded (see
    _fallback_blocked_aggregate)."""
    kw = {k: v for k, v in kwargs.items() if k != "reshard"}
    return select_partitions_blocked(*args[1:], job_id=job, **kw)


def _sync_scalars(result) -> None:
    """Forces the 0-d leaves (the n_kept gates) to host — the sync point
    where asynchronously-dispatched block failures surface."""
    for leaf in jax.tree_util.tree_leaves(result):
        if getattr(leaf, "ndim", None) == 0:
            np.asarray(leaf)


def _dispatch_blocks(block_iter, consume,
                     max_in_flight: int = PIPELINE_DEPTH,
                     retry_policy: Optional[rt_retry.RetryPolicy] = None,
                     overlap: bool = False) -> int:
    """Bounded-window async block dispatch shared by every blocked driver.

    jax execution is async, so the device pipelines upcoming block kernels
    while the host drains earlier results — one latency-bound sync per
    block would otherwise dominate under a remote-attached chip. The
    window is bounded: each in-flight block pins O(C) output buffers in
    HBM, and an unbounded pipeline over P/C blocks would hold O(P)
    results — the exact footprint this module exists to avoid.

    `block_iter` yields (block_index, entry) pairs where entry is either a
    _Replay (journaled results, consumed with no device work) or a
    zero-arg dispatch closure. The closure is re-invokable: it derives its
    own fold_in key, so re-dispatching it for a retry redraws bit-identical
    noise. Transient failures — at dispatch or at the consume-side sync —
    are retried with bounded backoff; OOM-classified failures surface as
    BlockOOMError AFTER all earlier in-flight blocks are drained, so the
    caller can re-plan from exactly the failed block.
    `consume(block_index, result)` syncs and drains one block. Returns
    the number of blocks dispatched (replays excluded).

    overlap=True (TPUBackend(overlap_drain=True); off by default) runs
    consume() on a dedicated drainer thread: block b's drain sync,
    journal fsync and staged transfers come OFF the dispatch thread, so
    block b+1's dispatch is issued while b is still draining (true
    compute/drain double-buffering — the serial mode only overlapped up
    to the window boundary, then blocked the dispatch loop on the
    oldest drain). Opt-in because drain deadlines now measure wall time
    that includes dispatch-side compile contention: on a shared-core
    host a watchdog-armed run can spiral (drain starves behind a
    compile -> deadline expiry -> retry/degrade -> more compiles), so
    pair overlap with a generous timeout_s or none. The drainer runs
    under the dispatch thread's watchdog, health scope, fault schedule
    and AOT activation; blocks are consumed strictly FIFO on the one
    thread, so journal records, result order and fold_in keys are
    bit-identical to overlap=False — asserted in tests — and a drain
    failure surfaces on the dispatch thread with the same
    classification (BlockOOMError for degradable faults) after the
    earlier in-flight blocks have drained.
    """
    policy = retry_policy or rt_retry.DEFAULT_POLICY
    pending = []
    n_dispatched = 0

    def start(b, make):
        # The per-block dispatch span gives the trace a block-granular
        # timeline alongside the watchdog's "dispatch" heartbeats/guards.
        with rt_trace.span("dispatch", block=b):
            result = rt_retry.retry_call(make, policy, block=b)
        rt_telemetry.record("release_dispatches", block=b)
        # Start the host copy of each scalar output (the n_kept gates) at
        # dispatch time: by the time consume() syncs on it, the value has
        # already crossed the link — int(n_kept) would otherwise pay one
        # blocking round trip per block on a remote-attached chip.
        for leaf in jax.tree_util.tree_leaves(result):
            if getattr(leaf, "ndim", None) == 0:
                _copy_to_host_async(leaf)
        return result

    def consume_one(b, entry, make):
        if make is None:  # journal replay
            consume(b, entry)
            return
        result = entry
        attempt = 0
        while True:
            try:
                rt_faults.maybe_fail("consume", b)
                # The drain sync runs under its own watchdog deadline
                # (when one is active): an expiry surfaces as a transient
                # BlockTimeoutError and re-dispatches the same key below.
                with rt_watchdog.guard("drain", b), \
                        rt_trace.span("drain", block=b):
                    rt_faults.maybe_hang(b, point="drain")
                    _sync_scalars(result)
                break
            except Exception as e:  # noqa: BLE001 - classified below
                if (not rt_retry.is_transient(e) or
                        attempt >= policy.max_retries):
                    raise
                delay = policy.delay(attempt)
                attempt += 1
                if rt_retry.is_timeout(e):
                    rt_telemetry.record("block_timeouts", block=b)
                rt_telemetry.record("block_retries", block=b)
                logging.warning(
                    "block %d failed at its sync point (%s); re-dispatching "
                    "under the same block key (retry %d/%d in %.2fs) — "
                    "noise is bit-identical, no second release", b,
                    type(e).__name__, attempt, policy.max_retries, delay)
                time.sleep(delay)
                result = start(b, make)
        with rt_trace.span("consume", block=b):
            consume(b, result)

    def _degradable(err):
        # Exhausted timeouts degrade exactly like OOM: halving the block
        # capacity shrinks per-block work, so the smaller block can land
        # inside the deadline — and the timed-out block never produced
        # consumed output, so the re-plan's fresh keys release nothing
        # twice.
        return rt_retry.is_oom(err) or rt_retry.is_timeout(err)

    def consume_or_oom(b, entry, make):
        try:
            consume_one(b, entry, make)
        except Exception as err:  # noqa: BLE001 - classified below: degradable (OOM/timeout) converts to BlockOOMError, the rest re-raise
            if make is not None and _degradable(err):
                raise rt_retry.BlockOOMError(b, err) from err
            raise

    active_wd = rt_watchdog.active()
    if overlap and max_in_flight > 1:
        return _dispatch_blocks_overlapped(block_iter, start,
                                           consume_or_oom, max_in_flight,
                                           active_wd, _degradable)
    for b, entry in block_iter:
        if active_wd is not None:
            active_wd.beat("dispatch")
        if isinstance(entry, _Replay):
            pending.append((b, entry, None))
        else:
            n_dispatched += 1
            try:
                result = start(b, entry)
            except Exception as err:  # noqa: BLE001 - classified below after the in-flight drain: degradable -> BlockOOMError, the rest re-raise
                # Drain the earlier in-flight blocks first: their results
                # (and journal records) must survive the abort so a
                # degradation or resume continues from this block, not
                # from zero. A secondary drain failure must not mask the
                # original error.
                try:
                    while pending:
                        consume_one(*pending.pop(0))
                except Exception:  # noqa: BLE001 - original error wins
                    logging.exception(
                        "draining in-flight blocks after a dispatch "
                        "failure itself failed; earlier results may be "
                        "incomplete")
                if _degradable(err):
                    raise rt_retry.BlockOOMError(b, err) from err
                raise
            pending.append((b, result, entry))
        if len(pending) >= max_in_flight:
            consume_or_oom(*pending.pop(0))
    while pending:
        consume_or_oom(*pending.pop(0))
    return n_dispatched


def _dispatch_blocks_overlapped(block_iter, start, consume_or_oom,
                                max_in_flight: int, active_wd,
                                _degradable) -> int:
    """The drainer-thread mode of _dispatch_blocks (see its docstring).

    The dispatch thread only issues device work and enqueues (b, result,
    make) triples into a bounded FIFO; one drainer thread syncs,
    journals and stages every block in order. The queue bound IS the
    in-flight window (a full queue blocks the enqueue — the same
    backpressure the serial pending list applied), so HBM residency is
    unchanged. Thread-scoped runtime context (watchdog activation,
    health job scope, fault schedule, AOT routing) is captured on the
    dispatch thread and re-activated on the drainer, so drain guards,
    counter attribution and injected consume faults behave exactly as
    in serial mode."""
    import queue as _queue

    from pipelinedp_tpu.runtime import health as rt_health

    job_health = rt_health.current()
    fault_schedule = rt_faults.active()
    aot_on = rt_aot.enabled()
    drain_q: "_queue.Queue" = _queue.Queue(maxsize=max_in_flight)
    drain_err: list = []
    n_dispatched = 0

    def drainer():
        import contextlib as _ctx
        fault_scope = (rt_faults.inject(fault_schedule)
                       if fault_schedule is not None else
                       _ctx.nullcontext())
        with rt_health.track(job_health), rt_watchdog.activate(active_wd), \
                rt_aot.activate(aot_on), fault_scope:
            while True:
                item = drain_q.get()
                if item is None:
                    return
                if drain_err:
                    # A failed block poisons the rest of the window: the
                    # serial mode would never have consumed them either
                    # (their journal records would land AFTER the failed
                    # block's on a resume — out-of-order durability).
                    continue
                try:
                    consume_or_oom(*item)
                except BaseException as e:  # noqa: BLE001 - transported to the dispatch thread verbatim; consume_or_oom already classified it
                    drain_err.append(e)

    thread = threading.Thread(target=drainer, name="pdp-block-drain",
                              daemon=True)
    thread.start()
    dispatch_err = None
    failed_block = None
    try:
        for b, entry in block_iter:
            if active_wd is not None:
                active_wd.beat("dispatch")
            if drain_err:
                break
            if isinstance(entry, _Replay):
                drain_q.put((b, entry, None))
                continue
            n_dispatched += 1
            try:
                result = start(b, entry)
            except Exception as err:  # noqa: BLE001 - classified after the in-flight drain below, exactly like serial mode
                dispatch_err, failed_block = err, b
                break
            drain_q.put((b, result, entry))
    finally:
        # Sentinel AFTER everything queued: the drainer finishes draining
        # the in-flight window (journal durability for earlier blocks)
        # before the dispatch thread surfaces any failure.
        drain_q.put(None)
        thread.join()
    if dispatch_err is not None:
        if drain_err:
            logging.exception(
                "draining in-flight blocks after a dispatch failure "
                "itself failed; earlier results may be incomplete",
                exc_info=drain_err[0])
        if _degradable(dispatch_err):
            raise rt_retry.BlockOOMError(failed_block,
                                         dispatch_err) from dispatch_err
        raise dispatch_err
    if drain_err:
        raise drain_err[0]
    return n_dispatched


# The async-copy helper moved to runtime/pipeline.py (the dense
# executor's drain shares it); the historical name stays importable.
_copy_to_host_async = rt_pipeline.copy_to_host_async


def _materialize_block_record(ids_sorted, outputs_sorted, k: int,
                              b_base: int) -> rt_journal.BlockRecord:
    """O(kept) journal-record materialization with overlapped copies.

    Every output slice's device->host copy starts BEFORE the first
    blocking np.asarray — the same discipline as the dense executor's
    _decode_rows drain. The journaled consume paths used to materialize
    ids + each column serially (one blocking round trip per array,
    the async-drain asymmetry); now the transfers overlap each other
    and the still-running block compute, and the np.asarray barrier
    waits once for the batch."""
    ids = ids_sorted[:k]
    cols = {name: col[:k] for name, col in outputs_sorted.items()}
    _copy_to_host_async(ids)
    for col in cols.values():
        _copy_to_host_async(col)
    rt_telemetry.record("release_dispatches")
    return rt_journal.BlockRecord(
        ids=np.asarray(ids).astype(np.int64) + b_base,  # staticcheck: disable=host-transfer — O(kept) journal materialization gated by the n_kept sync; the copy was started async above
        outputs={name: np.asarray(col)  # staticcheck: disable=host-transfer — O(kept) journal materialization; all column copies started async above, this barrier waits for the batch
                 for name, col in cols.items()})


class _StagedDrain:
    """Overlapped O(kept) result drains for the blocked drivers.

    consume() used to np.asarray each kept slice as its block was
    consumed — one blocking device->host round trip per array, so a
    10-block run with 3 output columns paid ~30 serial round trips
    (~2 s at the tunnel's ~64 ms RTT, the dominant term of the measured
    round-5 profile). Staging instead starts an async host copy per
    slice and defers the blocking np.asarray: transfers overlap each
    other and the remaining block compute. Order is preserved per
    target list (blocks are consumed ascending), so the concatenation
    contracts of the drivers are unchanged.

    Residency stays bounded: staged device buffers would otherwise
    accumulate O(total kept) in HBM — the exact footprint the bounded
    dispatch window exists to avoid. end_block() (called once per
    consumed block) materializes and frees block groups older than
    `max_staged_blocks`; those blocks finished computing a full window
    ago, so draining them rarely blocks and still overlaps the
    in-flight compute."""

    def __init__(self, max_staged_blocks: int = PIPELINE_DEPTH):
        self._staged = []
        self._block_sizes = []
        self._open = 0  # entries staged since the last end_block()
        self._max = max_staged_blocks

    def stage(self, target: list, arr, transform=None) -> None:
        """Append np.asarray(arr) (through transform, if given) to
        target at drain time; starts the host copy now."""
        _copy_to_host_async(arr)
        self._staged.append((target, arr, transform))
        self._open += 1

    def end_block(self) -> None:
        """Mark the end of one block's stage() calls; drains the oldest
        staged block once more than max_staged_blocks are pending."""
        self._block_sizes.append(self._open)
        self._open = 0
        while len(self._block_sizes) > self._max:
            self._drain_n(self._block_sizes.pop(0))

    def materialize(self) -> None:
        """Drain everything still staged (call after the dispatch loop)."""
        self._block_sizes.clear()
        self._open = 0
        self._drain_n(len(self._staged))

    def _drain_n(self, n: int) -> None:
        for target, arr, transform in self._staged[:n]:
            host = np.asarray(arr)
            target.append(transform(host) if transform else host)
        del self._staged[:n]


def _seed_pass1(seconds: float) -> None:
    """Feeds the pass-1 wall time into telemetry and the active
    watchdog's auto-deadline profile: pass 1 touches every row, so any
    single block is strictly cheaper and multiplier * this time is a
    generous per-block deadline (floored by the watchdog's
    min_timeout_s; explicit timeout_s overrides it entirely)."""
    rt_telemetry.record_duration("p1_bound_compact", seconds)
    wd = rt_watchdog.active()
    if wd is not None:
        wd.seed_profile(seconds)


def _pad_to(a, cap: int, fill):
    widths = ((0, cap - len(a)),) + ((0, 0),) * (a.ndim - 1)
    if isinstance(a, jax.Array):
        # Device-resident columns (streamed ingest) pad on device; np.pad
        # would silently download them.
        return jnp.pad(a, widths, constant_values=fill)
    return np.pad(a, widths, constant_values=fill)


def _bound_and_compact_host_staged(pid, pk, values, valid, min_v, max_v,
                                   min_s, max_s, mid, rows_key, cfg,
                                   row_chunk):
    """n > row_chunk: bound+compact chunk-by-chunk, stage survivors on host.

    Chunks split on privacy-id boundaries (L0 bounding is global per id);
    each chunk's survivors arrive already spk-sorted, the host merges them
    with one argsort over the concatenation.
    """
    order = np.argsort(pid, kind="stable")
    pid_s, pk_s, values_s, valid_s = (pid[order], pk[order], values[order],
                                      valid[order])
    b_pk, b_pair, b_leaf = [], [], []
    b_cols = {name: [] for name in executor.reduce_column_names(cfg)}
    start = 0
    for ci, end in enumerate(_chunk_ends(pid_s, row_chunk)):
        sl = slice(start, end)
        cap = round_capacity(end - start)
        spk, pair, cols, leaf, n_kept = _bounded_compact_kernel(
            _pad_to(pid_s[sl], cap, 0), _pad_to(pk_s[sl], cap, 0),
            _pad_to(values_s[sl], cap, 0), _pad_to(valid_s[sl], cap, False),
            min_v, max_v, min_s, max_s, mid, jax.random.fold_in(rows_key, ci),
            cfg)
        k = int(n_kept)  # the only per-chunk sync; bounds the d2h volume
        b_pk.append(np.asarray(spk[:k]))
        b_pair.append(np.asarray(pair[:k]))
        if cfg.quantiles:
            b_leaf.append(np.asarray(leaf[:k]))
        for name, col in cols.items():
            b_cols[name].append(np.asarray(col[:k]))
        start = end

    spk_all = np.concatenate(b_pk) if b_pk else np.zeros(0, np.int32)
    pair_all = np.concatenate(b_pair) if b_pair else np.zeros(0, bool)
    cols_all = {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in b_cols.items()
    }
    order2 = np.argsort(spk_all, kind="stable")
    leaf_all = None
    if cfg.quantiles:
        leaf_all = (np.concatenate(b_leaf)
                    if b_leaf else np.zeros(0, np.int32))[order2]
    return spk_all[order2], pair_all[order2], {
        name: col[order2] for name, col in cols_all.items()
    }, leaf_all


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"))
def _sharded_bound_compact(pid, pk, values, valid, min_v, max_v, min_s,
                           max_s, mid, rows_key, boundaries,
                           cfg: executor.KernelConfig, mesh):
    """Pass 1 over the mesh: per-shard bound + compact + spk-sort.

    Rows are pid-sharded, so contribution bounding (global per privacy id)
    is shard-local and the O(n log n) compaction sort — the dominant
    pass-1 cost — parallelizes D ways with zero collectives. Each shard
    also searchsorts its own stream against the block boundaries, so the
    host downloads one [S, n_blocks+1] offsets table instead of any rows.
    """
    from jax.sharding import PartitionSpec
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, shard_map
    SP = PartitionSpec

    def per_shard(pid_s, pk_s, values_s, valid_s, key_r, boundaries_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        key_s = jax.random.fold_in(key_r, shard_idx)
        spk_sorted, pair_s, cols_s, leaf_s, _ = _bound_compact_trace(
            pid_s, pk_s, values_s, valid_s, min_v, max_v, min_s, max_s, mid,
            key_s, cfg)
        starts = jnp.searchsorted(spk_sorted, boundaries_r,
                                  side="left").astype(jnp.int32)
        # all_gather -> replicated [S, n_blocks+1]: the driver needs every
        # shard's offsets on every host, and on a multi-controller mesh a
        # replicated table is the only layout host_fetch can read (a
        # process cannot address another host's table shard).
        starts = jax.lax.all_gather(starts, SHARD_AXIS, axis=0)
        if leaf_s is None:  # shard_map needs a concrete pytree leaf
            leaf_s = jnp.zeros(0, jnp.int32)
        return spk_sorted, pair_s, cols_s, leaf_s, starts

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(SP(SHARD_AXIS), SP(SHARD_AXIS),
                             SP(SHARD_AXIS), SP(SHARD_AXIS), SP(), SP()),
                   out_specs=(SP(SHARD_AXIS), SP(SHARD_AXIS),
                              SP(SHARD_AXIS), SP(SHARD_AXIS), SP()))
    return fn(pid, pk, values, valid, rows_key, boundaries)


_sharded_bound_compact = rt_aot.aot_probe("sharded_bound_compact",
                                          _sharded_bound_compact,
                                          static_argnames=("cfg", "mesh"))


@functools.partial(jax.jit, static_argnames=("cfg", "cap", "mesh"))
def _sharded_block_kernel(spk_all, pair_all, cols_all, leaf_all, lo_r, len_r,
                          base, min_v, max_v, mid, stds, key,
                          cfg: executor.KernelConfig, cap: int, mesh,
                          secure_tables=None):
    """Pass 2 over the mesh: one partition block, shard-local reduce + one
    [C] psum + replicated finalize.

    Each shard gathers its own `cap` stream rows at its own host-known
    offset (lo_r/len_r are per-shard tables indexed by axis_index),
    segment-sums them onto the block's dense [C] slice, and ONE psum over
    ICI combines the partials — the only collective. Selection + noise +
    kept-first compaction then run replicated under the same key, so every
    device holds identical O(kept)-transferable results.
    """
    from jax.sharding import PartitionSpec
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, shard_map
    SP = PartitionSpec

    def per_shard(spk_s, pair_s, cols_s, leaf_s, lo_all, len_all, stds_r,
                  key_r, tables_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        return _block_trace(spk_s, pair_s, cols_s, leaf_s,
                            lo_all[shard_idx], len_all[shard_idx], base,
                            min_v, max_v, mid, stds_r, key_r, cfg, cap,
                            tables_r, psum_axis=SHARD_AXIS)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(SP(SHARD_AXIS), SP(SHARD_AXIS),
                             SP(SHARD_AXIS), SP(SHARD_AXIS), SP(), SP(),
                             SP(), SP(), SP()),
                   out_specs=(SP(), SP(), SP()))
    return fn(spk_all, pair_all, cols_all, leaf_all, lo_r, len_r, stds, key,
              secure_tables)


_sharded_block_kernel = rt_aot.aot_probe(
    "sharded_block_kernel", _sharded_block_kernel,
    static_argnames=("cfg", "cap", "mesh"))


@functools.partial(jax.jit, static_argnames=("mesh",))
def _sharded_block_offsets(spk_all, boundaries, mesh):
    """Per-shard block offsets of the compacted stream against a NEW set
    of boundaries — the re-planning counterpart of the searchsorted fused
    into pass 1, used after an OOM degradation changes the block plan."""
    from jax.sharding import PartitionSpec
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, shard_map
    SP = PartitionSpec

    def per_shard(spk_s, boundaries_r):
        starts = jnp.searchsorted(spk_s, boundaries_r,
                                  side="left").astype(jnp.int32)
        # Replicated for the same multi-controller host_fetch reason as
        # the pass-1 offsets table.
        return jax.lax.all_gather(starts, SHARD_AXIS, axis=0)

    fn = shard_map(per_shard, mesh=mesh,
                   in_specs=(SP(SHARD_AXIS), SP()),
                   out_specs=SP())
    return fn(spk_all, boundaries)


_sharded_block_offsets = rt_aot.aot_probe("sharded_block_offsets",
                                          _sharded_block_offsets,
                                          static_argnames=("mesh",))


def _block_boundaries(base: int, capacity: int, n_blocks: int) -> np.ndarray:
    """int64 block boundaries over [base, base + n_blocks * capacity],
    clamped into int32 range: partition ids are < P <= int32 max and
    dropped rows carry the int32-max sentinel, so a clamped boundary still
    lands left of every sentinel (same overflow guard everywhere)."""
    return np.minimum(
        base + np.arange(n_blocks + 1, dtype=np.int64) * capacity,
        np.iinfo(np.int32).max).astype(np.int32)


@_runtime_entry("aggregate_blocked_sharded",
                fallback=_fallback_blocked_aggregate)
def aggregate_blocked_sharded(mesh,
                              pid,
                              pk,
                              values,
                              valid,
                              min_v,
                              max_v,
                              min_s,
                              max_s,
                              mid,
                              stds,
                              rng_key,
                              cfg: executor.KernelConfig,
                              *,
                              block_partitions: int = 1 << 20,
                              secure_tables=None,
                              reshard: str = "auto",
                              overlap: bool = False,
                              retry: Optional[rt_retry.RetryPolicy] = None,
                              journal: Optional[rt_journal.BlockJournal] = None,
                              job_id: Optional[str] = None
                              ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """aggregate_blocked over a device mesh: the huge-P counterpart of
    sharded.sharded_aggregate_arrays.

    The reference's unbounded-key regime scales across workers by handing
    the shuffle to Beam/Spark (pipeline_dp/pipeline_backend.py:339-352);
    here the same scaling is mesh-native: rows shard by privacy id (pass 1
    — bounding + the dominant compaction sort — runs D-way parallel with
    no collectives), and each partition block costs exactly one [C]-sized
    psum over ICI before replicated selection/noise. Dense [P] state never
    exists on any device, host traffic stays O(kept), and per-device HBM
    holds O(rows/D + C) — the mesh extends the single-device row capacity
    D-fold with no host staging anywhere on the device-resident path.

    Device-resident (streamed-ingest) columns reshard entirely on device:
    pid-hash bucketize -> one padded jax.lax.all_to_all over the mesh axis
    -> shard-local compaction (reshard.device_reshard_rows_by_pid); only a
    [D, D] count table and the [D, n_blocks+1] block-offset table ever
    cross to the host. Host-numpy inputs — which pay one upload regardless
    — take the exact load-balanced host permutation
    (sharded.shard_rows_by_pid), also reachable as the reshard="host"
    escape hatch. See stage_rows_to_mesh for the padding model.

    Failure semantics (shared with every blocked driver): transient block
    failures retry under the same fold_in key (bit-identical noise), OOM
    halves the partition block capacity and re-plans the remaining range,
    and a journal records each consumed block's drained results for
    resume — see README "Failure semantics". With elastic=True a
    device-fatal failure additionally rebuilds a smaller mesh from the
    surviving devices and re-enters here (block keys are independent of
    mesh geometry, so the degraded run replays the same release) — see
    README "Degraded-mesh semantics".

    Returns (kept_partition_ids int64[M], {metric: f[M]}) — identical
    contract to aggregate_blocked.
    """
    from pipelinedp_tpu.parallel.reshard import stage_rows_to_mesh

    # Chaos ingest seam (no-op without an active extreme_values fault).
    _poisoned = rt_faults.maybe_extreme_rows(values, pk)
    if _poisoned is not None:
        values = _poisoned

    P = cfg.n_partitions
    n_shards = mesh.devices.size
    pid, pk, values, valid = stage_rows_to_mesh(
        mesh, pid, pk, values, valid, reshard,
        values_dtype=np.dtype(executor._ftype()))

    rows_key, final_key = jax.random.split(rng_key, 2)
    stds = jnp.asarray(stds)

    C0 = min(block_partitions, P)
    n_blocks0 = -(-P // C0)
    boundaries0 = _block_boundaries(0, C0, n_blocks0)

    t_p1 = time.perf_counter()
    with rt_trace.span("contribution_bounding"):
        spk_all, pair_all, cols_all, leaf_all, starts = \
            _sharded_bound_compact(
                pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                rows_key, jnp.asarray(boundaries0), cfg, mesh)
        # The one per-aggregation host download that scales with n_blocks,
        # not rows: each shard's block offsets (host_fetch = sanctioned
        # under the transfer guard).
        starts0 = host_fetch(starts).reshape(n_shards, n_blocks0 + 1)
    _seed_pass1(time.perf_counter() - t_p1)

    output_names = [name for e in cfg.plan for name in e.outputs]
    kept_ids = []
    kept_outputs = {name: [] for name in output_names}
    job = job_id or "aggregate_blocked_sharded"

    drain = _StagedDrain()

    def append_record(record: rt_journal.BlockRecord):
        if record.n_kept:
            kept_ids.append(record.ids)
            for name, col in record.outputs.items():
                kept_outputs.setdefault(name, []).append(col)

    def run_range(base, C, gen, end):
        n_blocks = -(-(end - base) // C)
        if gen == 0 and C == C0:
            # Generation 0 starts at base 0 with capacity C0, so the
            # offsets fused into pass 1 are a prefix of the plan. (A
            # resumed plan journaled under a different capacity — the
            # _load_plan override warning — recomputes instead.)
            starts_r = starts0[:, :n_blocks + 1]
        else:
            starts_r = host_fetch(
                _sharded_block_offsets(
                    spk_all, jnp.asarray(_block_boundaries(base, C,
                                                           n_blocks)),
                    mesh)).reshape(n_shards, n_blocks + 1)

        def consume(j, result):
            b_base = base + j * C
            if isinstance(result, _Replay):
                append_record(result.record)
                drain.end_block()
                return
            n_kept, ids_sorted, outputs_sorted = result
            # Fail-closed sentinel BEFORE the journal persist: a
            # numerically poisoned block must never become a durable
            # record a later replay would release.
            rt_numeric.check_release(
                outputs_sorted, n_kept=n_kept,
                numeric_mode=cfg.numeric_mode,
                context=f"blocked meshed release (base {b_base})")
            k = int(n_kept)  # sync; gates O(kept) transfers
            if journal is not None:
                record = _materialize_block_record(ids_sorted,
                                                   outputs_sorted, k,
                                                   b_base)
                journal.put(job, rt_journal.block_key(b_base, C), record)
                append_record(record)
            elif k:
                drain.stage(kept_ids, ids_sorted[:k],
                            lambda h, base_=b_base: h.astype(np.int64) +
                            base_)
                for name, col in outputs_sorted.items():
                    drain.stage(kept_outputs.setdefault(name, []), col[:k])
            drain.end_block()

        def block_iter():
            for j in range(n_blocks):
                b_base = base + j * C
                if journal is not None:
                    record = journal.get(job,
                                         rt_journal.block_key(b_base, C))
                    if record is not None:
                        rt_telemetry.record("journal_replays", block=j)
                        yield (j, _Replay(record))
                        continue
                lo = starts_r[:, j].astype(np.int32)
                lens = (starts_r[:, j + 1] - starts_r[:, j]).astype(np.int32)
                if int(lens.sum()) == 0 and cfg.private_selection:
                    # Row-less on every shard: selection provably emits
                    # nothing.
                    continue
                c_actual = min(C, end - b_base)
                cfg_block = dataclasses.replace(cfg, n_partitions=c_actual)
                yield (j, functools.partial(
                    _sharded_block_kernel, spk_all, pair_all, cols_all,
                    leaf_all, jnp.asarray(lo), jnp.asarray(lens), b_base,
                    min_v, max_v, mid, stds,
                    _block_noise_key(final_key, gen, j), cfg_block,
                    round_capacity(int(lens.max())), mesh, secure_tables))

        _dispatch_blocks(block_iter(), consume, retry_policy=retry,
                         overlap=overlap)

    rt_retry.run_with_degradation(run_range, P, C0, journal=journal,
                                  job_id=job)
    drain.materialize()

    kept = (np.concatenate(kept_ids) if kept_ids else np.zeros(0, np.int64))
    return kept, {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in kept_outputs.items()
    }


def _selection_block_trace(spk_kept, lo, length, base, c_actual, key,
                           selection, cap: int, psum_axis=None):
    """Traceable body shared by the single-device and meshed selection
    block kernels: selection decisions for one partition block of the
    kept-pair stream.

    Gathers `cap` stream rows at host-known offset `lo`, scatter-adds the
    block's per-partition privacy-id counts into a dense [C] slice —
    psum'd over `psum_axis` under shard_map — draws the keep decisions,
    and sorts kept relative ids to the front so the host fetches exactly
    n_kept ids — the aggregate path's O(kept) compaction (_block_trace)
    applied to standalone selection.
    """
    from pipelinedp_tpu.ops import selection_ops
    idx = jnp.arange(cap, dtype=jnp.int32)
    valid = idx < length
    rel = jnp.where(valid,
                    jnp.take(spk_kept, lo + idx, mode="clip") - base,
                    c_actual).astype(jnp.int32)
    counts = jnp.zeros((c_actual + 1,), jnp.int32).at[rel].add(
        valid.astype(jnp.int32))[:c_actual]
    if psum_axis is not None:
        counts = jax.lax.psum(counts, psum_axis)
    keep = selection_ops.sample_keep_decisions(key, counts, selection)
    order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
    return keep.sum(), order


@functools.partial(jax.jit,
                   static_argnames=("c_actual", "selection", "cap"))
def _selection_block_kernel(spk_kept, lo, length, base, c_actual, key,
                            selection, cap: int):
    """Single-device selection block kernel (see _selection_block_trace)."""
    return _selection_block_trace(spk_kept, lo, length, base, c_actual, key,
                                  selection, cap)


_selection_block_kernel = rt_aot.aot_probe(
    "selection_block_kernel", _selection_block_kernel,
    static_argnames=("c_actual", "selection", "cap"))


@functools.partial(jax.jit,
                   static_argnames=("l0", "n_partitions", "mesh"))
def _sharded_select_compact(pid, pk, valid, rows_key, boundaries, l0: int,
                            n_partitions: int, mesh):
    """Selection pass 1 over the mesh: per-shard kept-pair compaction.

    Rows are pid-sharded, so pair dedupe + L0 sampling
    (executor.select_kept_pair_stream) are shard-local; each shard also
    searchsorts its own stream against the block boundaries.
    """
    from jax.sharding import PartitionSpec
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, shard_map
    SP = PartitionSpec

    def per_shard(pid_s, pk_s, valid_s, key_r, boundaries_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        key_s = jax.random.fold_in(key_r, shard_idx)
        spk_sorted, _ = executor.select_kept_pair_stream(
            pid_s, pk_s, valid_s, key_s, l0, n_partitions)
        starts = jnp.searchsorted(spk_sorted, boundaries_r,
                                  side="left").astype(jnp.int32)
        # Replicated offsets (all_gather): see _sharded_bound_compact.
        return spk_sorted, jax.lax.all_gather(starts, SHARD_AXIS, axis=0)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(SP(SHARD_AXIS), SP(SHARD_AXIS),
                             SP(SHARD_AXIS), SP(), SP()),
                   out_specs=(SP(SHARD_AXIS), SP()))
    return fn(pid, pk, valid, rows_key, boundaries)


_sharded_select_compact = rt_aot.aot_probe(
    "sharded_select_compact", _sharded_select_compact,
    static_argnames=("l0", "n_partitions", "mesh"))


@functools.partial(jax.jit,
                   static_argnames=("c_actual", "selection", "cap", "mesh"))
def _sharded_selection_block(spk_all, lo_r, len_r, base, c_actual, key,
                             selection, cap: int, mesh):
    """Selection pass 2 over the mesh: shard-local block counts + one [C]
    psum + replicated decisions/compaction (see _selection_block_trace)."""
    from jax.sharding import PartitionSpec
    from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, shard_map
    SP = PartitionSpec

    def per_shard(spk_s, lo_all, len_all, key_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        return _selection_block_trace(spk_s, lo_all[shard_idx],
                                      len_all[shard_idx], base, c_actual,
                                      key_r, selection, cap,
                                      psum_axis=SHARD_AXIS)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(SP(SHARD_AXIS), SP(), SP(), SP()),
                   out_specs=(SP(), SP()))
    return fn(spk_all, lo_r, len_r, key)


_sharded_selection_block = rt_aot.aot_probe(
    "sharded_selection_block", _sharded_selection_block,
    static_argnames=("c_actual", "selection", "cap", "mesh"))


@_runtime_entry("select_partitions_blocked_sharded",
                fallback=_fallback_blocked_select)
def select_partitions_blocked_sharded(mesh,
                                      pid,
                                      pk,
                                      valid,
                                      rng_key,
                                      l0: int,
                                      n_partitions: int,
                                      selection,
                                      *,
                                      block_partitions: int = 1 << 20,
                                      reshard: str = "auto",
                                      overlap: bool = False,
                                      retry: Optional[
                                          rt_retry.RetryPolicy] = None,
                                      journal: Optional[
                                          rt_journal.BlockJournal] = None,
                                      job_id: Optional[str] = None
                                      ) -> np.ndarray:
    """select_partitions_blocked over a device mesh.

    Rows shard by privacy id (device-resident inputs via the on-device
    all_to_all reshard, host inputs via the exact LPT permutation — see
    stage_rows_to_mesh); pass 1 — pair dedupe, L0 sampling and the
    compaction sort — runs D-way parallel with no further collectives;
    each partition block costs one int32[C] psum over ICI before
    replicated decisions. Neither dense [P] counts nor a bool[P] keep
    vector ever exists on any device, and host traffic stays
    O(rows/D + kept) for host inputs, O(D^2 + n_blocks + kept) for
    device-resident ones.

    Returns kept_partition_ids int64[M], ascending — identical contract
    to select_partitions_blocked.
    """
    from pipelinedp_tpu.parallel.reshard import stage_rows_to_mesh

    P = n_partitions
    n_shards = mesh.devices.size
    key_l0, key_sel = jax.random.split(rng_key)
    # Zero-width values column: selection never reads values, and a real
    # one would cost an O(rows) gather (or exchange) in the reshard.
    if isinstance(pid, jax.Array):
        dummy_values = jnp.zeros((pid.shape[0], 0), jnp.float32)
    else:
        dummy_values = np.zeros((len(pid), 0), np.float32)
    pid, pk, _, valid = stage_rows_to_mesh(mesh, pid, pk, dummy_values,
                                           valid, reshard)

    C0 = min(block_partitions, P)
    n_blocks0 = -(-P // C0)
    t_p1 = time.perf_counter()
    with rt_trace.span("contribution_bounding"):
        spk_all, starts = _sharded_select_compact(
            pid, pk, valid, key_l0,
            jnp.asarray(_block_boundaries(0, C0, n_blocks0)), l0, P, mesh)
        starts0 = host_fetch(starts).reshape(n_shards, n_blocks0 + 1)
    _seed_pass1(time.perf_counter() - t_p1)

    kept_ids = []
    job = job_id or "select_partitions_blocked_sharded"

    drain = _StagedDrain()

    def run_range(base, C, gen, end):
        n_blocks = -(-(end - base) // C)
        if gen == 0 and C == C0:
            # Generation 0 starts at base 0 with capacity C0, so the
            # offsets fused into pass 1 are a prefix of the plan. (A
            # resumed plan journaled under a different capacity — the
            # _load_plan override warning — recomputes instead.)
            starts_r = starts0[:, :n_blocks + 1]
        else:
            starts_r = host_fetch(
                _sharded_block_offsets(
                    spk_all, jnp.asarray(_block_boundaries(base, C,
                                                           n_blocks)),
                    mesh)).reshape(n_shards, n_blocks + 1)

        def consume(j, result):
            b_base = base + j * C
            if isinstance(result, _Replay):
                if result.record.n_kept:
                    kept_ids.append(result.record.ids)
                drain.end_block()
                return
            n_kept, order = result
            k = int(n_kept)  # sync; gates the O(kept) transfer
            if journal is not None:
                kept = order[:k]
                # Async-copy before the blocking materialization (the
                # dense _decode_rows discipline, shared via
                # _materialize_block_record on the aggregate routes).
                _copy_to_host_async(kept)
                ids = np.asarray(kept).astype(np.int64) + b_base  # staticcheck: disable=host-transfer — O(kept) journal materialization; the copy was started async on the line above
                journal.put(job, rt_journal.block_key(b_base, C),
                            rt_journal.BlockRecord(ids=ids, outputs={}))
                if k:
                    kept_ids.append(ids)
            elif k:
                drain.stage(kept_ids, order[:k],
                            lambda h, base_=b_base: h.astype(np.int64) +
                            base_)
            drain.end_block()

        def block_iter():
            for j in range(n_blocks):
                b_base = base + j * C
                if journal is not None:
                    record = journal.get(job,
                                         rt_journal.block_key(b_base, C))
                    if record is not None:
                        rt_telemetry.record("journal_replays", block=j)
                        yield (j, _Replay(record))
                        continue
                lo = starts_r[:, j].astype(np.int32)
                lens = (starts_r[:, j + 1] - starts_r[:, j]).astype(np.int32)
                if int(lens.sum()) == 0:
                    # Row-less on every shard: keep probability is 0.
                    continue
                c_actual = min(C, end - b_base)
                yield (j, functools.partial(
                    _sharded_selection_block, spk_all, jnp.asarray(lo),
                    jnp.asarray(lens), b_base, c_actual,
                    _block_noise_key(key_sel, gen, j), selection,
                    round_capacity(int(lens.max())), mesh))

        _dispatch_blocks(block_iter(), consume, retry_policy=retry,
                         overlap=overlap)

    rt_retry.run_with_degradation(run_range, P, C0, journal=journal,
                                  job_id=job)
    drain.materialize()

    if not kept_ids:
        return np.zeros(0, np.int64)
    return np.concatenate(kept_ids)


@_runtime_entry("select_partitions_blocked")
def select_partitions_blocked(pid,
                              pk,
                              valid,
                              rng_key,
                              l0: int,
                              n_partitions: int,
                              selection,
                              *,
                              block_partitions: int = 1 << 20,
                              overlap: bool = False,
                              retry: Optional[rt_retry.RetryPolicy] = None,
                              journal: Optional[
                                  rt_journal.BlockJournal] = None,
                              job_id: Optional[str] = None
                              ) -> np.ndarray:
    """Standalone DP partition selection over a huge partition space.

    Same semantics as executor.select_partitions_kernel (the reference's
    select_partitions at unbounded key cardinality,
    pipeline_dp/dp_engine.py:224-278), but neither the dense int32[P]
    count vector nor the bool[P] keep vector ever exists: pass 1 compacts
    the L0-sampled pair stream on device (executor.select_kept_pair_stream),
    pass 2 bins it into partition blocks and transfers only each block's
    kept ids — O(rows + kept) host traffic at any P.

    Returns kept_partition_ids int64[M], ascending.
    """
    P = n_partitions
    key_l0, key_sel = jax.random.split(rng_key)
    if not isinstance(pid, jax.Array):
        pid, pk, valid = np.asarray(pid), np.asarray(pk), np.asarray(valid)
    cap = round_capacity(len(pid))
    t_p1 = time.perf_counter()
    with rt_trace.span("contribution_bounding"):
        spk_sorted, _ = executor.select_kept_pair_stream(
            jnp.asarray(_pad_to(pid, cap, 0)),
            jnp.asarray(_pad_to(pk, cap, 0)),
            jnp.asarray(_pad_to(valid, cap, False)), key_l0, l0, P)
    _seed_pass1(time.perf_counter() - t_p1)

    C0 = min(block_partitions, P)
    kept_ids = []
    job = job_id or "select_partitions_blocked"

    drain = _StagedDrain()

    def run_range(base, C, gen, end):
        n_blocks = -(-(end - base) // C)
        block_starts = host_fetch(
            jnp.searchsorted(spk_sorted,
                             jnp.asarray(_block_boundaries(base, C,
                                                           n_blocks)),
                             side="left"))

        def consume(j, result):
            b_base = base + j * C
            if isinstance(result, _Replay):
                if result.record.n_kept:
                    kept_ids.append(result.record.ids)
                drain.end_block()
                return
            n_kept, order = result
            k = int(n_kept)  # sync; gates the O(kept) transfer
            if journal is not None:
                kept = order[:k]
                # Async-copy before the blocking materialization (the
                # dense _decode_rows discipline, shared via
                # _materialize_block_record on the aggregate routes).
                _copy_to_host_async(kept)
                ids = np.asarray(kept).astype(np.int64) + b_base  # staticcheck: disable=host-transfer — O(kept) journal materialization; the copy was started async on the line above
                journal.put(job, rt_journal.block_key(b_base, C),
                            rt_journal.BlockRecord(ids=ids, outputs={}))
                if k:
                    kept_ids.append(ids)
            elif k:
                drain.stage(kept_ids, order[:k],
                            lambda h, base_=b_base: h.astype(np.int64) +
                            base_)
            drain.end_block()

        def block_iter():
            for j in range(n_blocks):
                b_base = base + j * C
                if journal is not None:
                    record = journal.get(job,
                                         rt_journal.block_key(b_base, C))
                    if record is not None:
                        rt_telemetry.record("journal_replays", block=j)
                        yield (j, _Replay(record))
                        continue
                lo, hi = int(block_starts[j]), int(block_starts[j + 1])
                if lo == hi:
                    # Selection keeps empty partitions with probability 0
                    # (selection_ops.keep_probabilities: n <= 0 -> 0):
                    # row-less blocks provably emit nothing.
                    continue
                c_actual = min(C, end - b_base)
                yield (j, functools.partial(
                    _selection_block_kernel, spk_sorted, lo, hi - lo,
                    b_base, c_actual, _block_noise_key(key_sel, gen, j),
                    selection, round_capacity(hi - lo)))

        _dispatch_blocks(block_iter(), consume, retry_policy=retry,
                         overlap=overlap)

    rt_retry.run_with_degradation(run_range, P, C0, journal=journal,
                                  job_id=job)
    drain.materialize()

    if not kept_ids:
        return np.zeros(0, np.int64)
    out = np.concatenate(kept_ids)
    # Blocks are consumed in order but each block's kept ids arrive in
    # keep-first argsort order (ascending within the kept prefix because
    # the argsort is stable) — already globally ascending.
    return out


@_runtime_entry("aggregate_blocked")
def aggregate_blocked(pid,
                      pk,
                      values,
                      valid,
                      min_v,
                      max_v,
                      min_s,
                      max_s,
                      mid,
                      stds,
                      rng_key,
                      cfg: executor.KernelConfig,
                      *,
                      block_partitions: int = 1 << 20,
                      row_chunk: int = 1 << 24,
                      secure_tables=None,
                      phase_times: Optional[dict] = None,
                      overlap: bool = False,
                      retry: Optional[rt_retry.RetryPolicy] = None,
                      journal: Optional[rt_journal.BlockJournal] = None,
                      job_id: Optional[str] = None
                      ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """DP aggregation over an arbitrarily large partition space.

    Same semantics as executor.aggregate_kernel — including percentiles,
    whose per-block quantile trees descend lazily (O(C * branching) peak
    memory) over the block's own rows — but the partition axis is processed
    in blocks of `block_partitions` and only kept partitions are returned.

    phase_times: optional dict populated with per-phase wall-clock seconds
    (p1_bound_compact, block_offsets, p2_blocks_total, p2_sync_wait,
    p2_drain, blocks_dispatched, total) — the profiling hook used by
    benchmarks/profile_large_p.py so the profiler times THIS code, not a
    replica. Adds one device sync after pass 1; leave None in production.

    retry/journal/job_id: failure-semantics knobs (module docstring).
    Journaled runs materialize each block's results at consume time (one
    sync per block) so the record is durable immediately — the staged
    drain's transfer overlap is traded for crash-resumability.

    Returns (kept_partition_ids int64[M], {metric: f[M]}).
    """
    profiling = phase_times is not None
    t0 = time.perf_counter()
    # Chaos ingest seam (no-op without an active extreme_values fault).
    _poisoned = rt_faults.maybe_extreme_rows(values, pk)
    if _poisoned is not None:
        values = _poisoned
    P = cfg.n_partitions
    device_resident = isinstance(pid, jax.Array)
    if device_resident:
        # Streamed-ingest columns stay on device (no download/re-upload);
        # only the chunked host-staging regime below needs host copies.
        values = values.astype(executor._ftype())
    else:
        pid = np.asarray(pid)
        pk = np.asarray(pk)
        # Pre-cast to the kernel float dtype: the kernel casts on device
        # anyway, and float64 host arrays would double the upload volume.
        values = np.asarray(values, dtype=np.dtype(executor._ftype()))
        valid = np.asarray(valid)
    n = len(pid)

    rows_key, final_key = jax.random.split(rng_key, 2)
    stds = jnp.asarray(stds)

    # --- Pass 1: bound rows, compact + spk-sort the survivors. ------------
    with rt_trace.span("contribution_bounding", rows=n):
        if n <= row_chunk:
            # Device-resident: one kernel call, rows stay in HBM for
            # pass 2.
            cap = round_capacity(n)
            spk_all, pair_all, cols_all, leaf_all, _ = \
                _bounded_compact_kernel(
                    _pad_to(pid, cap, 0), _pad_to(pk, cap, 0),
                    _pad_to(values, cap, 0), _pad_to(valid, cap, False),
                    min_v, max_v, min_s, max_s, mid,
                    jax.random.fold_in(rows_key, 0), cfg)
        else:
            if device_resident:
                # Host staging re-chunks on privacy-id boundaries with
                # host argsorts; one download is unavoidable here.
                pid, pk, values, valid = (np.asarray(pid), np.asarray(pk),
                                          np.asarray(values),
                                          np.asarray(valid))
            spk_all, pair_all, cols_all, leaf_all = \
                _bound_and_compact_host_staged(
                    pid, pk, values, valid, min_v, max_v, min_s, max_s,
                    mid, rows_key, cfg, row_chunk)
            # Blocks gather from device-resident arrays either way;
            # per-block inputs are O(block rows), so upload the merged
            # stream once.
            spk_all = jnp.asarray(spk_all)
            pair_all = jnp.asarray(pair_all)
            cols_all = {
                name: jnp.asarray(col) for name, col in cols_all.items()
            }
            if leaf_all is not None:
                leaf_all = jnp.asarray(leaf_all)
    if profiling:
        # Not block_until_ready: it is a no-op on some remote platforms
        # (the tunneled axon TPU), which would shift pass-1 tail cost
        # into the block_offsets bucket. A one-element host fetch proves
        # the stream and all its producers finished. Zero-size streams
        # have no element to fetch; block_until_ready is the only sync
        # left (where it no-ops, an empty pass 1 is also dispatch-only —
        # but the timing is no longer SILENTLY dispatch-only on platforms
        # with a working wait).
        if spk_all.size:
            host_fetch(spk_all[-1])
        else:
            jax.block_until_ready(spk_all)
        phase_times["p1_bound_compact"] = time.perf_counter() - t0
    # Without profiling, pass 1 was dispatched async — the wall time here
    # under-measures, but the watchdog floors the auto deadline and takes
    # the max over later completed-guard observations, so the seed only
    # has to be the right order of magnitude.
    _seed_pass1(time.perf_counter() - t0)

    # --- Pass 2: bin by partition block, finalize each block. -------------
    # Dropped rows carry an int32-max sentinel > P, so searchsorted over
    # the compacted stream yields both block offsets AND the survivor
    # count (boundary overflow guard: _block_boundaries).
    C0 = min(block_partitions, P)
    output_names = [name for e in cfg.plan for name in e.outputs]
    kept_ids = []
    kept_outputs = {name: [] for name in output_names}
    job = job_id or "aggregate_blocked"
    n_dispatched_total = 0
    offsets_seconds = 0.0

    drain = _StagedDrain()

    def append_record(record: rt_journal.BlockRecord):
        if record.n_kept:
            kept_ids.append(record.ids)
            for name, col in record.outputs.items():
                kept_outputs.setdefault(name, []).append(col)

    def run_range(base, C, gen, end):
        nonlocal n_dispatched_total, offsets_seconds
        to = time.perf_counter()
        n_blocks = -(-(end - base) // C)
        block_starts = host_fetch(
            jnp.searchsorted(spk_all,
                             jnp.asarray(_block_boundaries(base, C,
                                                           n_blocks)),
                             side="left"))
        offsets_seconds += time.perf_counter() - to

        def consume(j, result):
            b_base = base + j * C
            if isinstance(result, _Replay):
                append_record(result.record)
                drain.end_block()
                return
            n_kept, ids_sorted, outputs_sorted = result
            # Fail-closed sentinel BEFORE the journal persist: a
            # numerically poisoned block must never become a durable
            # record a later replay would release.
            rt_numeric.check_release(
                outputs_sorted, n_kept=n_kept,
                numeric_mode=cfg.numeric_mode,
                context=f"blocked release (base {b_base})")
            ts = time.perf_counter()
            k = int(n_kept)  # sync; gates O(kept) transfers
            ta = time.perf_counter()
            if journal is not None:
                # Journaled runs materialize per block (one sync each) so
                # the record is durable the moment the block is consumed —
                # the overlap the staged drain buys is traded for
                # crash-resumability (the overlapped drainer thread takes
                # that sync off the dispatch path; the copies themselves
                # still batch through copy_to_host_async).
                record = _materialize_block_record(ids_sorted,
                                                   outputs_sorted, k,
                                                   b_base)
                journal.put(job, rt_journal.block_key(b_base, C), record)
                append_record(record)
            elif k:
                drain.stage(kept_ids, ids_sorted[:k],
                            lambda h, base_=b_base: h.astype(np.int64) +
                            base_)
                for name, col in outputs_sorted.items():
                    drain.stage(kept_outputs.setdefault(name, []), col[:k])
            drain.end_block()
            if profiling:
                # Sync wait (device still computing) and drain are
                # attributed separately — conflating them would re-create
                # the transfer-bound misdiagnosis this hook exists to
                # prevent. Per-block drain time is stage/flush overhead
                # (the O(kept) transfers are async and mostly land in the
                # post-loop materialize() increment, or in end_block()
                # flushes of blocks older than the window).
                phase_times["p2_sync_wait"] = (
                    phase_times.get("p2_sync_wait", 0.0) + ta - ts)
                phase_times["p2_drain"] = (phase_times.get("p2_drain", 0.0) +
                                           time.perf_counter() - ta)

        def block_iter():
            for j in range(n_blocks):
                b_base = base + j * C
                if journal is not None:
                    record = journal.get(job,
                                         rt_journal.block_key(b_base, C))
                    if record is not None:
                        rt_telemetry.record("journal_replays", block=j)
                        yield (j, _Replay(record))
                        continue
                lo, hi = int(block_starts[j]), int(block_starts[j + 1])
                if lo == hi and cfg.private_selection:
                    # Private selection keeps empty partitions with
                    # probability 0 (selection_ops.keep_probabilities:
                    # n <= 0 -> 0), so row-less blocks provably emit
                    # nothing — skip their device work. In the sparse
                    # 10^9-partition regime this skips nearly every block.
                    continue
                c_actual = min(C, end - b_base)
                cfg_block = dataclasses.replace(cfg, n_partitions=c_actual)
                yield (j, functools.partial(
                    _block_kernel_dev, spk_all, pair_all, cols_all,
                    leaf_all, lo, hi - lo, b_base, min_v, max_v, mid, stds,
                    _block_noise_key(final_key, gen, j), cfg_block,
                    round_capacity(hi - lo), secure_tables))

        n_dispatched_total += _dispatch_blocks(block_iter(), consume,
                                               retry_policy=retry,
                                               overlap=overlap)

    t2 = time.perf_counter()
    rt_retry.run_with_degradation(run_range, P, C0, journal=journal,
                                  job_id=job)
    td = time.perf_counter()
    drain.materialize()
    if profiling:
        now = time.perf_counter()
        phase_times["block_offsets"] = offsets_seconds
        phase_times["p2_drain"] = (phase_times.get("p2_drain", 0.0) +
                                   now - td)
        phase_times["p2_blocks_total"] = now - t2
        phase_times["blocks_dispatched"] = n_dispatched_total
        phase_times["total"] = now - t0

    # Each block emits kept partitions in ascending relative id (the compact
    # sort is stable) and blocks are consumed in ascending order, so the
    # concatenation is already globally ascending.
    kept = (np.concatenate(kept_ids) if kept_ids else np.zeros(0, np.int64))
    return kept, {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in kept_outputs.items()
    }
