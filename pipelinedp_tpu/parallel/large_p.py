"""Very large partition spaces: blocked partition-axis execution.

The dense fused kernel materializes [0, P) columns — ideal up to P ~ 10^6,
but at P = 10^7..10^9 (the reference's unbounded-key shuffle regime,
``pipeline_dp/pipeline_backend.py:339-352``) a replicated dense partition
axis no longer fits. This module shards the PARTITION axis instead:

  1. **Bound once** (device, chunked over rows): contribution bounding is a
     row-space computation (executor.bounded_row_columns) independent of P.
     Row chunks split on privacy-id boundaries so every id's pairs stay in
     one chunk — the same co-location invariant the pid-sharded multi-chip
     path uses.
  2. **Bin by partition block** (host, vectorized argsort): bounded rows are
     ordered by partition id; block b owns partitions [b*C, (b+1)*C).
  3. **Finalize per block** (device): each block segment-sums its own rows
     into a dense [C] slice and runs DP selection + noise on just that slice
     (selection and noise are pointwise over partitions, so blocks are
     independent — no collective, no rescans: total work is O(n log n + P)).
  4. **Compact**: only kept partitions are emitted, so output size is
     O(kept), not O(P).

Peak device memory is O(row_chunk + C) regardless of P.
"""

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import executor


def round_capacity(x: int, min_cap: int = 8) -> int:
    """Round up keeping 4 significant bits (<= 1/16 ~ 6.25% slack, 12.5%
    worst-case just above a power of two).

    Bounds the number of distinct padded shapes (so the jit cache stays
    small) without the up-to-2x waste of next-power-of-two padding.
    """
    x = max(int(x), min_cap)
    step = 1 << max((x - 1).bit_length() - 4, 3)
    return -(-x // step) * step


@functools.partial(jax.jit, static_argnames=("cfg",))
def _bounded_rows_kernel(pid, pk, values, valid, min_v, max_v, min_s, max_s,
                         mid, key, cfg: executor.KernelConfig):
    spk, keep_row, pair_start, reduce_cols, _ = executor.bounded_row_columns(
        pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, key, cfg)
    return spk, keep_row, pair_start, reduce_cols


@functools.partial(jax.jit, static_argnames=("cfg",))
def _block_kernel(spk_rel, keep_row, pair_start, reduce_cols, min_v, mid,
                  stds, key, cfg: executor.KernelConfig, secure_tables=None):
    cols = executor.reduce_rows_to_partitions(spk_rel, keep_row, pair_start,
                                              reduce_cols, cfg.n_partitions,
                                              cfg.vector_size)
    return executor.finalize(cols, min_v, mid, stds, key, cfg, secure_tables)


def _chunk_ends(pid_sorted: np.ndarray, row_chunk: int) -> np.ndarray:
    """Chunk end offsets, each extended to the next privacy-id boundary.

    A privacy id's rows must stay in one chunk (L0 bounding is global per
    id), so a single id with more rows than row_chunk forces an oversized
    chunk — the one irreducible violation of the O(row_chunk) memory bound;
    it is logged so the operator knows which workload property caused it.
    """
    import logging
    n = len(pid_sorted)
    ends = []
    start = 0
    while start < n:
        end = min(start + row_chunk, n)
        if end < n:
            end = int(
                np.searchsorted(pid_sorted, pid_sorted[end - 1],
                                side="right"))
        if end - start > 2 * row_chunk:
            logging.warning(
                "large_p: a single privacy id spans %d rows (> 2x row_chunk="
                "%d); its chunk cannot be split without breaking per-id "
                "contribution bounding. Device memory for this chunk scales "
                "with that id's row count.", end - start, row_chunk)
        ends.append(end)
        start = end
    return np.asarray(ends)


def aggregate_blocked(pid,
                      pk,
                      values,
                      valid,
                      min_v,
                      max_v,
                      min_s,
                      max_s,
                      mid,
                      stds,
                      rng_key,
                      cfg: executor.KernelConfig,
                      *,
                      block_partitions: int = 1 << 20,
                      row_chunk: int = 1 << 24,
                      secure_tables=None
                      ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """DP aggregation over an arbitrarily large partition space.

    Same semantics as executor.aggregate_kernel (minus percentiles), but the
    partition axis is processed in blocks of `block_partitions` and only
    kept partitions are returned.

    Returns (kept_partition_ids int64[M], {metric: f[M]}).
    """
    if cfg.quantiles:
        raise NotImplementedError(
            "PERCENTILE is not supported on the blocked large-partition "
            "path; use the dense kernel (quantile trees already chunk the "
            "partition axis internally).")
    P = cfg.n_partitions
    pid = np.asarray(pid)
    pk = np.asarray(pk)
    values = np.asarray(values)
    valid = np.asarray(valid)

    rows_key, final_key = jax.random.split(rng_key, 2)

    # --- Pass 1: bound rows, chunked on privacy-id boundaries. ------------
    order = np.argsort(pid, kind="stable")
    pid_s, pk_s, values_s, valid_s = (pid[order], pk[order], values[order],
                                      valid[order])
    b_pk, b_pair = [], []
    b_cols = {name: [] for name in executor.reduce_column_names(cfg)}
    start = 0
    for ci, end in enumerate(_chunk_ends(pid_s, row_chunk)):
        sl = slice(start, end)
        cap = round_capacity(end - start)
        pad = cap - (end - start)

        def padded(a, fill=0):
            widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
            return np.pad(a[sl], widths, constant_values=fill)

        spk, keep, pair, cols = _bounded_rows_kernel(
            padded(pid_s), padded(pk_s), padded(values_s),
            padded(valid_s, False), min_v, max_v, min_s, max_s, mid,
            jax.random.fold_in(rows_key, ci), cfg)
        keep = np.asarray(keep)
        b_pk.append(np.asarray(spk)[keep])
        b_pair.append(np.asarray(pair)[keep])
        for name, col in cols.items():
            b_cols[name].append(np.asarray(col)[keep])
        start = end

    spk_all = np.concatenate(b_pk) if b_pk else np.zeros(0, np.int32)
    pair_all = np.concatenate(b_pair) if b_pair else np.zeros(0, bool)
    cols_all = {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in b_cols.items()
    }

    # --- Pass 2: bin by partition block, finalize each block. -------------
    order2 = np.argsort(spk_all, kind="stable")
    spk_all = spk_all[order2]
    pair_all = pair_all[order2]
    cols_all = {name: col[order2] for name, col in cols_all.items()}

    C = min(block_partitions, P)
    n_blocks = -(-P // C)
    block_starts = np.searchsorted(spk_all,
                                   np.arange(n_blocks + 1) * C,
                                   side="left")
    output_names = [name for e in cfg.plan for name in e.outputs]
    kept_ids = []
    kept_outputs = {name: [] for name in output_names}
    for b in range(n_blocks):
        lo, hi = int(block_starts[b]), int(block_starts[b + 1])
        if lo == hi and cfg.private_selection:
            # Private selection keeps empty partitions with probability 0
            # (selection_ops.keep_probabilities: n <= 0 -> 0), so row-less
            # blocks provably emit nothing — skip their device work. In the
            # sparse 10^9-partition regime this skips nearly every block.
            continue
        c_actual = min(C, P - b * C)
        cfg_block = dataclasses.replace(cfg, n_partitions=c_actual)
        cap = round_capacity(hi - lo)
        pad = cap - (hi - lo)

        def padded(a, fill):
            widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
            return np.pad(a, widths, constant_values=fill)

        spk_rel = (spk_all[lo:hi].astype(np.int64) - b * C).astype(np.int32)
        outputs, keep, _ = _block_kernel(
            padded(spk_rel, c_actual),
            padded(np.ones(hi - lo, bool), False),
            padded(pair_all[lo:hi], False),
            {name: padded(col[lo:hi], 0) for name, col in cols_all.items()},
            min_v, mid, jnp.asarray(stds), jax.random.fold_in(final_key, b),
            cfg_block, secure_tables)
        keep = np.asarray(keep)
        idx = np.nonzero(keep)[0]
        kept_ids.append(idx.astype(np.int64) + b * C)
        for name, col in outputs.items():
            kept_outputs.setdefault(name, []).append(np.asarray(col)[idx])

    kept = (np.concatenate(kept_ids) if kept_ids else np.zeros(0, np.int64))
    return kept, {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in kept_outputs.items()
    }
