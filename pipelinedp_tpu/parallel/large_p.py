"""Very large partition spaces: blocked partition-axis execution.

The dense fused kernel materializes [0, P) columns — ideal up to P ~ 10^6,
but at P = 10^7..10^9 (the reference's unbounded-key shuffle regime,
``pipeline_dp/pipeline_backend.py:339-352``) a replicated dense partition
axis no longer fits. This module shards the PARTITION axis instead:

  1. **Bound once** (device): contribution bounding is a row-space
     computation (executor.bounded_row_columns) independent of P; the same
     kernel then compacts (drops bounded-away rows) and orders the
     survivors by partition id — all on device, one extra payload sort.
  2. **Bin by partition block**: block b owns partitions [b*C, (b+1)*C);
     block row ranges come from one searchsorted over the compacted stream.
  3. **Finalize per block** (device): each block segment-sums its own rows
     into a dense [C] slice and runs DP selection + noise on just that
     slice (selection and noise are pointwise over partitions, so blocks
     are independent — no collective, no rescans: total work is
     O(n log n + P)).
  4. **Compact**: kept partitions are sorted to the front ON DEVICE, so
     only O(kept) values ever cross the device->host boundary — the
     dominant cost under a remote-attached chip, where transferring dense
     [C] outputs per block costs more than all device compute combined.

Two row-staging regimes, switched on whether the rows fit one device chunk:

  * **Device-resident** (n <= row_chunk, the common case): rows never
    return to the host between passes; per-block inputs are device-side
    gathers at host-known offsets. Host traffic = block offsets + kept
    results.
  * **Host-staged** (n > row_chunk): row chunks split on privacy-id
    boundaries are bounded+compacted on device, the compacted survivors
    staged back to host, merged, and re-uploaded per block — preserving
    the O(row_chunk + C) device-memory bound at any n.
"""

import dataclasses
import functools
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import executor


def round_capacity(x: int, min_cap: int = 8) -> int:
    """Round up keeping 4 significant bits (<= 1/16 ~ 6.25% slack, 12.5%
    worst-case just above a power of two).

    Bounds the number of distinct padded shapes (so the jit cache stays
    small) without the up-to-2x waste of next-power-of-two padding.
    """
    x = max(int(x), min_cap)
    step = 1 << max((x - 1).bit_length() - 4, 3)
    return -(-x // step) * step


@functools.partial(jax.jit, static_argnames=("cfg",))
def _bounded_compact_kernel(pid, pk, values, valid, min_v, max_v, min_s,
                            max_s, mid, key, cfg: executor.KernelConfig):
    """Bound contributions, drop bounded-away rows, order by partition.

    Returns (spk, pair_start, reduce_cols, leaf, n_kept): the surviving
    bounded rows sorted by partition id (dropped rows carry an int32-max
    sentinel key and sort to the tail; n_kept counts the survivors). With
    percentiles, `leaf` carries each row's quantile-tree leaf index through
    the same compaction sort (None otherwise).
    """
    spk, keep_row, pair_start, reduce_cols, qrows = \
        executor.bounded_row_columns(pid, pk, values, valid, min_v, max_v,
                                     min_s, max_s, mid, key, cfg)
    names = list(reduce_cols)
    sort_key = jnp.where(keep_row, spk, jnp.iinfo(jnp.int32).max)
    payloads = ([pair_start.astype(jnp.int32)] +
                [reduce_cols[m] for m in names])
    if cfg.quantiles:
        payloads.append(qrows[1])  # per-row leaf index
    (spk_s,), pay = executor._sort_rows([sort_key], payloads)
    cols_s = {m: pay[1 + j] for j, m in enumerate(names)}
    leaf_s = pay[-1] if cfg.quantiles else None
    return spk_s, pay[0].astype(bool), cols_s, leaf_s, keep_row.sum()


@functools.partial(jax.jit, static_argnames=("cfg", "cap"))
def _block_kernel_dev(spk_s, pair_s, cols_s, leaf_s, lo, length, base, min_v,
                      max_v, mid, stds, key, cfg: executor.KernelConfig,
                      cap: int, secure_tables=None):
    """Finalize one partition block from the device-resident row stream.

    Gathers `cap` rows at host-known offset `lo` (rows beyond `length` are
    masked), reduces them onto the block's dense [C] slice, runs selection
    + noise (and, with percentiles, the block's quantile descent), and
    sorts kept partitions to the front so the host can fetch exactly
    n_kept results.
    """
    idx = jnp.arange(cap, dtype=jnp.int32)
    valid = idx < length
    take = lambda a: jnp.take(a, lo + idx, mode="clip")
    spk_rel = jnp.where(valid, take(spk_s) - base, cfg.n_partitions)
    spk_rel = spk_rel.astype(jnp.int32)
    pair = take(pair_s) & valid
    cols = {
        name: jnp.where(valid, take(col), jnp.zeros((), col.dtype))
        for name, col in cols_s.items()
    }
    # Rows were compacted into (kept-first, spk-ascending) order by
    # _bounded_compact_kernel; the block slice preserves it, and masked
    # tail rows carry the cfg.n_partitions sentinel — still sorted.
    dense = executor.reduce_rows_to_partitions(spk_rel, valid, pair, cols,
                                               cfg.n_partitions,
                                               cfg.vector_size,
                                               presorted=True)
    outputs, keep, _ = executor.finalize(dense, min_v, mid, stds, key, cfg,
                                         secure_tables)
    if cfg.quantiles:
        # Per-block quantile trees over just the block's rows: relative
        # partition ids index trees [0, C); quantile_outputs picks the lazy
        # descent whenever the block exceeds one dense histogram chunk, so
        # peak memory stays O(C * branching), never O(C * leaves).
        qkey = jax.random.fold_in(key, 7919)
        outputs.update(
            executor.quantile_outputs((spk_rel, take(leaf_s), valid), min_v,
                                      max_v, stds, qkey, cfg,
                                      secure_tables=secure_tables))
    order = jnp.argsort(~keep, stable=True)  # kept partitions first
    ids_sorted = order.astype(jnp.int32)
    outputs_sorted = {name: col[order] for name, col in outputs.items()}
    return keep.sum(), ids_sorted, outputs_sorted


def _chunk_ends(pid_sorted: np.ndarray, row_chunk: int) -> np.ndarray:
    """Chunk end offsets, each extended to the next privacy-id boundary.

    A privacy id's rows must stay in one chunk (L0 bounding is global per
    id), so a single id with more rows than row_chunk forces an oversized
    chunk — the one irreducible violation of the O(row_chunk) memory bound;
    it is logged so the operator knows which workload property caused it.
    """
    import logging
    n = len(pid_sorted)
    ends = []
    start = 0
    while start < n:
        end = min(start + row_chunk, n)
        if end < n:
            end = int(
                np.searchsorted(pid_sorted, pid_sorted[end - 1],
                                side="right"))
        if end - start > 2 * row_chunk:
            logging.warning(
                "large_p: a single privacy id spans %d rows (> 2x row_chunk="
                "%d); its chunk cannot be split without breaking per-id "
                "contribution bounding. Device memory for this chunk scales "
                "with that id's row count.", end - start, row_chunk)
        ends.append(end)
        start = end
    return np.asarray(ends)


def _pad_to(a, cap: int, fill):
    widths = ((0, cap - len(a)),) + ((0, 0),) * (a.ndim - 1)
    if isinstance(a, jax.Array):
        # Device-resident columns (streamed ingest) pad on device; np.pad
        # would silently download them.
        return jnp.pad(a, widths, constant_values=fill)
    return np.pad(a, widths, constant_values=fill)


def _bound_and_compact_host_staged(pid, pk, values, valid, min_v, max_v,
                                   min_s, max_s, mid, rows_key, cfg,
                                   row_chunk):
    """n > row_chunk: bound+compact chunk-by-chunk, stage survivors on host.

    Chunks split on privacy-id boundaries (L0 bounding is global per id);
    each chunk's survivors arrive already spk-sorted, the host merges them
    with one argsort over the concatenation.
    """
    order = np.argsort(pid, kind="stable")
    pid_s, pk_s, values_s, valid_s = (pid[order], pk[order], values[order],
                                      valid[order])
    b_pk, b_pair, b_leaf = [], [], []
    b_cols = {name: [] for name in executor.reduce_column_names(cfg)}
    start = 0
    for ci, end in enumerate(_chunk_ends(pid_s, row_chunk)):
        sl = slice(start, end)
        cap = round_capacity(end - start)
        spk, pair, cols, leaf, n_kept = _bounded_compact_kernel(
            _pad_to(pid_s[sl], cap, 0), _pad_to(pk_s[sl], cap, 0),
            _pad_to(values_s[sl], cap, 0), _pad_to(valid_s[sl], cap, False),
            min_v, max_v, min_s, max_s, mid, jax.random.fold_in(rows_key, ci),
            cfg)
        k = int(n_kept)  # the only per-chunk sync; bounds the d2h volume
        b_pk.append(np.asarray(spk[:k]))
        b_pair.append(np.asarray(pair[:k]))
        if cfg.quantiles:
            b_leaf.append(np.asarray(leaf[:k]))
        for name, col in cols.items():
            b_cols[name].append(np.asarray(col[:k]))
        start = end

    spk_all = np.concatenate(b_pk) if b_pk else np.zeros(0, np.int32)
    pair_all = np.concatenate(b_pair) if b_pair else np.zeros(0, bool)
    cols_all = {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in b_cols.items()
    }
    order2 = np.argsort(spk_all, kind="stable")
    leaf_all = None
    if cfg.quantiles:
        leaf_all = (np.concatenate(b_leaf)
                    if b_leaf else np.zeros(0, np.int32))[order2]
    return spk_all[order2], pair_all[order2], {
        name: col[order2] for name, col in cols_all.items()
    }, leaf_all


def aggregate_blocked(pid,
                      pk,
                      values,
                      valid,
                      min_v,
                      max_v,
                      min_s,
                      max_s,
                      mid,
                      stds,
                      rng_key,
                      cfg: executor.KernelConfig,
                      *,
                      block_partitions: int = 1 << 20,
                      row_chunk: int = 1 << 24,
                      secure_tables=None,
                      phase_times: Optional[dict] = None
                      ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """DP aggregation over an arbitrarily large partition space.

    Same semantics as executor.aggregate_kernel — including percentiles,
    whose per-block quantile trees descend lazily (O(C * branching) peak
    memory) over the block's own rows — but the partition axis is processed
    in blocks of `block_partitions` and only kept partitions are returned.

    phase_times: optional dict populated with per-phase wall-clock seconds
    (p1_bound_compact, block_offsets, p2_blocks_total, p2_drain,
    blocks_dispatched, total) — the profiling hook used by
    benchmarks/profile_large_p.py so the profiler times THIS code, not a
    replica. Adds one device sync after pass 1; leave None in production.

    Returns (kept_partition_ids int64[M], {metric: f[M]}).
    """
    profiling = phase_times is not None
    t0 = time.perf_counter()
    P = cfg.n_partitions
    device_resident = isinstance(pid, jax.Array)
    if device_resident:
        # Streamed-ingest columns stay on device (no download/re-upload);
        # only the chunked host-staging regime below needs host copies.
        values = values.astype(executor._ftype())
    else:
        pid = np.asarray(pid)
        pk = np.asarray(pk)
        # Pre-cast to the kernel float dtype: the kernel casts on device
        # anyway, and float64 host arrays would double the upload volume.
        values = np.asarray(values, dtype=np.dtype(executor._ftype()))
        valid = np.asarray(valid)
    n = len(pid)

    rows_key, final_key = jax.random.split(rng_key, 2)
    stds = jnp.asarray(stds)

    # --- Pass 1: bound rows, compact + spk-sort the survivors. ------------
    if n <= row_chunk:
        # Device-resident: one kernel call, rows stay in HBM for pass 2.
        cap = round_capacity(n)
        spk_all, pair_all, cols_all, leaf_all, _ = _bounded_compact_kernel(
            _pad_to(pid, cap, 0), _pad_to(pk, cap, 0),
            _pad_to(values, cap, 0), _pad_to(valid, cap, False), min_v,
            max_v, min_s, max_s, mid, jax.random.fold_in(rows_key, 0), cfg)
    else:
        if device_resident:
            # Host staging re-chunks on privacy-id boundaries with host
            # argsorts; one download is unavoidable in this regime.
            pid, pk, values, valid = (np.asarray(pid), np.asarray(pk),
                                      np.asarray(values), np.asarray(valid))
        spk_all, pair_all, cols_all, leaf_all = \
            _bound_and_compact_host_staged(
                pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                rows_key, cfg, row_chunk)
        # Blocks gather from device-resident arrays either way; per-block
        # inputs are O(block rows), so upload the merged stream once.
        spk_all = jnp.asarray(spk_all)
        pair_all = jnp.asarray(pair_all)
        cols_all = {name: jnp.asarray(col) for name, col in cols_all.items()}
        if leaf_all is not None:
            leaf_all = jnp.asarray(leaf_all)
    if profiling:
        jax.block_until_ready(spk_all)
        phase_times["p1_bound_compact"] = time.perf_counter() - t0

    # --- Pass 2: bin by partition block, finalize each block. -------------
    t1 = time.perf_counter()
    C = min(block_partitions, P)
    n_blocks = -(-P // C)
    # Dropped rows carry an int32-max sentinel > P, so searchsorted over
    # the compacted stream yields both block offsets AND the survivor count.
    # Boundaries in int64 on host, clamped into int32 range for the device
    # searchsorted: partition ids are < P <= int32 max and dropped rows
    # carry the int32-max sentinel, so a clamped boundary still lands left
    # of every sentinel. (Unclamped int32 arithmetic would overflow when P
    # is within one block of 2^31 and silently drop the final blocks.)
    boundaries = np.minimum(
        np.arange(n_blocks + 1, dtype=np.int64) * C,
        np.iinfo(np.int32).max).astype(np.int32)
    block_starts = np.asarray(
        jnp.searchsorted(spk_all, jnp.asarray(boundaries), side="left"))
    if profiling:
        phase_times["block_offsets"] = time.perf_counter() - t1
    output_names = [name for e in cfg.plan for name in e.outputs]
    kept_ids = []
    kept_outputs = {name: [] for name in output_names}

    def consume(b, result):
        n_kept, ids_sorted, outputs_sorted = result
        ts = time.perf_counter()
        k = int(n_kept)  # sync; gates O(kept) transfers
        ta = time.perf_counter()
        if k:
            kept_ids.append(
                np.asarray(ids_sorted[:k]).astype(np.int64) + b * C)
            for name, col in outputs_sorted.items():
                kept_outputs.setdefault(name, []).append(
                    np.asarray(col[:k]))
        if profiling:
            # Sync wait (device still computing) and drain (the O(kept)
            # transfers) are attributed separately — conflating them would
            # re-create the transfer-bound misdiagnosis this hook exists
            # to prevent.
            phase_times["p2_sync_wait"] = (
                phase_times.get("p2_sync_wait", 0.0) + ta - ts)
            phase_times["p2_drain"] = (phase_times.get("p2_drain", 0.0) +
                                       time.perf_counter() - ta)

    # Dispatch ahead of the sync point: jax execution is async, so the
    # device pipelines upcoming block kernels while the host drains earlier
    # results — one latency-bound sync per block would otherwise dominate
    # under a remote-attached chip. The window is bounded: each in-flight
    # block pins O(C) output buffers in HBM, and an unbounded pipeline over
    # P/C blocks would hold O(P) results — the exact footprint this module
    # exists to avoid.
    max_in_flight = 8
    pending = []
    n_dispatched = 0
    t2 = time.perf_counter()
    for b in range(n_blocks):
        lo, hi = int(block_starts[b]), int(block_starts[b + 1])
        if lo == hi and cfg.private_selection:
            # Private selection keeps empty partitions with probability 0
            # (selection_ops.keep_probabilities: n <= 0 -> 0), so row-less
            # blocks provably emit nothing — skip their device work. In the
            # sparse 10^9-partition regime this skips nearly every block.
            continue
        n_dispatched += 1
        c_actual = min(C, P - b * C)
        cfg_block = dataclasses.replace(cfg, n_partitions=c_actual)
        pending.append((b, _block_kernel_dev(spk_all, pair_all, cols_all,
                                             leaf_all, lo, hi - lo, b * C,
                                             min_v, max_v, mid, stds,
                                             jax.random.fold_in(final_key, b),
                                             cfg_block,
                                             round_capacity(hi - lo),
                                             secure_tables)))
        if len(pending) >= max_in_flight:
            consume(*pending.pop(0))
    for entry in pending:
        consume(*entry)
    if profiling:
        now = time.perf_counter()
        phase_times["p2_blocks_total"] = now - t2
        phase_times["blocks_dispatched"] = n_dispatched
        phase_times["total"] = now - t0

    # Each block emits kept partitions in ascending relative id (the compact
    # sort is stable) and blocks are consumed in ascending order, so the
    # concatenation is already globally ascending.
    kept = (np.concatenate(kept_ids) if kept_ids else np.zeros(0, np.int64))
    return kept, {
        name: (np.concatenate(chunks) if chunks else np.zeros(0))
        for name, chunks in kept_outputs.items()
    }
