"""Multi-chip execution: mesh construction and sharded aggregation."""

from pipelinedp_tpu.parallel.mesh import make_mesh
from pipelinedp_tpu.parallel.sharded import (
    shard_rows_by_pid,
    sharded_aggregate_arrays,
)
