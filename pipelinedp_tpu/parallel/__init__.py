"""Multi-chip execution: mesh construction, row resharding (host-staged
or on-device all_to_all), and sharded aggregation."""

from pipelinedp_tpu.parallel.mesh import (
    initialize_distributed,
    is_fully_addressable,
    local_devices,
    make_mesh,
    process_count,
    process_index,
)
from pipelinedp_tpu.parallel.reshard import (
    device_reshard_rows_by_pid,
    stage_rows_to_mesh,
)
from pipelinedp_tpu.parallel.sharded import (
    shard_rows_by_pid,
    sharded_aggregate_arrays,
)
