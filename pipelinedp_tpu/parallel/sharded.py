"""Multi-chip sharded DP aggregation (shard_map + psum over ICI).

Strategy (SURVEY.md §2.5 "TPU-native equivalent"): the reference's three
keyed shuffles become one on-device exchange —

  1. Rows are sharded by privacy-unit id, so all of a privacy unit's rows
     live on one shard and contribution bounding (the by-pid "shuffle") is
     shard-local. Device-resident inputs (streamed ingest) are resharded
     entirely on device: pid-hash bucketize -> padded jax.lax.all_to_all
     over the mesh axis -> shard-local compaction
     (parallel/reshard.device_reshard_rows_by_pid); only a [D, D] count
     table ever crosses to the host. Host-numpy inputs — which pay one
     upload regardless — take the exact load-balanced host permutation
     (heavy ids greedy-LPT, tail serpentine: shard_rows_by_pid), also
     reachable as the reshard="host" escape hatch.
  2. Each shard computes dense per-partition partial columns
     (executor.partial_columns) — the by-partition "shuffle" is a local
     segment-sum into the dense [0, P) layout.
  3. One lax.psum over the mesh combines the partials; partition selection
     and noise then run replicated (same PRNG key on every shard, so every
     shard holds identical results with no broadcast step).

The collective cost is one all_to_all of the row payload (device-resident
inputs only) plus one psum of (~6 x P) floats per aggregation, riding ICI —
compared to the reference's full data shuffle over the network.
"""

import threading
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from pipelinedp_tpu import executor
from pipelinedp_tpu.ops import segment_ops
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel.mesh import SHARD_AXIS, round_capacity, shard_map
from pipelinedp_tpu.parallel.reshard import stage_rows_to_mesh
from pipelinedp_tpu.runtime import aot as rt_aot
from pipelinedp_tpu.runtime import entry as rt_entry
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import retry as rt_retry
from pipelinedp_tpu.runtime import trace as rt_trace

# Concurrent multi-device program launches are NOT safe on every
# platform: XLA's CPU collectives rendezvous by arrival order, so two
# shard_map programs dispatched from different host threads can
# interleave their per-device AllReduce participants — each program
# captures some of the device threads and both wait forever for the
# rest (observed as `collective_ops_utils.h ... may be stuck`). Real
# TPU runtimes serialize program launches on the device stream, so the
# hazard is exclusively multi-THREADED hosts: the service worker pool
# (and its megabatch coalescer) is the only place this tree launches
# collectives from more than one thread, so the service brackets its
# lifetime with enable/disable below and every meshed release dispatch
# — solo or megabatched — then runs under the lock and BLOCKS on its
# outputs before releasing it, so one program's collectives fully
# drain before the next program's begin. Outside a service the guard
# stands down entirely: single-threaded callers keep XLA's async
# dispatch pipelining (forcing a drain per launch costs ~20% on
# dispatch-heavy meshed paths like percentile descent). An RLock, so
# an elastic re-entry (device-loss fallback re-dispatching inside the
# guarded region) cannot self-deadlock.
_COLLECTIVE_LAUNCH_LOCK = threading.RLock()
_COLLECTIVE_SERIALIZE_LOCK = threading.Lock()
_collective_serialize_depth = 0  # guarded by _COLLECTIVE_SERIALIZE_LOCK


def enable_collective_serialization() -> None:
    """Turns on collective-launch serialization (refcounted). Called by
    every component that launches meshed programs from worker threads
    — the service worker pool — BEFORE its first worker starts."""
    global _collective_serialize_depth
    with _COLLECTIVE_SERIALIZE_LOCK:
        _collective_serialize_depth += 1


def disable_collective_serialization() -> None:
    """Drops one serialization hold, after the holder's workers have
    all joined."""
    global _collective_serialize_depth
    with _COLLECTIVE_SERIALIZE_LOCK:
        _collective_serialize_depth = max(0, _collective_serialize_depth - 1)


def _collective_launch(dispatch):
    """Runs `dispatch` (a thunk returning jax outputs); while any
    multi-threaded launcher holds a serialization enable, the dispatch
    runs under the collective-launch lock and blocks until the program
    has drained."""
    with _COLLECTIVE_SERIALIZE_LOCK:
        serialize = _collective_serialize_depth > 0
    if not serialize:
        return dispatch()
    with _COLLECTIVE_LAUNCH_LOCK:
        return jax.block_until_ready(dispatch())


def shard_rows_by_pid(pid: np.ndarray, pk: np.ndarray, values: np.ndarray,
                      valid: np.ndarray, n_shards: int):
    """Reorders + pads rows so each privacy id's rows land on exactly one
    shard, with shards load-balanced by ROW COUNT, all shards equal-sized.

    Assignment is two-phase load balancing: the heaviest few thousand ids go
    greedy-LPT (each to the least-loaded shard, catching hot-id skew), and
    the long tail — whose counts are near-uniform — is laid out serpentine
    over the shards in one vectorized pass, so the host cost stays O(U)
    numpy, not O(U) Python, at hundreds of millions of unique ids. Per-shard
    capacity is rounded up keeping 4 significant bits (<= 12.5% slack —
    bounded jit-cache shapes without power-of-two's up-to-2x waste).

    Returns arrays of length n_shards * rows_per_shard whose s-th block is
    shard s's rows (invalid-padded) — the layout shard_map expects for a
    leading-axis split.
    """
    import heapq
    _, inverse, ucounts = np.unique(pid, return_inverse=True,
                                    return_counts=True)
    heavy_first = np.argsort(-ucounts, kind="stable")
    shard_of_uid = np.empty(len(ucounts), dtype=np.int64)
    n_greedy = min(len(ucounts), max(n_shards * 64, 4096))
    heap = [(0, s) for s in range(n_shards)]
    for uid in heavy_first[:n_greedy]:
        load, s = heapq.heappop(heap)
        shard_of_uid[uid] = s
        heapq.heappush(heap, (load + int(ucounts[uid]), s))
    tail = heavy_first[n_greedy:]
    if len(tail):
        # Serpentine over shards ordered lightest-first after phase 1.
        shard_order = np.array([s for _, s in sorted(heap)], dtype=np.int64)
        rank = np.arange(len(tail))
        block, offset = divmod(rank, n_shards)
        pos = np.where(block % 2 == 0, offset, n_shards - 1 - offset)
        shard_of_uid[tail] = shard_order[pos]
    shard = shard_of_uid[inverse]
    order = np.argsort(shard, kind="stable")
    counts = np.bincount(shard, minlength=n_shards)
    per_shard = round_capacity(int(counts.max()))
    n_out = n_shards * per_shard

    out_pid = np.zeros(n_out, dtype=pid.dtype)
    out_pk = np.full(n_out, -1, dtype=pk.dtype)
    out_values = np.zeros((n_out,) + values.shape[1:], dtype=values.dtype)
    out_valid = np.zeros(n_out, dtype=bool)

    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    # Position of each (sorted) row inside its shard block.
    positions = np.arange(len(pid)) - offsets[shard[order]]
    dest = shard[order] * per_shard + positions
    out_pid[dest] = pid[order]
    out_pk[dest] = pk[order]
    out_values[dest] = values[order]
    out_valid[dest] = valid[order]
    return out_pid, out_pk, out_values, out_valid


def _combine_partials(cols, cfg):
    """One psum combines the shards' partial columns; numeric_mode="safe"
    routes float partials through the compensated cross-shard sum so the
    combine cannot re-introduce the rounding the compensated segment
    sums removed. cfg.numeric_mode is static, so the default mode
    compiles the identical psum program it always has."""
    if cfg.numeric_mode == "safe":
        return jax.tree.map(
            lambda x: segment_ops.compensated_psum(x, SHARD_AXIS), cols)
    return jax.tree.map(lambda x: jax.lax.psum(x, SHARD_AXIS), cols)


@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _sharded_kernel(pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                    stds, rng_key, cfg: executor.KernelConfig, mesh: Mesh,
                    secure_tables=None):

    def per_shard(pid_s, pk_s, values_s, valid_s, stds_r, key_r, tables_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        rows_key, final_key = jax.random.split(key_r, 2)
        # Distinct sampling randomness per shard; identical finalize key.
        shard_rows_key = jax.random.fold_in(rows_key, shard_idx)
        cols, qrows = executor.partial_columns(pid_s, pk_s, values_s, valid_s,
                                               min_v, max_v, min_s, max_s,
                                               mid, shard_rows_key, cfg)
        cols = _combine_partials(cols, cfg)
        outputs, keep, row_count = executor.finalize(cols, min_v, mid, stds_r,
                                                     final_key, cfg, tables_r)
        if cfg.quantiles:
            # Chunk histograms are psum'd inside quantile_outputs (tree
            # merge over the mesh); noise + descent replicated via key_r.
            qkey = jax.random.fold_in(key_r, 7919)
            outputs.update(
                executor.quantile_outputs(qrows, min_v, max_v, stds_r, qkey,
                                          cfg, psum_axis=SHARD_AXIS,
                                          secure_tables=tables_r))
        return outputs, keep, row_count

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                             P(SHARD_AXIS), P(), P(), P()),
                   out_specs=P())
    return fn(pid, pk, values, valid, stds, rng_key, secure_tables)


@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _sharded_release_kernel(pid, pk, values, valid, min_v, max_v, min_s,
                            max_s, mid, stds, rng_key,
                            cfg: executor.KernelConfig, mesh: Mesh,
                            secure_tables=None):
    """The fused-release form of _sharded_kernel: the same per-shard
    body, then kept-first compaction (executor.compact_release) fused
    into the SAME program — selection/noise/compaction run replicated
    over already-psum'd columns, so every device holds identical
    O(kept)-transferable results and the driver fetches one scalar gate
    instead of the dense bool[P] + [P] columns."""

    def per_shard(pid_s, pk_s, values_s, valid_s, stds_r, key_r, tables_r):
        shard_idx = jax.lax.axis_index(SHARD_AXIS)
        rows_key, final_key = jax.random.split(key_r, 2)
        shard_rows_key = jax.random.fold_in(rows_key, shard_idx)
        cols, qrows = executor.partial_columns(pid_s, pk_s, values_s, valid_s,
                                               min_v, max_v, min_s, max_s,
                                               mid, shard_rows_key, cfg)
        cols = _combine_partials(cols, cfg)
        outputs, keep, row_count = executor.finalize(cols, min_v, mid, stds_r,
                                                     final_key, cfg, tables_r)
        if cfg.quantiles:
            qkey = jax.random.fold_in(key_r, 7919)
            outputs.update(
                executor.quantile_outputs(qrows, min_v, max_v, stds_r, qkey,
                                          cfg, psum_axis=SHARD_AXIS,
                                          secure_tables=tables_r))
        n_kept, order, outputs_sorted = executor.compact_release(
            outputs, keep)
        return n_kept, order, outputs_sorted, row_count

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                             P(SHARD_AXIS), P(), P(), P()),
                   out_specs=P())
    return fn(pid, pk, values, valid, stds, rng_key, secure_tables)


def _select_per_shard_trace(pid_s, pk_s, valid_s, key_r, l0, n_partitions,
                            selection):
    """Shared per-shard selection body of the two meshed entry points."""
    shard_idx = jax.lax.axis_index(SHARD_AXIS)
    key_l0, key_sel = jax.random.split(key_r)
    # Distinct pair-sampling randomness per shard (rows of one privacy
    # id all live on one shard, so L0 sampling stays shard-local);
    # identical selection key, so every shard holds the same keep mask.
    counts = executor.select_partition_counts(
        pid_s, pk_s, valid_s, jax.random.fold_in(key_l0, shard_idx), l0,
        n_partitions)
    counts = jax.lax.psum(counts, SHARD_AXIS)
    return selection_ops.sample_keep_decisions(key_sel, counts, selection)


@partial(jax.jit,
         static_argnames=("l0", "n_partitions", "selection", "mesh"))
def _sharded_select_kernel(pid, pk, valid, rng_key, l0: int,
                           n_partitions: int,
                           selection: selection_ops.SelectionParams,
                           mesh: Mesh):

    def per_shard(pid_s, pk_s, valid_s, key_r):
        return _select_per_shard_trace(pid_s, pk_s, valid_s, key_r, l0,
                                       n_partitions, selection)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                             P()),
                   out_specs=P())
    return fn(pid, pk, valid, rng_key)


@partial(jax.jit,
         static_argnames=("l0", "n_partitions", "selection", "mesh"))
def _sharded_select_release_kernel(pid, pk, valid, rng_key, l0: int,
                                   n_partitions: int,
                                   selection: selection_ops.SelectionParams,
                                   mesh: Mesh):
    """_sharded_select_kernel + fused kept-first compaction (replicated;
    same ordering as np.nonzero over the dense keep vector)."""

    def per_shard(pid_s, pk_s, valid_s, key_r):
        keep = _select_per_shard_trace(pid_s, pk_s, valid_s, key_r, l0,
                                       n_partitions, selection)
        order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
        return keep.sum(), order

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                             P()),
                   out_specs=(P(), P()))
    return fn(pid, pk, valid, rng_key)


@partial(jax.jit, static_argnames=("cfg", "mesh"))
def _sharded_batched_release_kernel(pid, pk, values, valid, min_v, max_v,
                                    min_s, max_s, mid, stds, rng_keys,
                                    cfg: executor.KernelConfig, mesh: Mesh,
                                    secure_tables=None):
    """Lane-stacked _sharded_release_kernel: ONE launch releases L jobs
    over the mesh. Row arrays carry a leading job-lane axis over the
    per-shard blocked layout ([L, D*cap] / [L, D*cap, V], every lane
    staged by the SAME host LPT permutation its solo run would take) and
    rng_keys is the [L, 2] stack of the jobs' own base keys. The
    per-shard body is _sharded_release_kernel's verbatim, vmapped over
    the lane axis — fold_in(shard_idx), the psum of partial columns and
    the replicated finalize/compaction all batch elementwise, so lane
    l's release is bit-identical to its solo meshed run."""

    def per_shard(pid_s, pk_s, values_s, valid_s, stds_r, keys_r,
                  tables_r):

        def lane(pid_l, pk_l, values_l, valid_l, key_l):
            shard_idx = jax.lax.axis_index(SHARD_AXIS)
            rows_key, final_key = jax.random.split(key_l, 2)
            shard_rows_key = jax.random.fold_in(rows_key, shard_idx)
            cols, qrows = executor.partial_columns(
                pid_l, pk_l, values_l, valid_l, min_v, max_v, min_s,
                max_s, mid, shard_rows_key, cfg)
            cols = _combine_partials(cols, cfg)
            outputs, keep, row_count = executor.finalize(
                cols, min_v, mid, stds_r, final_key, cfg, tables_r)
            if cfg.quantiles:
                qkey = jax.random.fold_in(key_l, 7919)
                outputs.update(
                    executor.quantile_outputs(qrows, min_v, max_v, stds_r,
                                              qkey, cfg,
                                              psum_axis=SHARD_AXIS,
                                              secure_tables=tables_r))
            n_kept, order, outputs_sorted = executor.compact_release(
                outputs, keep)
            return n_kept, order, outputs_sorted, row_count

        return jax.vmap(lane)(pid_s, pk_s, values_s, valid_s, keys_r)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(None, SHARD_AXIS), P(None, SHARD_AXIS),
                             P(None, SHARD_AXIS), P(None, SHARD_AXIS),
                             P(), P(), P()),
                   out_specs=P())
    return fn(pid, pk, values, valid, stds, rng_keys, secure_tables)


@partial(jax.jit,
         static_argnames=("l0", "n_partitions", "selection", "mesh"))
def _sharded_batched_select_release_kernel(
        pid, pk, valid, rng_keys, l0: int, n_partitions: int,
        selection: selection_ops.SelectionParams, mesh: Mesh):
    """Lane-stacked _sharded_select_release_kernel (same lane-axis and
    bit-identity contract as _sharded_batched_release_kernel)."""

    def per_shard(pid_s, pk_s, valid_s, keys_r):

        def lane(pid_l, pk_l, valid_l, key_l):
            keep = _select_per_shard_trace(pid_l, pk_l, valid_l, key_l,
                                           l0, n_partitions, selection)
            order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
            return keep.sum(), order

        return jax.vmap(lane)(pid_s, pk_s, valid_s, keys_r)

    fn = shard_map(per_shard,
                   mesh=mesh,
                   in_specs=(P(None, SHARD_AXIS), P(None, SHARD_AXIS),
                             P(None, SHARD_AXIS), P()),
                   out_specs=(P(), P()))
    return fn(pid, pk, valid, rng_keys)


# Compile/dispatch attribution + AOT executable routing for the dense
# meshed entry points (runtime/aot.py wraps runtime/trace.probe_jit).
_sharded_kernel = rt_aot.aot_probe("sharded_kernel", _sharded_kernel,
                                   static_argnames=("cfg", "mesh"))
_sharded_release_kernel = rt_aot.aot_probe(
    "sharded_release_kernel", _sharded_release_kernel,
    static_argnames=("cfg", "mesh"))
_sharded_batched_release_kernel = rt_aot.aot_probe(
    "sharded_batched_release_kernel", _sharded_batched_release_kernel,
    static_argnames=("cfg", "mesh"))
_sharded_batched_select_release_kernel = rt_aot.aot_probe(
    "sharded_batched_select_release_kernel",
    _sharded_batched_select_release_kernel,
    static_argnames=("l0", "n_partitions", "selection", "mesh"))
_sharded_select_kernel = rt_aot.aot_probe(
    "sharded_select_kernel", _sharded_select_kernel,
    static_argnames=("l0", "n_partitions", "selection", "mesh"))
_sharded_select_release_kernel = rt_aot.aot_probe(
    "sharded_select_release_kernel", _sharded_select_release_kernel,
    static_argnames=("l0", "n_partitions", "selection", "mesh"))


def _fallback_select_partitions(args, kwargs, job):
    """Elastic floor of sharded_select_partitions: the single-device
    selection kernel on the surviving device. The selection key
    (key_sel half of the split) is replicated on the mesh, so the
    single-device decisions are the same release."""

    def go(mesh, pid, pk, valid, rng_key, l0, n_partitions, selection,
           fused=False, reshard="auto", retry=None, job_id=None):
        del mesh, reshard, job_id
        from pipelinedp_tpu.parallel.large_p import _pad_to
        cap = round_capacity(len(pid))
        kernel = (executor.select_partitions_release_kernel
                  if fused else executor.select_partitions_kernel)
        return rt_retry.retry_call(
            lambda: kernel(
                jnp.asarray(_pad_to(pid, cap, 0)),
                jnp.asarray(_pad_to(pk, cap, 0)),
                jnp.asarray(_pad_to(valid, cap, False)), rng_key, l0,
                n_partitions, selection),
            retry, what="single-device select_partitions dispatch")

    return go(*args, **kwargs)


def _fallback_aggregate_arrays(args, kwargs, job):
    """Elastic floor of sharded_aggregate_arrays: the single-device
    fused kernel (identical output contract; the finalize/noise key is
    the replicated half of the same split, so released noise is the
    same release)."""

    def go(mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
           stds, rng_key, cfg, secure_tables=None, fused=False,
           reshard="auto", retry=None, job_id=None):
        del mesh, reshard, job_id
        from pipelinedp_tpu.parallel.large_p import _pad_to
        if isinstance(values, jax.Array):
            values = values.astype(executor._ftype())
        else:
            values = np.asarray(values, dtype=np.dtype(executor._ftype()))
        cap = round_capacity(len(pid))
        kernel = (executor.aggregate_release_kernel
                  if fused else executor.aggregate_kernel)
        return rt_retry.retry_call(
            lambda: kernel(
                jnp.asarray(_pad_to(pid, cap, 0)),
                jnp.asarray(_pad_to(pk, cap, 0)),
                jnp.asarray(_pad_to(values, cap, 0)),
                jnp.asarray(_pad_to(valid, cap, False)), min_v, max_v,
                min_s, max_s, mid, jnp.asarray(stds), rng_key, cfg,
                secure_tables),
            retry, what="single-device aggregation dispatch")

    return go(*args, **kwargs)


@rt_entry.runtime_entry("sharded_select_partitions",
                        fallback=_fallback_select_partitions)
def sharded_select_partitions(mesh: Mesh, pid, pk, valid, rng_key, l0: int,
                              n_partitions: int,
                              selection: selection_ops.SelectionParams,
                              fused: bool = False,
                              reshard: str = "auto",
                              retry: rt_retry.RetryPolicy = None,
                              job_id: Optional[str] = None):
    """Standalone partition selection over the mesh: shard rows by privacy
    id (on-device all_to_all for device-resident inputs, host LPT
    permutation otherwise — see stage_rows_to_mesh), count shard-locally
    (executor.select_partition_counts), psum the int32[P] count vector
    over ICI, select replicated.

    Runtime knobs (shared entry, runtime/entry.py): timeout_s=/watchdog=
    deadlines, job_id= health attribution, elastic=/min_devices=
    device-loss tolerance (the one-device floor runs the single-device
    selection kernel — the selection key is replicated, so decisions
    are the same release).

    Returns keep: bool[n_partitions], replicated across the mesh — or,
    with fused=True, (n_kept, ids_sorted) with kept ids compacted to
    the front inside the same program (the O(kept) fused-release
    drain).
    """
    # Zero-width values column: selection never reads values, and a real
    # column would cost an O(rows) gather/scatter (or exchange) in the
    # reshard.
    if isinstance(pid, jax.Array):
        dummy_values = jnp.zeros((pid.shape[0], 0), jnp.float32)
    else:
        dummy_values = np.zeros((len(pid), 0), np.float32)
    pid, pk, _, valid = stage_rows_to_mesh(mesh, pid, pk, dummy_values,
                                           valid, reshard)
    # Retried dispatches reuse the identical rng_key: a retry is a replay
    # of the same selection decisions, never a second draw.
    kernel = (_sharded_select_release_kernel
              if fused else _sharded_select_kernel)
    with rt_trace.span("dispatch"):
        return _collective_launch(lambda: rt_retry.retry_call(
            lambda: kernel(pid, pk, valid, rng_key, l0,
                           n_partitions, selection, mesh),
            retry, what="sharded select_partitions dispatch"))


@rt_entry.runtime_entry("sharded_aggregate_arrays",
                        fallback=_fallback_aggregate_arrays)
def sharded_aggregate_arrays(mesh: Mesh, pid, pk, values, valid, min_v, max_v,
                             min_s, max_s, mid, stds, rng_key,
                             cfg: executor.KernelConfig, secure_tables=None,
                             fused: bool = False,
                             reshard: str = "auto",
                             retry: rt_retry.RetryPolicy = None,
                             job_id: Optional[str] = None):
    """Shards rows by pid over `mesh` and runs the two-phase fused program.

    Accepts host numpy arrays or device-resident jax arrays (any length);
    device-resident columns reshard over ICI without touching the host
    (stage_rows_to_mesh). Returns the same (outputs, keep, row_count)
    triple as executor.aggregate_kernel, with results replicated across
    the mesh — or, with fused=True, the compacted
    (n_kept, ids_sorted, outputs_sorted, row_count) release of
    executor.aggregate_release_kernel (kept-first ordering fused into
    the one program, so the caller fetches a scalar gate + O(kept)
    columns).

    Runtime knobs (shared entry, runtime/entry.py): timeout_s=/watchdog=
    deadlines, job_id= health attribution, and elastic=/min_devices=
    device-loss tolerance — a device-fatal failure rebuilds a smaller
    mesh from the survivors and re-enters; the one-device floor runs the
    single-device fused kernel (the finalize/noise key is replicated, so
    every geometry releases the same noise).
    """
    # Chaos ingest seam (no-op without an active extreme_values fault).
    _poisoned = rt_faults.maybe_extreme_rows(values, pk)
    if _poisoned is not None:
        values = _poisoned
    pid, pk, values, valid = stage_rows_to_mesh(
        mesh, pid, pk, values, valid, reshard,
        values_dtype=np.dtype(executor._ftype()))
    # Retried dispatches reuse the identical rng_key, so the redrawn noise
    # is bit-identical — a retry replays the same release.
    kernel = _sharded_release_kernel if fused else _sharded_kernel
    with rt_trace.span("dispatch"):
        return _collective_launch(lambda: rt_retry.retry_call(
            lambda: kernel(pid, pk, values, valid, min_v, max_v,
                           min_s, max_s, mid, jnp.asarray(stds),
                           rng_key, cfg, mesh, secure_tables),
            retry, what="sharded aggregation dispatch"))
