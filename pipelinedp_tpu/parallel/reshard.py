"""Device-native pid reshard: all_to_all over ICI instead of host staging.

Every meshed aggregation needs each privacy unit's rows co-located on one
shard (contribution bounding is global per id). The original implementation
(sharded.shard_rows_by_pid) permutes all rows ON THE HOST and re-uploads —
an O(rows) host round trip that forfeits the mesh's D-fold row-capacity
claim the moment the inputs are already device-resident (streamed ingest).
This module keeps the rows in HBM end to end:

  1. **Bucketize** (per shard, on device): dest(row) = mix(pid) mod D — a
     salted murmur-style hash, identical on every shard, so all rows of a
     privacy id map to one destination no matter where they start.
  2. **Count exchange** (the one host fetch): the [D, D] send-count table
     is REDUCED ON DEVICE (one psum for the receive loads, one pmax for
     the largest send bucket) to a replicated int32[3] stats vector —
     [max send bucket, max receive load, total valid rows] — and only
     that crosses to the host (mesh.host_fetch). This is what makes the
     exchange safe on a multi-controller mesh: a process can never
     address another host's shard of the table, but every process can
     read its own replica of the reduced stats, and because the stats
     are bit-identical everywhere, every controller derives the SAME
     static capacities and compiles the SAME exchange program (divergent
     capacities would deadlock the collective).
  3. **Padded all_to_all**: each shard packs its rows into [D, cap_send]
     invalid-padded buckets and ONE jax.lax.all_to_all per column moves
     them over the SHARD_AXIS mesh axis (ICI within a host, DCN across
     hosts on a pod).
  4. **Compaction**: each shard sorts its received rows valid-first and
     slices to the host-known output capacity, restoring the dense
     leading-axis layout every meshed kernel consumes.

Capacity caching: the rounded (cap_send, out_cap) pair is cached per
exchange geometry (mesh devices, padded per-shard capacity, salt, value
column shape/dtype). A repeated exchange at a cached geometry dispatches
the exchange kernel OPTIMISTICALLY at the cached capacities — overlapping
the stats fetch instead of blocking on it — and only falls back to a
re-dispatch when the fetched stats show the cached capacity no longer
fits (counted in the ``reshard_capacity_reuse`` telemetry counter when it
does fit). The cache is per-process and keyed purely by call geometry, so
every controller of a multi-process mesh makes the same hit/miss decision
and stays on the same compiled program.

Load balance, re-derived for the hash-bucketed layout: shard_rows_by_pid
balanced ROW counts exactly (greedy-LPT heavy ids + serpentine tail), so
its per-shard capacity was max-load-optimal up to round_capacity slack.
Hash bucketing balances UNIQUE IDS in expectation instead: with U ids of
weights w_1..w_U (sum n), a shard's expected load is n/D and the deviation
is driven by the heaviest ids (Var = sum w_i^2 * (D-1)/D^2) — near-uniform
workloads land within a few percent of n/D, while a single id holding a
large fraction of all rows makes its shard irreducibly hot (the same
irreducible case greedy-LPT had). Padding waste is bounded and asserted:
the output capacity is round_capacity(max shard load) (<= 12.5% slack over
the measured max), and a >2x max/mean skew logs a warning naming the
hash-balance assumption that broke.

The host path (sharded.shard_rows_by_pid) remains for host-numpy inputs —
where one upload is unavoidable and the exact LPT balance is free — and as
the reshard="host" escape hatch on every meshed entry point.
"""

import collections
import contextlib
import functools
import logging
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from pipelinedp_tpu.runtime.concurrency import guarded_by

from pipelinedp_tpu.parallel import mesh as mesh_lib
from pipelinedp_tpu.parallel.mesh import (SHARD_AXIS, host_fetch,
                                          round_capacity, row_sharding,
                                          rows_per_shard, shard_map)
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import retry as rt_retry
from pipelinedp_tpu.runtime import telemetry as rt_telemetry
from pipelinedp_tpu.runtime import trace as rt_trace
from pipelinedp_tpu.runtime import watchdog as rt_watchdog

# Fetches at or below this many elements are control-plane sized; the
# transfer-guard treats anything larger as row data.
_CONTROL_TABLE_ELEMENTS = 1 << 12


def _dest_shard(pid, n_shards: int, salt: int):
    """Destination shard of each row: murmur-mixed pid hash mod D.

    A pure function of pid (identical on every shard), so co-location
    needs no coordination. int64 pids fold to uint32 first — collisions
    only merge ids onto one shard, never split one id across shards.
    """
    from pipelinedp_tpu.executor import _hash_mix
    h = _hash_mix(pid.astype(jnp.uint32) * jnp.uint32(0x9E3779B9) ^
                  jnp.uint32(salt))
    return (h % jnp.uint32(n_shards)).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("n_shards", "salt", "mesh"))
def _count_stats_kernel(pid, valid, n_shards: int, salt: int, mesh: Mesh):
    """Replicated int32[3] = [max send bucket, max receive load, total
    valid rows]: the [D, D] send-count table reduced on device (psum for
    the per-destination receive loads, pmax for the largest send bucket).
    The only data the host sees before the exchange — and, being fully
    replicated, the only form a multi-controller process could fetch at
    all (each reads its local replica; no host ever addresses another
    host's table shard)."""

    def per_shard(pid_s, valid_s):
        dest = _dest_shard(pid_s, n_shards, salt)
        idx = jnp.where(valid_s, dest, n_shards)
        counts = jnp.zeros((n_shards + 1,), jnp.int32).at[idx].add(
            1)[:n_shards]
        recv = jax.lax.psum(counts, SHARD_AXIS)
        max_send = jax.lax.pmax(counts.max(), SHARD_AXIS)
        return jnp.stack([max_send, recv.max(), recv.sum()])

    fn = shard_map(per_shard, mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                   out_specs=P())
    return fn(pid, valid)


@functools.partial(jax.jit,
                   static_argnames=("cap_send", "out_cap", "n_shards",
                                    "salt", "mesh"))
def _exchange_kernel(pid, pk, values, valid, cap_send: int, out_cap: int,
                     n_shards: int, salt: int, mesh: Mesh):
    """Pack -> all_to_all -> compact, one jit program, zero host traffic.

    Each shard sorts its rows by destination, gathers them into invalid-
    padded [D, cap_send] buckets, exchanges bucket d with shard d over the
    mesh axis, then sorts the received [D * cap_send] rows valid-first and
    slices to the host-known out_cap — the dense leading-axis layout the
    meshed kernels consume.
    """

    def per_shard(pid_s, pk_s, values_s, valid_s):
        n_local = pid_s.shape[0]
        dest = jnp.where(valid_s, _dest_shard(pid_s, n_shards, salt),
                         n_shards)
        order = jnp.argsort(dest, stable=True)
        starts = jnp.searchsorted(dest[order],
                                  jnp.arange(n_shards + 1, dtype=jnp.int32))
        j = jnp.arange(cap_send, dtype=jnp.int32)
        slot = starts[:-1, None] + j[None, :]  # [D, cap_send] row ranks
        slot_valid = slot < starts[1:, None]
        take = order[jnp.minimum(slot, n_local - 1)]

        def exchange(col, fill):
            bucket = jnp.where(
                slot_valid.reshape(slot_valid.shape + (1,) *
                                   (col.ndim - 1)), col[take],
                jnp.asarray(fill, col.dtype))
            return jax.lax.all_to_all(bucket, SHARD_AXIS, 0, 0, tiled=True)

        r_valid = jax.lax.all_to_all(slot_valid, SHARD_AXIS, 0, 0,
                                     tiled=True)
        r_pid = exchange(pid_s, 0)
        r_pk = exchange(pk_s, -1)
        r_val = exchange(values_s, 0)

        def flat(x):
            return x.reshape((n_shards * cap_send,) + x.shape[2:])

        fvalid = flat(r_valid)
        keep_first = jnp.argsort(~fvalid, stable=True)[:out_cap]
        return (flat(r_pid)[keep_first], flat(r_pk)[keep_first],
                flat(r_val)[keep_first], fvalid[keep_first])

    fn = shard_map(per_shard, mesh=mesh, in_specs=(P(SHARD_AXIS),) * 4,
                   out_specs=(P(SHARD_AXIS),) * 4)
    return fn(pid, pk, values, valid)


# Compile/dispatch attribution for the reshard entry points (trace
# summaries separate all_to_all compiles from steady-state exchanges).
_count_stats_kernel = rt_trace.probe_jit("reshard_count_stats",
                                         _count_stats_kernel)
_exchange_kernel = rt_trace.probe_jit("reshard_exchange", _exchange_kernel)


def _row_payload_bytes(*cols) -> int:
    """Total byte size of the row columns a staging path moves."""
    return int(sum(getattr(c, "nbytes", 0) for c in cols))


def _pad_and_shard(mesh: Mesh, per_shard_cap: int, pid, pk, values, valid):
    """Pads device columns to n_shards * per_shard_cap (invalid-marked) and
    lays them out as an even leading-axis split over the mesh — all on
    device (device_put between device layouts is a device-to-device copy,
    ICI on a pod). Columns already at the target length and layout (the
    multi-host ingest uploads per-process shards pre-padded to exactly
    this split) pass through untouched — no eager cross-process copy."""
    n_shards = mesh.devices.size
    pad = n_shards * per_shard_cap - pid.shape[0]
    sharding = row_sharding(mesh)

    def padded(col, fill):
        if pad:
            widths = ((0, pad),) + ((0, 0),) * (col.ndim - 1)
            col = jnp.pad(col, widths, constant_values=fill)
        if getattr(col, "sharding", None) == sharding:
            return col
        return jax.device_put(col, sharding)

    return (padded(pid, 0), padded(pk, -1), padded(values, 0),
            padded(valid, False))


# Rounded (cap_send, out_cap) pairs per exchange geometry, insertion-
# ordered for deterministic FIFO eviction. Per-process and keyed purely
# by call geometry, so every controller of a multi-process mesh makes
# the same hit/miss decision (a divergent static capacity would compile
# divergent collectives and deadlock the exchange).
_capacity_lock = threading.Lock()
_capacity_cache: "collections.OrderedDict[tuple, Tuple[int, int]]" = \
    collections.OrderedDict()
_CAPACITY_CACHE_MAX = 64
_GUARDED_BY = guarded_by("_capacity_lock", "_capacity_cache")


def reset_capacity_cache() -> None:
    """Drops the cached exchange capacities (test isolation)."""
    with _capacity_lock:
        _capacity_cache.clear()


def _capacity_key(mesh: Mesh, per_in: int, salt: int, values) -> tuple:
    return (tuple(getattr(d, "id", d) for d in mesh.devices.flat),
            int(per_in), int(salt), tuple(values.shape[1:]),
            str(values.dtype))


def _warn_skew(max_recv: int, total: int, n_shards: int) -> None:
    if total and max_recv * n_shards > 2 * total:
        logging.warning(
            "device reshard: hash-bucketed max shard load %d > 2x mean "
            "(%.0f) — a few privacy ids dominate the row mass, so the "
            "hash balance assumption (load ~ n/D) does not hold for this "
            "input; the hot shard bounds the padded capacity.", max_recv,
            total / n_shards)


def device_reshard_rows_by_pid(mesh: Mesh, pid, pk, values, valid,
                               salt: int = 0):
    """Device-native counterpart of sharded.shard_rows_by_pid.

    Takes device-resident row columns (any one-device or mesh layout),
    returns (pid, pk, values, valid) of length n_shards * out_cap laid out
    as an even leading-axis split over `mesh`, every privacy id's rows on
    exactly one shard, invalid-padded. Rows never visit the host; the only
    device->host traffic is the replicated int32[3] count-stats vector
    (mesh.host_fetch) — multi-controller safe, since each process reads
    its own replica of the on-device-reduced table.

    Repeated exchanges at a cached geometry dispatch optimistically at
    the cached capacities, overlapping the stats fetch with the exchange
    instead of serializing capacity-sync -> dispatch; the fetched stats
    then either confirm the fit (reshard_capacity_reuse) or trigger one
    corrective re-dispatch at the exact capacities (rare: the row
    distribution grew past the cached bucket).
    """
    n_shards = mesh.devices.size
    n = pid.shape[0]
    if n_shards == 1:
        cap = round_capacity(n)
        return _pad_and_shard(mesh, cap, pid, pk, values, valid)
    per_in = rows_per_shard(n, n_shards)
    pid, pk, values, valid = _pad_and_shard(mesh, per_in, pid, pk, values,
                                            valid)
    stats_dev = _count_stats_kernel(pid, valid, n_shards, salt, mesh)
    key = _capacity_key(mesh, per_in, salt, values)
    with _capacity_lock:
        cached = _capacity_cache.get(key)
    out = None
    if cached is not None:
        # Optimistic dispatch at the cached capacities: the exchange
        # compiles/runs while the stats land, so the steady-state path
        # never blocks on the capacity sync before dispatching.
        out = _exchange_kernel(pid, pk, values, valid, cached[0],
                               cached[1], n_shards, salt, mesh)
    max_send, max_recv, total = (
        int(x) for x in host_fetch(stats_dev))
    if cached is not None and max_send <= cached[0] and \
            max_recv <= cached[1]:
        rt_telemetry.record("reshard_capacity_reuse")
        _warn_skew(max_recv, total, n_shards)
        return out
    cap_send = round_capacity(max_send)
    out_cap = round_capacity(max_recv)
    # Padding-waste bound: round_capacity guarantees <= 12.5% slack over
    # the measured max shard load (+ the 8-row floor). Asserted so a
    # future capacity-rounding change cannot silently break the memory
    # story this reshard is sold on.
    assert out_cap <= max(-(-9 * max_recv) // 8, 8), (out_cap, max_recv)
    with _capacity_lock:
        _capacity_cache[key] = (cap_send, out_cap)
        while len(_capacity_cache) > _CAPACITY_CACHE_MAX:
            _capacity_cache.popitem(last=False)
    _warn_skew(max_recv, total, n_shards)
    return _exchange_kernel(pid, pk, values, valid, cap_send, out_cap,
                            n_shards, salt, mesh)


def stage_rows_to_mesh(mesh: Mesh, pid, pk, values, valid,
                       reshard: str = "auto",
                       values_dtype: Optional[np.dtype] = None):
    """Shared input staging of every meshed entry point: rows in (host or
    device), pid-co-located mesh-sharded rows out.

    reshard:
      * "auto" (default) — device-resident inputs take the collective
        reshard (rows never touch the host); host inputs take the exact
        LPT host permutation (they pay one upload either way).
      * "host" — force the host permutation (escape hatch: exact row
        balance, or a platform without all_to_all).
      * "device" — force the collective (host inputs are uploaded once,
        unbalanced, then exchanged on device).

    Both permutations are pure functions of the TARGET mesh geometry:
    the collective destination is hash(pid) mod D and the host path is
    an LPT layout over D shards, with nothing cached against the mesh
    the rows were previously staged for. That is what makes elastic
    mesh degradation (runtime/retry.run_with_mesh_degradation) a plain
    re-entry: after a device loss the driver calls this again with the
    shrunken mesh and the permutation rebuilds for the new D — already
    invalid-padded inputs restage correctly because every kernel masks
    by `valid`.

    Multi-controller meshes (is_fully_addressable False): device-resident
    inputs must be GLOBAL arrays over the mesh (the multi-host ingest,
    ingest.encode_local_shard_to_mesh, builds them from per-process
    shards), and the collective exchange is the only reshard —
    reshard='host' is rejected and a failed collective propagates
    instead of degrading, since no process can materialize the other
    hosts' rows. Host-numpy inputs are accepted under the standard
    multi-controller contract that every process passes the identical
    array (each computes the same permutation and uploads it replicated).
    """
    if reshard not in ("auto", "host", "device"):
        raise ValueError(f"reshard must be auto|host|device, got {reshard}")
    if reshard == "host" and not mesh_lib.is_fully_addressable(mesh):
        raise ValueError(
            "reshard='host' is unavailable on a multi-controller mesh: "
            "the LPT permutation needs every row materialized on one "
            "host, and no process can address the other hosts' shards. "
            "Use reshard='auto' (the collective exchange) instead.")
    device_resident = isinstance(pid, jax.Array)
    use_device = (reshard == "device" or
                  (reshard == "auto" and device_resident))
    if use_device:
        if values_dtype is not None:
            values = values.astype(values_dtype)
        if not device_resident:
            pid, pk, values, valid = (jnp.asarray(pid), jnp.asarray(pk),
                                      jnp.asarray(values),
                                      jnp.asarray(valid))
        try:
            # The collective exchange runs under its own watchdog deadline
            # (when one is active on this thread): a hang on the
            # all_to_all fabric surfaces as BlockTimeoutError and degrades
            # to the host permutation exactly like a failed collective.
            # The span carries the exchanged row-payload byte count so
            # trace summaries attribute collective volume.
            with rt_watchdog.guard("collective"), \
                    rt_trace.span(
                        "reshard.collective",
                        bytes=_row_payload_bytes(pid, pk, values, valid)):
                # A device LOST during the exchange is not a collective
                # failure the host permutation can route around — the
                # mesh itself contains a dead chip — so device-fatal
                # errors propagate to the elastic degradation loop
                # (classified below), which rebuilds a smaller mesh and
                # re-derives this permutation for the new geometry.
                rt_faults.maybe_fail("device_loss", point="collective")
                rt_faults.maybe_fail("collective")
                rt_faults.maybe_hang(point="collective")
                return device_reshard_rows_by_pid(mesh, pid, pk, values,
                                                  valid)
        except Exception as e:  # noqa: BLE001 - classified below
            if not _is_collective_failure(e):
                raise
            if not mesh_lib.is_fully_addressable(mesh):
                # A multi-controller mesh has no host permutation to
                # degrade to: no process can materialize the other
                # hosts' rows, so the failure propagates (the elastic
                # loop may still rebuild a smaller mesh if the cause is
                # device-fatal; a plain collective fault is terminal
                # here, exactly like a failed psum would be).
                logging.warning(
                    "device collective reshard failed on a "
                    "multi-controller mesh (%s) — the host LPT fallback "
                    "needs every row addressable on one host, so the "
                    "failure propagates.", type(e).__name__)
                raise
            # The fallback is a transient-style recovery attempt and
            # spends the job-wide retry budget (exhaustion raises typed
            # instead of grinding through composed chaos faults).
            rt_retry.consume_retry_budget("reshard host fallback")
            rt_telemetry.record("reshard_host_fallbacks")
            logging.warning(
                "device collective reshard failed (%s: %s); gracefully "
                "degrading to the host LPT permutation — rows stage "
                "through the host for this aggregation (one O(rows) "
                "round trip), results are unchanged.", type(e).__name__,
                str(e).splitlines()[0][:200])
            # host_fetch = the sanctioned materialization channel; the
            # fallback legitimately moves rows through the host.
            pid, pk, values, valid = (host_fetch(pid), host_fetch(pk),
                                      host_fetch(values), host_fetch(valid))
    from pipelinedp_tpu.parallel import sharded
    with rt_trace.span("reshard.host") as sp:
        values = np.asarray(values)
        if values_dtype is not None:
            values = values.astype(values_dtype, copy=False)
        pid, pk, values, valid = sharded.shard_rows_by_pid(
            np.asarray(pid), np.asarray(pk), values, np.asarray(valid),
            mesh.devices.size)
        sp.set(bytes=_row_payload_bytes(pid, pk, values, valid))
        sharding = row_sharding(mesh)
        return (jax.device_put(jnp.asarray(pid), sharding),
                jax.device_put(jnp.asarray(pk), sharding),
                jax.device_put(jnp.asarray(values), sharding),
                jax.device_put(jnp.asarray(valid), sharding))


def _is_collective_failure(exc: BaseException) -> bool:
    """Failures worth degrading to the host reshard for: the injected
    collective fault, a deadline expiry on the exchange, transient
    runtime failures, or an error naming the exchange itself.
    Programming errors (shape/type) must propagate — and so must
    device-fatal failures: a host permutation cannot route around a
    dead chip that is still part of the mesh, so those go to the
    elastic degradation loop instead, which rebuilds the permutation
    for the shrunken geometry."""
    if isinstance(exc, rt_faults.InjectedCollectiveError):
        return True
    if isinstance(exc, rt_watchdog.BlockTimeoutError):
        return True
    if rt_retry.is_device_fatal(exc):
        return False
    if isinstance(exc, rt_faults.InjectedFault):
        return False
    if rt_retry.is_transient(exc):
        return True
    msg = str(exc)
    return any(marker in msg for marker in ("all_to_all", "all-to-all",
                                            "collective", "AllToAll"))


@contextlib.contextmanager
def forbid_row_fetches(max_elements: int = _CONTROL_TABLE_ELEMENTS):
    """Transfer guard proving rows never leave the device in its scope.

    jax.transfer_guard cannot catch device->host reads on the CPU backend
    (arrays are host-backed, the "transfer" is zero-copy), so the guard
    instruments the actual host-materialization entry point instead:
    np.asarray of a jax.Array larger than a control table raises unless
    it runs inside mesh.host_fetch. Used by the transfer-guard tests and
    the multi-chip dryrun to prove the device-resident path performs zero
    O(rows) host transfers before dispatch.
    """
    real_asarray = np.asarray

    def guarded(a, *args, **kwargs):
        if (isinstance(a, jax.Array) and a.size > max_elements and
                not getattr(mesh_lib._sanctioned_fetch, "active", False)):
            raise AssertionError(
                f"O(rows) device->host fetch of shape {a.shape} inside a "
                f"forbid_row_fetches scope — the device-resident path must "
                f"not stage rows through the host")
        return real_asarray(a, *args, **kwargs)

    np.asarray = guarded
    try:
        yield
    finally:
        np.asarray = real_asarray
