"""Pipeline execution backends.

The engine is a backend-generic dataflow builder: every step is a call to one
of the ~20 `PipelineBackend` primitives with a stage-name string (reference:
pipeline_dp/pipeline_backend.py:38-195). Backends provided here:

  * LocalBackend        — lazy Python generators; the ground-truth semantics.
  * TPUBackend          — columnar JAX/XLA execution. It is a *marker + device
                          config* object: DPEngine recognizes it and lowers
                          the whole aggregation to one fused XLA program
                          (executor.py) instead of interpreting the op graph.
                          The generic op vocabulary is still implemented
                          (host-side, numpy) so non-fused utilities
                          (histograms, analysis glue) run anywhere.
  * MultiProcLocalBackend — multiprocessing Pool over materialized stages.
  * BeamBackend / SparkRDDBackend — thin adapters over Apache Beam / PySpark,
                          available when those packages are importable
                          (they are optional, exactly as in the reference).

An Annotator hook mirrors reference :826-852.
"""

import abc
import collections
import functools
import itertools
import operator
import random
from typing import Any, Callable, Iterable, List, Optional

import numpy as np

from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu import input_validators
from pipelinedp_tpu import sampling_utils

try:
    import apache_beam as beam
except ImportError:
    beam = None

try:
    import pyspark
except ImportError:
    pyspark = None


class PipelineBackend(abc.ABC):
    """Interface implemented by all execution backends."""

    def to_collection(self, collection_or_iterable, col, stage_name: str):
        """Converts an iterable to the backend-native collection."""
        del col, stage_name
        return collection_or_iterable

    def to_multi_transformable_collection(self, col):
        """Returns a collection that can be iterated multiple times."""
        return col

    @abc.abstractmethod
    def map(self, col, fn, stage_name: str):
        pass

    @abc.abstractmethod
    def map_with_side_inputs(self, col, fn, side_input_cols, stage_name: str):
        """fn(row, *side_inputs) where each side input collection is
        materialized and passed as one object."""

    @abc.abstractmethod
    def flat_map(self, col, fn, stage_name: str):
        pass

    def flat_map_with_side_inputs(self, col, fn, side_input_cols,
                                  stage_name: str):
        raise NotImplementedError(
            f"flat_map_with_side_inputs is not supported in "
            f"{type(self).__name__}")

    @abc.abstractmethod
    def map_tuple(self, col, fn, stage_name: str):
        pass

    @abc.abstractmethod
    def map_values(self, col, fn, stage_name: str):
        pass

    @abc.abstractmethod
    def group_by_key(self, col, stage_name: str):
        """(key, value) -> (key, iterable-of-values)."""

    @abc.abstractmethod
    def filter(self, col, fn, stage_name: str):
        pass

    @abc.abstractmethod
    def filter_by_key(self, col, keys_to_keep, stage_name: str):
        """Keeps only (key, data) whose key is in keys_to_keep (local list/set
        or distributed collection)."""

    @abc.abstractmethod
    def keys(self, col, stage_name: str):
        pass

    @abc.abstractmethod
    def values(self, col, stage_name: str):
        pass

    @abc.abstractmethod
    def sample_fixed_per_key(self, col, n: int, stage_name: str):
        """(key, value) -> (key, [<=n uniformly sampled values])."""

    @abc.abstractmethod
    def count_per_element(self, col, stage_name: str):
        """element -> (element, count)."""

    @abc.abstractmethod
    def sum_per_key(self, col, stage_name: str):
        pass

    @abc.abstractmethod
    def combine_accumulators_per_key(self, col,
                                     combiner: 'dp_combiners.Combiner',
                                     stage_name: str):
        """Merges all accumulators per key with combiner.merge_accumulators."""

    @abc.abstractmethod
    def reduce_per_key(self, col, fn: Callable, stage_name: str):
        """Reduces values per key with an associative commutative fn."""

    @abc.abstractmethod
    def flatten(self, cols: Iterable, stage_name: str):
        """Union of several collections."""

    @abc.abstractmethod
    def distinct(self, col, stage_name: str):
        pass

    @abc.abstractmethod
    def to_list(self, col, stage_name: str):
        """1-element collection holding the list of all elements."""

    def annotate(self, col, stage_name: str, **kwargs):
        """Applies all registered annotators (no-op by default)."""
        return col


class UniqueLabelsGenerator:
    """Generates unique stage labels (needed by Beam transform naming)."""

    def __init__(self, suffix):
        self._labels = set()
        self._suffix = ("_" + suffix) if suffix else ""

    def _add_if_unique(self, label):
        if label in self._labels:
            return False
        self._labels.add(label)
        return True

    def unique(self, label):
        if not label:
            label = "UNDEFINED_STAGE_NAME"
        suffix_label = label + self._suffix
        if self._add_if_unique(suffix_label):
            return suffix_label
        for i in itertools.count(1):
            label_candidate = f"{label}_{i}{self._suffix}"
            if self._add_if_unique(label_candidate):
                return label_candidate


class LocalBackend(PipelineBackend):
    """Lazy single-machine backend over Python generators.

    Ground-truth semantics for every other backend (reference :477-583).
    """

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def to_multi_transformable_collection(self, col):
        return list(col)

    def map(self, col, fn, stage_name: str = None):
        return (fn(x) for x in col)

    def map_with_side_inputs(self, col, fn, side_input_cols, stage_name=None):
        side_inputs = [list(s) for s in side_input_cols]

        def gen():
            for x in col:
                yield fn(x, *side_inputs)

        return gen()

    def flat_map(self, col, fn, stage_name: str = None):
        return (x for el in col for x in fn(el))

    def flat_map_with_side_inputs(self, col, fn, side_input_cols,
                                  stage_name=None):
        side_inputs = [list(s) for s in side_input_cols]

        def gen():
            for el in col:
                yield from fn(el, *side_inputs)

        return gen()

    def map_tuple(self, col, fn, stage_name: str = None):
        return (fn(*x) for x in col)

    def map_values(self, col, fn, stage_name: str = None):
        return ((k, fn(v)) for k, v in col)

    def group_by_key(self, col, stage_name: str = None):

        def gen():
            d = collections.defaultdict(list)
            for key, value in col:
                d[key].append(value)
            yield from d.items()

        return gen()

    def filter(self, col, fn, stage_name: str = None):
        return (x for x in col if fn(x))

    def filter_by_key(self, col, keys_to_keep, stage_name: str = None):

        def gen():
            keys = keys_to_keep if isinstance(keys_to_keep,
                                              (set, frozenset, dict)) else set(
                                                  keys_to_keep)
            for key, value in col:
                if key in keys:
                    yield key, value

        return gen()

    def keys(self, col, stage_name: str = None):
        return (k for k, _ in col)

    def values(self, col, stage_name: str = None):
        return (v for _, v in col)

    def sample_fixed_per_key(self, col, n: int, stage_name: str = None):

        def gen():
            for key, values in self.group_by_key(col):
                if len(values) > n:
                    values = self._rng.sample(values, n)
                yield key, values

        return gen()

    def count_per_element(self, col, stage_name: str = None):

        def gen():
            yield from collections.Counter(col).items()

        return gen()

    def sum_per_key(self, col, stage_name: str = None):
        return self.reduce_per_key(col, operator.add, stage_name)

    def combine_accumulators_per_key(self, col,
                                     combiner: 'dp_combiners.Combiner',
                                     stage_name: str = None):
        return self.reduce_per_key(col, combiner.merge_accumulators, stage_name)

    def reduce_per_key(self, col, fn: Callable, stage_name: str = None):

        def gen():
            d = {}
            for key, value in col:
                d[key] = fn(d[key], value) if key in d else value
            yield from d.items()

        return gen()

    def flatten(self, cols, stage_name: str = None):
        return itertools.chain(*cols)

    def distinct(self, col, stage_name: str = None):

        def gen():
            yield from set(col)

        return gen()

    def to_list(self, col, stage_name: str = None):
        return iter([list(col)])

    def annotate(self, col, stage_name: str, **kwargs):
        for annotator in _annotators:
            col = annotator.annotate(col, self, stage_name, **kwargs)
        return col


class TPUBackend(LocalBackend):
    """Columnar JAX/XLA backend.

    DPEngine detects this backend and lowers aggregate() to the fused
    columnar executor (executor.py / parallel/sharded.py): one jit-compiled
    program doing contribution bounding + per-partition combine + partition
    selection + noise on device. Standalone select_partitions() lowers to
    its own single-program device kernel
    (executor.select_partitions_kernel): pair dedupe + L0 sampling via one
    payload-carrying sort, privacy-id counts via segment ops, vectorized
    selection — O(rows) memory, no dense per-partition columns.

    The generic op vocabulary is inherited from LocalBackend so that
    non-fused framework utilities (dataset histograms, analysis glue,
    explain-report plumbing) keep working with this backend too.

    Args:
        mesh: optional jax.sharding.Mesh (1-D, axis "shards", see
            parallel/mesh.make_mesh). When set, rows are sharded by privacy
            id across the mesh and partials combined with lax.psum
            (parallel/sharded.py). When None, single-device jit.
        reshard: how meshed paths co-locate each privacy id's rows on one
            shard (parallel/reshard.stage_rows_to_mesh). "auto" (default):
            device-resident columns (streamed ingest) reshard on device —
            pid-hash bucketize + one padded jax.lax.all_to_all over ICI,
            rows never touching the host — while host-numpy inputs take
            the exact load-balanced host permutation they'd pay an upload
            for anyway. "host"/"device" force one path (escape hatches:
            exact row balance, or a platform without all_to_all).
        max_partitions: optional static result width. When set, the kernel
            compiles for this many partitions regardless of how many appear
            in the data — reuse it across datasets to avoid recompiles.
        noise_seed: base seed for the on-device counter-based RNG. None ->
            fresh nondeterministic seed per aggregation.
        secure_noise: release values snapped to a discrete grid with
            table-sampled discrete Laplace/Gaussian noise
            (ops/secure_noise.py) instead of continuous f32 draws — the
            device counterpart of the reference's PyDP snapped secure
            mechanisms (dp_computations.py:131-152). Costs one O(log K)
            table search per released value.
        large_partition_threshold: partition counts above this route
            aggregation AND standalone partition selection through the
            blocked partition-axis path (parallel/large_p.py), which
            never materializes dense [0, P) state and transfers only
            kept partitions — the reference's unbounded-key regime. With
            a mesh the blocked path runs sharded (pid-sharded pass 1,
            one [C]-sized psum per partition block over ICI). None
            disables the routing.
        retry: optional pipelinedp_tpu.runtime.RetryPolicy for transient
            block-dispatch failures (None = the runtime default: 3
            retries, bounded exponential backoff). A retried block
            re-derives the same fold_in key and redraws bit-identical
            noise — no second DP release, no budget re-spend. OOM on a
            block kernel instead halves the partition block capacity and
            re-plans; see README "Failure semantics".
        journal: optional pipelinedp_tpu.runtime.BlockJournal. When set,
            the blocked drivers record each consumed block's drained
            O(kept) results keyed by (job_id, block); an interrupted run
            re-invoked with the same journal + job_id resumes from the
            last consumed block instead of restarting. Pair with
            noise_seed for a deterministic resume (a journal without a
            seed warns: only journaled blocks keep their original noise).
        job_id: journal key namespace for this pipeline's aggregations.
            None derives a digest of the static kernel config + seed —
            pass explicit distinct ids when one pipeline runs several
            identically-configured aggregations.
        block_partitions: partition block capacity C of the blocked path
            (None = the drivers' default, 2^20). The failure-domain knob:
            smaller blocks mean finer-grained retry/journal/OOM-degrade
            units at more dispatch overhead.
        timeout_s: per-operation deadline (seconds) for the blocked
            drivers' watchdog: every block dispatch, drain sync and the
            device-reshard collective must finish inside it or the
            watchdog cancels at the next cooperative point. A timed-out
            block retries under the SAME fold_in key (bit-identical
            noise); repeated timeouts degrade the block capacity like
            OOM; a timed-out reshard collective falls back to the host
            permutation. None (default) enforces no deadline unless
            `watchdog` is given.
        watchdog: optional pipelinedp_tpu.runtime.Watchdog instance to
            share/configure directly (auto-derived deadlines from the
            pass-1 profile, custom multiplier). timeout_s is shorthand
            for watchdog=Watchdog(timeout_s=...).
        elastic: device-loss tolerance for the meshed paths. When True,
            a device-fatal runtime failure (a chip dropping off the
            slice) no longer kills the run: the runtime probes the mesh
            for surviving devices, rebuilds a smaller mesh, re-derives
            shardings and the reshard permutation for the new geometry
            and re-enters the driver — journaled blocks replay, the
            rest re-derive the same fold_in(final_key, b) keys, so the
            degraded run is bit-compatible with the un-faulted one
            (zero duplicate ledger registrations). At the one-device
            floor the unsharded driver runs instead. Meaningless
            without a mesh.
        elastic_grow: full fleet elasticity for the meshed paths. When
            True, the meshed drivers run under
            runtime/retry.run_with_mesh_elasticity: everything elastic
            does (shrink tolerance is included — elastic_grow implies
            elastic), PLUS scale-UP — join candidates announced via
            runtime/retry.announce_join (new hosts/devices probed
            healthy) are admitted at the next block boundary and the
            mesh rebuilds over the larger device set. Block keys are
            geometry-independent, so the grown run's releases are
            bit-identical to the fixed-geometry run's. Meaningless
            without a mesh.
        min_devices: elastic degradation floor (default 1). Losses that
            leave fewer live devices raise
            runtime.MeshDegradationError naming the job_id and journal
            path a resume needs, and health() reports FAILED.
        pipeline_depth: staging window of the streaming executor
            (runtime/pipeline.py): at most this many encoded chunks in
            flight between the host encode pool and the device
            accumulator when aggregating a ChunkSource. None (default)
            takes the shared PIPELINE_DEPTH (8) — the same depth that
            bounds the blocked drivers' in-flight block kernels.
            Backpressure: a full window stops the producer from pulling
            new chunks, so host memory holds O(depth) chunks.
        encode_threads: host thread pool size for chunk
            parse/factorization on the streamed (ChunkSource) entry.
            None (default) auto-sizes (min(4, cpu_count)); 0 forces the
            serial chunk encode; >= 1 pipelines: chunk k+1 factorizes on
            the pool while chunk k's columns land in the device-resident
            accumulator. Pipelined and serial execution are
            bit-identical — the accumulator reproduces executor.pad_rows
            exactly, so the same compiled kernel sees the same arrays
            and releases the same noise.
        encode_mode: how streamed (ChunkSource) input is vocabulary-
            encoded. "host" (default): the exact chunked host encoder —
            per-chunk factorize on the encode pool, sequential
            vocabulary stitch on the consumer. "hash_device": chunk
            workers only HASH raw keys (vectorized, order-independent),
            raw hash columns stream host->device once, dense
            first-occurrence codes are assigned inside jit
            (device_encode.py), and partition keys are decoded only at
            the DP-selected indices. Bit-identical outputs to "host"
            under the same noise keys; a detected 64-bit hash collision
            (counted in ingest_hash_collisions) falls back to the exact
            host encoder when the chunk source is re-iterable. A
            ChunkSource(encode_mode=...) overrides this per source.
        coordinator_address: jax.distributed coordinator endpoint
            ("host:port"). With num_processes, brings up the
            multi-controller runtime at backend construction
            (parallel/mesh.initialize_distributed — idempotent, selects
            the gloo CPU collectives the 2-process dryrun uses) so
            jax.devices() spans the pod before any mesh is built. The
            process id comes from JAX_PROCESS_INDEX or cluster
            auto-detection. Both knobs None (the default) skips
            distributed bring-up entirely.
        num_processes: total controller count of the jax.distributed
            job; must be identical on every process. See
            coordinator_address.
        aot: ahead-of-time executable routing (runtime/aot.py). When
            True, the warm-path jit entry points (the fused kernels,
            the sharded kernels, the blocked block bodies) execute
            cached ``.lower().compile()`` executables keyed by (spec
            fingerprint, row bucket, mesh geometry, dtype/sharding
            set) instead of re-entering jax.jit's Python dispatch —
            the first call per key compiles (aot_cache_misses), every
            later call across every job and tenant of the process hits
            (aot_cache_hits), with zero Python retraces. Results are
            bit-identical; any entry that cannot lower falls back to
            the traced jit path with one warning. Off by default.
        fused_release: run the dense routes through the fused RELEASE
            kernels (default True): contribution bounding, group
            stats, DP selection, noise and kept-first compaction as
            ONE device program, so the host fetches a scalar gate plus
            O(kept) columns instead of the dense bool[P] keep vector
            and [P] outputs. Bit-identical to False (the unfused
            kernel + host-side np.nonzero decode — kept as the
            comparison baseline).
        overlap_drain: compute/drain overlap on the blocked drivers
            (opt-in, default False): block b's drain sync, journal
            fsync and staged transfers run on a dedicated drainer
            thread while block b+1 dispatches. Blocks are consumed
            strictly FIFO under the same watchdog/health/fault scopes,
            so journal records, replay keys and results are
            bit-identical to the serial consume loop. Opt-in because
            drain deadlines then measure wall time that includes
            dispatch-side compile contention — on a shared-core host a
            tight timeout_s can expire on drains that are merely
            queued behind a compile; pair with a generous deadline.
        trace: span-based pipeline tracing (runtime/trace.py). When
            True, every run records nested, job-scoped spans (stage
            phases, per-block dispatch/drain, reshard collectives with
            byte counts, jit compile attribution) and instant events for
            every runtime incident the counters record. Export with
            dump_trace(path) (Chrome/Perfetto trace-event JSON) or read
            trace_summary(). Off (the default) costs one bool check per
            call site — the blocked-driver hot path is unaffected.
        metrics_port: live Prometheus scrape endpoint
            (runtime/observability.py). When set, a background thread
            serves every declared counter and gauge (queue depth, live
            devices, health states, budget remaining, memory
            watermarks) per job_id at
            http://127.0.0.1:<port>/metrics WHILE runs are in flight —
            0 binds an ephemeral port, read back via
            backend.metrics_endpoint(). None (default) serves nothing.
        metrics_path: the portless scrape mode for CI sandboxes that
            cannot open sockets: the same Prometheus text re-written
            atomically (write-then-rename, never torn) to this file
            every ~250ms. Combinable with metrics_port; None (default)
            writes nothing.
        numeric_mode: accumulation arithmetic discipline for the fused
            release kernels (pipelinedp_tpu/numeric.py). "fast" (the
            default) keeps the historical f32 segment reduction —
            bit-identical programs, the release sentinel only refuses
            NaN/Inf. "safe" switches segment sums to a compensated
            (TwoSum hi/lo) associative scan — exact for integer-valued
            workloads to ~2**48 — and arms the sentinel's overflow
            classification: saturation raises a typed
            NumericOverflowError, the release fails closed (nothing
            decoded, nothing journaled, budget settled conservatively).
        snap_grid_bits: floor exponent for the power-of-two snapping
            grid used by the discrete/snapped mechanisms and the
            secure-noise tables: releases land on multiples of
            max(mechanism grid, 2**snap_grid_bits). None (default)
            leaves the mechanism-chosen grid alone; coarser grids cost
            sensitivity (the snap widens Δ by one grid unit).
    """

    def __init__(self,
                 mesh=None,
                 max_partitions: Optional[int] = None,
                 noise_seed: Optional[int] = None,
                 secure_noise: bool = False,
                 large_partition_threshold: Optional[int] = 1 << 21,
                 reshard: str = "auto",
                 retry=None,
                 journal=None,
                 job_id: Optional[str] = None,
                 block_partitions: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 watchdog=None,
                 elastic: bool = False,
                 elastic_grow: bool = False,
                 min_devices: int = 1,
                 trace: bool = False,
                 aot: bool = False,
                 fused_release: bool = True,
                 overlap_drain: bool = False,
                 pipeline_depth: Optional[int] = None,
                 encode_threads: Optional[int] = None,
                 encode_mode: str = "host",
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 metrics_port: Optional[int] = None,
                 metrics_path: Optional[str] = None,
                 numeric_mode: str = "fast",
                 snap_grid_bits: Optional[int] = None):
        super().__init__(seed=noise_seed)
        if reshard not in ("auto", "host", "device"):
            raise ValueError(
                f"reshard must be auto|host|device, got {reshard!r}")
        # Runtime knobs are validated here, at the API boundary, so a bad
        # timeout/job_id/retry budget fails with an actionable message
        # instead of deep inside the journal or the watchdog monitor.
        if timeout_s is not None:
            input_validators.validate_timeout_s(timeout_s, "TPUBackend")
        if job_id is not None:
            input_validators.validate_job_id(job_id, "TPUBackend")
        if retry is not None:
            input_validators.validate_retry_policy(retry, "TPUBackend")
        if journal is not None:
            input_validators.validate_journal(journal, "TPUBackend")
        if watchdog is not None:
            input_validators.validate_watchdog(watchdog, "TPUBackend")
        input_validators.validate_elastic(elastic, "TPUBackend")
        input_validators.validate_elastic_grow(elastic_grow, "TPUBackend")
        input_validators.validate_min_devices(min_devices, "TPUBackend")
        input_validators.validate_trace(trace, "TPUBackend")
        input_validators.validate_aot(aot, "TPUBackend")
        input_validators.validate_fused_release(fused_release, "TPUBackend")
        input_validators.validate_overlap_drain(overlap_drain, "TPUBackend")
        if pipeline_depth is not None:
            input_validators.validate_pipeline_depth(
                pipeline_depth, "TPUBackend")
        if encode_threads is not None:
            input_validators.validate_encode_threads(
                encode_threads, "TPUBackend")
        input_validators.validate_encode_mode(encode_mode, "TPUBackend")
        if num_processes is not None:
            input_validators.validate_num_processes(
                num_processes, "TPUBackend")
        if coordinator_address is not None:
            input_validators.validate_coordinator_address(
                coordinator_address, "TPUBackend")
        if metrics_port is not None:
            input_validators.validate_metrics_port(
                metrics_port, "TPUBackend")
        if metrics_path is not None:
            input_validators.validate_metrics_path(
                metrics_path, "TPUBackend")
        input_validators.validate_numeric_mode(numeric_mode, "TPUBackend")
        if snap_grid_bits is not None:
            input_validators.validate_snap_grid_bits(
                snap_grid_bits, "TPUBackend")
        if (coordinator_address is None) != (num_processes is None):
            raise ValueError(
                "TPUBackend: coordinator_address and num_processes must "
                "be set together — they are the two halves of the "
                "jax.distributed bring-up (process_id comes from "
                "JAX_PROCESS_INDEX or cluster auto-detection).")
        if coordinator_address is not None and num_processes > 1:
            # Multi-controller bring-up BEFORE any mesh is touched:
            # jax.devices() must already span the pod when the caller
            # builds (or defaults) the mesh. Idempotent across backends.
            from pipelinedp_tpu.parallel import mesh as mesh_lib
            mesh_lib.initialize_distributed(coordinator_address,
                                            num_processes)
        self.mesh = mesh
        self.max_partitions = max_partitions
        self.noise_seed = noise_seed
        self.secure_noise = secure_noise
        self.large_partition_threshold = large_partition_threshold
        self.reshard = reshard
        self.retry = retry
        self.journal = journal
        self.job_id = job_id
        self.block_partitions = block_partitions
        self.timeout_s = timeout_s
        self.watchdog = watchdog
        self.elastic = elastic
        self.elastic_grow = elastic_grow
        self.min_devices = min_devices
        self.trace = trace
        self.aot = aot
        self.fused_release = fused_release
        self.overlap_drain = overlap_drain
        self.pipeline_depth = pipeline_depth
        self.encode_threads = encode_threads
        self.encode_mode = encode_mode
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        self.metrics_port = metrics_port
        self.metrics_path = metrics_path
        self.numeric_mode = numeric_mode
        self.snap_grid_bits = snap_grid_bits
        if trace:
            from pipelinedp_tpu.runtime import trace as rt_trace
            rt_trace.enable()
        # Live metrics exporters (HTTP endpoint and/or atomic file):
        # started here so counters and gauges are scrapeable from the
        # first aggregation, stopped via stop_metrics().
        self._metrics_exporters = []
        if metrics_port is not None or metrics_path is not None:
            from pipelinedp_tpu.runtime import observability as rt_obs
            if metrics_port is not None:
                self._metrics_exporters.append(
                    rt_obs.start_exporter(port=metrics_port))
            if metrics_path is not None:
                self._metrics_exporters.append(
                    rt_obs.start_exporter(path=metrics_path))
        # Job ids whose health this backend's aggregations fed (the
        # executor records them as it resolves/derives them).
        self._health_jobs = set()

    @property
    def is_tpu(self) -> bool:
        return True

    def for_job(self,
                job_id: Optional[str] = None,
                noise_seed: Optional[int] = None,
                journal=None) -> 'TPUBackend':
        """A job-scoped view of this backend for concurrent multiplexing.

        The multi-tenant service (pipelinedp_tpu/service/) holds ONE
        backend/mesh for its lifetime but runs many jobs on it at once;
        each job needs its own noise seed and job id without mutating
        the shared backend under a concurrent sibling. The derived
        backend shares the mesh and every data-plane/runtime knob —
        jit-compiled entry points are cached per function + shapes +
        static config, so identical specs submitted through different
        for_job views hit the SAME compiled programs (the compile-cache
        reuse the service asserts) — while job_id/noise_seed/journal
        override per job. Metrics exporters and distributed bring-up
        stay owned by the parent: a view never starts or stops either.
        """
        return TPUBackend(
            mesh=self.mesh,
            max_partitions=self.max_partitions,
            noise_seed=(self.noise_seed if noise_seed is None
                        else noise_seed),
            secure_noise=self.secure_noise,
            large_partition_threshold=self.large_partition_threshold,
            reshard=self.reshard,
            retry=self.retry,
            journal=(self.journal if journal is None else journal),
            job_id=(self.job_id if job_id is None else job_id),
            block_partitions=self.block_partitions,
            timeout_s=self.timeout_s,
            watchdog=self.watchdog,
            elastic=self.elastic,
            elastic_grow=self.elastic_grow,
            min_devices=self.min_devices,
            aot=self.aot,
            fused_release=self.fused_release,
            overlap_drain=self.overlap_drain,
            pipeline_depth=self.pipeline_depth,
            encode_threads=self.encode_threads,
            encode_mode=self.encode_mode,
            numeric_mode=self.numeric_mode,
            snap_grid_bits=self.snap_grid_bits)

    def dump_trace(self, path: str, job_id: Optional[str] = None) -> str:
        """Writes the recorded trace as Chrome/Perfetto trace-event JSON
        (load in ui.perfetto.dev or chrome://tracing). With a job_id,
        only that job's events. Returns the path. Requires
        TPUBackend(trace=True) (or runtime.trace.enable()) to have been
        on while the runs of interest executed."""
        from pipelinedp_tpu.runtime import trace as rt_trace
        return rt_trace.dump(path, job_id=job_id)

    def trace_summary(self, job_id: Optional[str] = None) -> dict:
        """In-memory trace rollup: top spans by inclusive/exclusive wall
        time, instant-event counts, transferred bytes and per-entry-point
        jit compile stats — see runtime/trace.trace_summary."""
        from pipelinedp_tpu.runtime import trace as rt_trace
        return rt_trace.trace_summary(job_id=job_id)

    def health(self) -> dict:
        """Health snapshots of the jobs this backend has run (or, before
        any blocked run attributed a job to this backend, every job the
        process tracked): {job_id: {state, counters, phase_seconds,
        journal_quarantined, ...}} — see runtime/health.py for the
        HEALTHY/DEGRADED/STALLED/FAILED semantics."""
        from pipelinedp_tpu.runtime import health as rt_health
        snaps = rt_health.snapshot_all()
        jobs = set(self._health_jobs)
        if self.job_id is not None:
            jobs.add(self.job_id)
        if jobs:
            return {j: s for j, s in snaps.items() if j in jobs}
        return snaps

    def odometer(self, job_id: Optional[str] = None,
                 accountant=None) -> dict:
        """The privacy-budget odometer: spent-vs-remaining over the
        ordered per-mechanism audit trail (one record per
        BudgetAccountant registration — job, metric, mechanism kind,
        eps/delta share, process provenance). Filter by job_id and/or
        a specific accountant; with an accountant the report includes
        total/remaining epsilon and `reconciled` (record count ==
        mechanism_count AND eps shares sum exactly to the ledger's
        spent epsilon). See runtime/observability.odometer_report."""
        from pipelinedp_tpu.runtime import observability as rt_obs
        return rt_obs.odometer_report(accountant=accountant,
                                      job_id=job_id)

    def scrape_metrics(self) -> str:
        """The current Prometheus exposition text (counters + gauges,
        gauge sources refreshed) — the same bytes the metrics_port
        endpoint and metrics_path file serve. Works without either
        knob."""
        from pipelinedp_tpu.runtime import observability as rt_obs
        return rt_obs.render_prometheus()

    def metrics_endpoint(self) -> Optional[str]:
        """The live scrape address: the HTTP URL when metrics_port is
        configured (resolved ephemeral port included), else the
        metrics_path file, else None."""
        for exporter in self._metrics_exporters:
            if exporter.port is not None:
                return exporter.endpoint
        for exporter in self._metrics_exporters:
            return exporter.endpoint
        return None

    def stop_metrics(self) -> None:
        """Stops this backend's metrics exporters (the HTTP server
        thread and/or the file re-writer)."""
        for exporter in self._metrics_exporters:
            exporter.stop()
        self._metrics_exporters = []


# Lambdas cannot be pickled for Pool.map; with the fork start method the
# function is instead inherited by workers through a module-global set by the
# pool initializer (the reference uses the same workaround,
# pipeline_backend.py:586-598).
_pool_current_func = None


def _pool_worker_init(func):
    global _pool_current_func
    _pool_current_func = func


def _pool_worker(row):
    return _pool_current_func(row)


def _pool_worker_flat(row):
    # flat_map fns may return generators, which can't be pickled back to the
    # driver — materialize in the worker.
    return list(_pool_current_func(row))


class MultiProcLocalBackend(PipelineBackend):
    """Multiprocessing backend: elementwise stages fan out over a Pool.

    Stages materialize their input (no laziness); keyed ops run on the driver.
    Experimental — mirrors the reference's experimental status
    (pipeline_backend.py:586-823).
    """

    def __init__(self, n_jobs: Optional[int] = None):
        import multiprocessing as mp
        self._mp = mp
        self._n_jobs = n_jobs or mp.cpu_count()
        self._local = LocalBackend()

    def to_multi_transformable_collection(self, col):
        # Generators from this backend's lazy stages are single-iteration;
        # the contract requires re-iterability.
        return list(col)

    def _pool_map(self, fn, data):
        with self._mp.Pool(self._n_jobs,
                           initializer=_pool_worker_init,
                           initargs=(fn,)) as pool:
            return pool.map(_pool_worker, data)

    def map(self, col, fn, stage_name: str = None):
        # Lazy: the pool fan-out happens on first iteration, preserving the
        # two-phase budget protocol (results materialized only after
        # compute_budgets()).
        def gen():
            yield from self._pool_map(fn, list(col))

        return gen()

    def map_with_side_inputs(self, col, fn, side_input_cols, stage_name=None):
        return self._local.map_with_side_inputs(col, fn, side_input_cols)

    def flat_map(self, col, fn, stage_name: str = None):

        def gen():
            with self._mp.Pool(self._n_jobs,
                               initializer=_pool_worker_init,
                               initargs=(fn,)) as pool:
                batches = pool.map(_pool_worker_flat, list(col))
            for batch in batches:
                yield from batch

        return gen()

    def map_tuple(self, col, fn, stage_name: str = None):
        return (fn(*x) for x in col)

    def map_values(self, col, fn, stage_name: str = None):
        return self._local.map_values(col, fn)

    def group_by_key(self, col, stage_name: str = None):
        return self._local.group_by_key(col)

    def filter(self, col, fn, stage_name: str = None):
        return self._local.filter(col, fn)

    def filter_by_key(self, col, keys_to_keep, stage_name: str = None):
        return self._local.filter_by_key(col, keys_to_keep)

    def keys(self, col, stage_name: str = None):
        return self._local.keys(col)

    def values(self, col, stage_name: str = None):
        return self._local.values(col)

    def sample_fixed_per_key(self, col, n: int, stage_name: str = None):
        return self._local.sample_fixed_per_key(col, n)

    def count_per_element(self, col, stage_name: str = None):
        return self._local.count_per_element(col)

    def sum_per_key(self, col, stage_name: str = None):
        return self._local.sum_per_key(col)

    def combine_accumulators_per_key(self, col, combiner, stage_name=None):
        return self._local.combine_accumulators_per_key(col, combiner)

    def reduce_per_key(self, col, fn, stage_name: str = None):
        return self._local.reduce_per_key(col, fn)

    def flatten(self, cols, stage_name: str = None):
        return itertools.chain(*cols)

    def distinct(self, col, stage_name: str = None):
        return self._local.distinct(col)

    def to_list(self, col, stage_name: str = None):
        return iter([list(col)])

    def annotate(self, col, stage_name: str, **kwargs):
        return self._local.annotate(col, stage_name, **kwargs)


if beam is not None:

    class BeamBackend(PipelineBackend):
        """Apache Beam adapter (optional dependency, reference :223-374)."""

        def __init__(self, suffix: str = ""):
            self._ulg = UniqueLabelsGenerator(suffix)

        @property
        def unique_lable_generator(self):  # reference-compatible name
            return self._ulg

        def to_collection(self, collection_or_iterable, col, stage_name):
            if isinstance(collection_or_iterable, beam.PCollection):
                return collection_or_iterable
            return col.pipeline | self._ulg.unique(stage_name) >> beam.Create(
                collection_or_iterable)

        def map(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Map(fn)

        def map_with_side_inputs(self, col, fn, side_input_cols, stage_name):
            side_inputs = [
                beam.pvalue.AsList(side) for side in side_input_cols
            ]
            return col | self._ulg.unique(stage_name) >> beam.Map(
                fn, *side_inputs)

        def flat_map(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.FlatMap(fn)

        def flat_map_with_side_inputs(self, col, fn, side_input_cols,
                                      stage_name):
            side_inputs = [
                beam.pvalue.AsList(side) for side in side_input_cols
            ]
            return col | self._ulg.unique(stage_name) >> beam.FlatMap(
                fn, *side_inputs)

        def map_tuple(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Map(
                lambda x: fn(*x))

        def map_values(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.MapTuple(
                lambda k, v: (k, fn(v)))

        def group_by_key(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.GroupByKey()

        def filter(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Filter(fn)

        def filter_by_key(self, col, keys_to_keep, stage_name):

            class PartitionsFilterJoin(beam.DoFn):

                def process(self, joined_data):
                    key, rest = joined_data
                    values, to_keep = rest.get(VALUES), rest.get(TO_KEEP)
                    if not values:
                        return
                    if to_keep:
                        for value in values:
                            yield key, value

            VALUES, TO_KEEP = 0, 1
            if isinstance(keys_to_keep, (list, set)):
                keys_to_keep_pcol = col.pipeline | self._ulg.unique(
                    "keys_to_keep") >> beam.Create(keys_to_keep)
            else:
                keys_to_keep_pcol = keys_to_keep
            keys_to_keep_kv = keys_to_keep_pcol | self._ulg.unique(
                "key_by") >> beam.Map(lambda k: (k, True))
            return ({
                VALUES: col,
                TO_KEEP: keys_to_keep_kv
            } | self._ulg.unique(stage_name) >> beam.CoGroupByKey() |
                    self._ulg.unique("Filter join") >> beam.ParDo(
                        PartitionsFilterJoin()))

        def keys(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Keys()

        def values(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Values()

        def sample_fixed_per_key(self, col, n, stage_name):
            return col | self._ulg.unique(
                stage_name) >> beam.combiners.Sample.FixedSizePerKey(n)

        def count_per_element(self, col, stage_name):
            return col | self._ulg.unique(
                stage_name) >> beam.combiners.Count.PerElement()

        def sum_per_key(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.CombinePerKey(sum)

        def combine_accumulators_per_key(self, col, combiner, stage_name):

            def merge_accumulators(accumulators):
                return functools.reduce(combiner.merge_accumulators,
                                        accumulators)

            return col | self._ulg.unique(stage_name) >> beam.CombinePerKey(
                merge_accumulators)

        def reduce_per_key(self, col, fn, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.CombinePerKey(
                lambda values: functools.reduce(fn, values))

        def flatten(self, cols, stage_name):
            return tuple(cols) | self._ulg.unique(stage_name) >> beam.Flatten()

        def distinct(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.Distinct()

        def to_list(self, col, stage_name):
            return col | self._ulg.unique(stage_name) >> beam.combiners.ToList()

        def annotate(self, col, stage_name, **kwargs):
            for annotator in _annotators:
                col = annotator.annotate(col, self,
                                         self._ulg.unique(stage_name), **kwargs)
            return col


if pyspark is not None:

    class SparkRDDBackend(PipelineBackend):
        """PySpark RDD adapter (optional dependency, reference :377-474)."""

        def __init__(self, sc: 'pyspark.SparkContext'):
            self._sc = sc

        def to_collection(self, collection_or_iterable, col, stage_name):
            if isinstance(collection_or_iterable, pyspark.RDD):
                return collection_or_iterable
            return self._sc.parallelize(collection_or_iterable)

        def map(self, col, fn, stage_name=None):
            return col.map(fn)

        def map_with_side_inputs(self, col, fn, side_input_cols, stage_name):
            raise NotImplementedError(
                "map_with_side_inputs is not implemented for SparkRDDBackend.")

        def flat_map(self, col, fn, stage_name=None):
            return col.flatMap(fn)

        def map_tuple(self, col, fn, stage_name=None):
            return col.map(lambda x: fn(*x))

        def map_values(self, col, fn, stage_name=None):
            return col.mapValues(fn)

        def group_by_key(self, col, stage_name=None):
            return col.groupByKey()

        def filter(self, col, fn, stage_name=None):
            return col.filter(fn)

        def filter_by_key(self, col, keys_to_keep, stage_name=None):
            if isinstance(keys_to_keep, pyspark.RDD):
                filtering_rdd = keys_to_keep.map(lambda x: (x, None))
                return col.join(filtering_rdd).map(lambda x: (x[0], x[1][0]))
            keys = set(keys_to_keep)
            return col.filter(lambda x: x[0] in keys)

        def keys(self, col, stage_name=None):
            return col.keys()

        def values(self, col, stage_name=None):
            return col.values()

        def sample_fixed_per_key(self, col, n, stage_name=None):
            # Uniformity caveat matches the reference (:446-449).
            return col.groupByKey().mapValues(
                lambda vals: sampling_utils.
                choose_from_list_without_replacement(list(vals), n))

        def count_per_element(self, col, stage_name=None):
            return col.map(lambda x: (x, 1)).reduceByKey(operator.add)

        def sum_per_key(self, col, stage_name=None):
            return col.reduceByKey(operator.add)

        def combine_accumulators_per_key(self, col, combiner, stage_name=None):
            return col.reduceByKey(combiner.merge_accumulators)

        def reduce_per_key(self, col, fn, stage_name=None):
            return col.reduceByKey(fn)

        def flatten(self, cols, stage_name=None):
            return self._sc.union(list(cols))

        def distinct(self, col, stage_name=None):
            return col.distinct()

        def to_list(self, col, stage_name=None):
            raise NotImplementedError(
                "to_list is not implemented for SparkRDDBackend.")


class Annotator(abc.ABC):
    """User hook attaching metadata (budget, params) to collections."""

    @abc.abstractmethod
    def annotate(self, col, backend: PipelineBackend, stage_name: str,
                 **kwargs):
        """Returns `col` annotated with metadata from kwargs."""


_annotators: List[Annotator] = []


def register_annotator(annotator: Annotator):
    _annotators.append(annotator)
