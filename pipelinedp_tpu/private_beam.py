"""Beam-idiomatic private API: PrivatePCollection + private PTransforms.

Mirrors the reference's pipeline_dp/private_beam.py:41-645 API surface
(MakePrivate, Variance/Mean/Sum/Count/PrivacyIdCount/SelectPartitions,
Map/FlatMap, PrivateCombineFn + CombinePerKey), delegating the shared
param-conversion / engine-invocation logic to private_collection.py so the
Beam layer is only the PTransform plumbing.

Requires apache_beam; importing this module without it raises ImportError.
"""

from typing import Callable, Optional

from apache_beam import pvalue
from apache_beam.transforms import ptransform

from pipelinedp_tpu import aggregate_params
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import dp_engine as dp_engine_mod
from pipelinedp_tpu import data_extractors
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu import private_collection
from pipelinedp_tpu.private_collection import (  # re-export (reference parity)
    CombinePerKeyParams, PrivateCombineFn,)

# Beam requires globally-unique stage names; one shared BeamBackend provides
# the unique-label generator for all private transforms
# (reference private_beam.py:26-38).
_beam_backend = None


def _get_beam_backend() -> 'pipeline_backend.BeamBackend':
    global _beam_backend
    if _beam_backend is None:
        _beam_backend = pipeline_backend.BeamBackend()
    return _beam_backend


class PrivatePTransform(ptransform.PTransform):
    """Abstract base for private PTransforms (reference private_beam.py:41)."""

    def __init__(self, return_anonymized: bool, label: Optional[str] = None):
        label = _get_beam_backend()._ulg.unique(label)
        super().__init__(label)
        self._return_anonymized = return_anonymized
        self._budget_accountant = None

    def set_additional_parameters(
            self, budget_accountant: budget_accounting.BudgetAccountant):
        self._budget_accountant = budget_accountant

    def __rrshift__(self, label):
        self.label = _get_beam_backend()._ulg.unique(label)
        return self

    def expand(self, pcol: pvalue.PCollection) -> pvalue.PCollection:
        raise NotImplementedError()


class PrivatePCollection:
    """Private counterpart of a PCollection: only DP-aggregated data can be
    extracted, via PrivatePTransforms (reference private_beam.py:71-94)."""

    def __init__(self, pcol: pvalue.PCollection,
                 budget_accountant: budget_accounting.BudgetAccountant):
        self._pcol = pcol
        self._budget_accountant = budget_accountant

    def __or__(self, private_transform: PrivatePTransform):
        if not isinstance(private_transform, PrivatePTransform):
            raise TypeError(
                "private_transform should be of type PrivatePTransform but is "
                f"{private_transform}")
        private_transform.set_additional_parameters(
            budget_accountant=self._budget_accountant)
        transformed = self._pcol.pipeline.apply(private_transform, self._pcol)
        if private_transform._return_anonymized:
            return transformed
        return PrivatePCollection(transformed, self._budget_accountant)


class MakePrivate(PrivatePTransform):
    """Wraps a PCollection into a PrivatePCollection."""

    def __init__(self,
                 budget_accountant: budget_accounting.BudgetAccountant,
                 privacy_id_extractor: Callable,
                 label: Optional[str] = None):
        super().__init__(return_anonymized=False, label=label)
        self._budget_accountant = budget_accountant
        self._privacy_id_extractor = privacy_id_extractor

    def expand(self, pcol: pvalue.PCollection):
        backend = _get_beam_backend()
        pcol = backend.map(pcol, lambda x: (self._privacy_id_extractor(x), x),
                           "Extract privacy id")
        return PrivatePCollection(pcol, self._budget_accountant)


class _SingleMetricPTransform(PrivatePTransform):
    """Shared body of the per-metric transforms: delegate to the
    framework-neutral single-metric aggregation."""

    _METRIC_NAME = None

    def __init__(self,
                 metric_params,
                 label: Optional[str] = None,
                 public_partitions=None,
                 out_explain_computaton_report=None,
                 out_explain_computation_report=None):
        # Both kwarg spellings accepted: the misspelled one is reference
        # parity (private_beam.py:122), the correct one matches
        # DPEngine.aggregate and PrivateCollection.
        super().__init__(return_anonymized=True, label=label)
        self._metric_params = metric_params
        self._public_partitions = public_partitions
        self._explain_computaton_report = (out_explain_computation_report or
                                           out_explain_computaton_report)

    def expand(self, pcol: pvalue.PCollection) -> pvalue.PCollection:
        return private_collection.run_single_metric_aggregation(
            _get_beam_backend(), self._budget_accountant, pcol,
            self._metric_params, self._METRIC_NAME, self._public_partitions,
            self._explain_computaton_report)


class Variance(_SingleMetricPTransform):
    """DP variance per partition (reference private_beam.py:115)."""
    _METRIC_NAME = 'variance'


class Mean(_SingleMetricPTransform):
    """DP mean per partition (reference private_beam.py:179)."""
    _METRIC_NAME = 'mean'


class Sum(_SingleMetricPTransform):
    """DP sum per partition (reference private_beam.py:241)."""
    _METRIC_NAME = 'sum'


class Count(_SingleMetricPTransform):
    """DP count per partition (reference private_beam.py:303)."""
    _METRIC_NAME = 'count'


class PrivacyIdCount(_SingleMetricPTransform):
    """DP distinct-privacy-id count per partition
    (reference private_beam.py:367)."""
    _METRIC_NAME = 'privacy_id_count'


class SelectPartitions(PrivatePTransform):
    """DP partition-key selection (reference private_beam.py:428-452)."""

    def __init__(
            self,
            select_partitions_params: aggregate_params.SelectPartitionsParams,
            partition_extractor: Callable,
            label: Optional[str] = None):
        super().__init__(return_anonymized=True, label=label)
        self._select_partitions_params = select_partitions_params
        self._partition_extractor = partition_extractor

    def expand(self, pcol: pvalue.PCollection) -> pvalue.PCollection:
        backend = _get_beam_backend()
        engine = dp_engine_mod.DPEngine(self._budget_accountant, backend)
        extractors = data_extractors.DataExtractors(
            partition_extractor=lambda x: self._partition_extractor(x[1]),
            privacy_id_extractor=lambda x: x[0])
        return engine.select_partitions(pcol, self._select_partitions_params,
                                        extractors)


class Map(PrivatePTransform):
    """Non-anonymizing element transform (reference private_beam.py:455)."""

    def __init__(self, fn: Callable, label: Optional[str] = None):
        super().__init__(return_anonymized=False, label=label)
        self._fn = fn

    def expand(self, pcol: pvalue.PCollection):
        return _get_beam_backend().map_values(pcol, self._fn, "Map")


class FlatMap(PrivatePTransform):
    """Non-anonymizing expansion (reference private_beam.py:469)."""

    def __init__(self, fn: Callable, label: Optional[str] = None):
        super().__init__(return_anonymized=False, label=label)
        self._fn = fn

    def expand(self, pcol: pvalue.PCollection):

        def fn(row):
            key = row[0]
            for value in self._fn(row[1]):
                yield key, value

        return _get_beam_backend().flat_map(pcol, fn, "FlatMap")


class CombinePerKey(PrivatePTransform):
    """Custom private combine over (key, value) elements
    (reference private_beam.py:603-644)."""

    def __init__(self,
                 combine_fn: PrivateCombineFn,
                 params: CombinePerKeyParams,
                 label: Optional[str] = None):
        super().__init__(return_anonymized=True, label=label)
        self._combine_fn = combine_fn
        self._params = params

    def expand(self, pcol: pvalue.PCollection):
        return private_collection.run_combine_per_key(
            _get_beam_backend(), self._budget_accountant, pcol,
            self._combine_fn, self._params)
