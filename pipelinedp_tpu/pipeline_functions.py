"""Backend-generic composite pipeline functions.

Reference parity: pipeline_dp/pipeline_functions.py:23-109.
"""

import dataclasses
from typing import Any, Callable, Dict, Type

from pipelinedp_tpu import pipeline_backend


def key_by(backend: pipeline_backend.PipelineBackend, col,
           key_extractor: Callable, stage_name: str):
    """element -> (key_extractor(element), element)."""
    return backend.map(col, lambda el: (key_extractor(el), el),
                       f"{stage_name}: key by")


def size(backend: pipeline_backend.PipelineBackend, col, stage_name: str):
    """Returns a 1-element collection with the number of elements."""
    col = backend.map(col, lambda x: "fake_common_key",
                      f"{stage_name}: mapped to common key")
    col = backend.count_per_element(col, f"{stage_name}: counted elements")
    return backend.values(col, f"{stage_name}: extracted counts")


def collect_to_container(backend: pipeline_backend.PipelineBackend,
                         cols: Dict[str, Any], container_class: Type,
                         stage_name: str):
    """Collects several 1-element collections into one container dataclass.

    Args:
        cols: {field_name: 1-element collection}; field names must match
            container_class's dataclass fields.
        container_class: dataclass to construct.
    """
    field_names = list(cols.keys())
    flattened = backend.flatten(
        [
            backend.map(col, lambda x, name=name: (name, x),
                        f"{stage_name}: key {name} by field name")
            for name, col in cols.items()
        ],
        f"{stage_name}: flatten fields",
    )
    grouped = backend.to_list(flattened, f"{stage_name}: collect fields")

    def construct(kv_pairs):
        kwargs = dict(kv_pairs)
        missing = set(field_names) - set(kwargs)
        if missing:
            raise ValueError(f"missing fields {missing} for "
                             f"{container_class.__name__}")
        return container_class(**kwargs)

    return backend.map(grouped, construct,
                       f"{stage_name}: construct container")


def min_max_elements(backend: pipeline_backend.PipelineBackend, col,
                     stage_name: str):
    """Returns a 1-element collection ((min, max)) of the input collection."""
    col = backend.map(col, lambda x: (None, (x, x)),
                      f"{stage_name}: to (min, max)")
    col = backend.reduce_per_key(
        col, lambda a, b: (min(a[0], b[0]), max(a[1], b[1])),
        f"{stage_name}: reduce to (min, max)")
    return backend.values(col, f"{stage_name}: drop key")
