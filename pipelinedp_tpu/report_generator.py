"""Explain Computation reports.

A human-readable narration of one DP aggregation: input parameters plus the
ordered computation-graph stages. Stage descriptions may be callables so that
values that only exist after BudgetAccountant.compute_budgets() (eps/delta,
noise stddev) resolve lazily at report() time.

Reference parity: pipeline_dp/report_generator.py:46-115. In the TPU build
stage names also become jax.named_scope annotations on the compiled graph
(see executor.py), so the report and the profiler speak the same language.
"""

from typing import Callable, Optional, Union

from pipelinedp_tpu import aggregate_params as agg


class ReportGenerator:
    """Collects ordered stage descriptions for one DP aggregation."""

    def __init__(self,
                 params,
                 method_name: str,
                 is_public_partition: Optional[bool] = None):
        self._params_str = None
        if params:
            self._params_str = agg.parameters_to_readable_string(
                params, is_public_partition)
        self._method_name = method_name
        self._stages = []

    def add_stage(self, stage_description: Union[Callable, str]) -> None:
        """Adds a stage description; may be a Callable resolved at report()
        time (for budget-dependent text)."""
        self._stages.append(stage_description)

    def report(self) -> str:
        """Renders the report text."""
        if not self._params_str:
            return ""
        result = [f"DPEngine method: {self._method_name}"]
        result.append(self._params_str)
        result.append("Computation graph:")
        for i, stage in enumerate(self._stages):
            text = stage() if callable(stage) else stage
            result.append(f" {i + 1}. {text}")
        return "\n".join(result)


class ExplainComputationReport:
    """Out-param container holding the report for one DP aggregation."""

    def __init__(self):
        self._report_generator = None

    def _set_report_generator(self, report_generator: ReportGenerator):
        self._report_generator = report_generator

    def text(self) -> str:
        """Returns the report text.

        Raises:
            ValueError: called before the aggregation, or before
              BudgetAccountant.compute_budgets().
        """
        if self._report_generator is None:
            raise ValueError("The report_generator is not set.\nWas this object"
                             " passed as an argument to DP aggregation method?")
        try:
            return self._report_generator.report()
        except Exception as e:  # noqa: BLE001 - wrap-and-reraise: any stage-formatting failure becomes one actionable ValueError
            raise ValueError(
                "Explain computation report failed to be generated.\n"
                "Was BudgetAccountant.compute_budgets() called?") from e
