"""Chunked, overlapped host->device ingest.

The reference delegates unbounded input to Beam/Spark IO
(pipeline_dp/pipeline_backend.py:223-374); the TPU build's equivalent is a
streaming host pipeline: parse -> factorize -> upload proceeds chunk by
chunk, and because device copies dispatch asynchronously, the upload of
chunk i overlaps the host parse/factorization of chunk i+1. On the 1-core
bench host that overlap — not host parallelism — is what moves end-to-end
time toward max(host encode, device transfer) instead of their sum.

With encode_threads >= 1 the same entry point routes through the
device-resident streaming executor (runtime/pipeline.py): the heavy,
order-independent half of vocabulary encoding (chunk_factorize) runs per
chunk on a host thread pool feeding a bounded staging queue, the cheap
sequential half (ChunkedVocabEncoder.merge) stitches the global
vocabulary in stream order on the consumer, and rows accumulate into
persistent, buffer-donated device buffers (DeviceRowAccumulator) sized
to the executor.pad_rows power-of-two buckets — so the pipelined
encoding is bit-identical to the serial one, down to the padded kernel
input arrays.

The result is a device-resident EncodedData whose columns are jax arrays;
the executor pads it on device (executor.pad_rows) and the engine accepts
it directly in place of a row collection (columnar.encode passthrough), so

    encoded = ingest.stream_encode_columns(chunk_iter)
    result = engine.aggregate(encoded, params, extractors)

is the bulk-file counterpart of handing the engine Python rows.

Contribution bounding is global per privacy id, so the fused kernel still
runs over the full device-resident dataset — streaming here bounds HOST
memory and overlaps transfer, not device memory (the blocked large-P path
owns that axis).
"""

import dataclasses
import logging
from collections import Counter as collections_counter
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import columnar

try:
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is in the standard image
    _pd = None

# Shared NaN canonicalization (columnar.factorize's dict fallback uses the
# same sentinel, so spilled state and chunk factorization agree).
_NAN_KEY = columnar._NAN_KEY
_dict_key = columnar._canonical_key


def _kind_group(dtype) -> str:
    """Coarse dtype family for the sorted-vocab compatibility check."""
    if dtype.kind in "biuf":
        return "num"
    if dtype.kind in "SU":
        return "str"
    return "obj"


def chunk_factorize(raw) -> Tuple[np.ndarray, np.ndarray]:
    """Chunk-local factorization: (int32 codes, uniques in
    first-occurrence order).

    The order-independent, C-speed half of ChunkedVocabEncoder.encode —
    pure and thread-safe, so the streaming executor
    (runtime/pipeline.py) can run it per chunk on the host thread pool
    while the cheap sequential half (``ChunkedVocabEncoder.merge``)
    stitches the global vocabulary in stream order on the consumer.
    """
    raw = columnar._as_key_array(raw)
    if _pd is not None:
        codes, uniques = _pd.factorize(raw, use_na_sentinel=False)
        return codes.astype(np.int32), np.asarray(uniques)
    codes, uniques = columnar.factorize(raw)
    uniques = np.asarray(uniques)
    if columnar._pd is not None:
        # columnar.factorize took its pandas branch, which already
        # yields first-occurrence order — the normalization below would
        # redo a full np.unique + argsort per chunk for nothing.
        return codes.astype(np.int32), uniques
    # Normalize the chunk's uniques to first-occurrence order
    # (factorize's np.unique branch yields sorted order) so new global
    # codes are assigned exactly as one factorize over the concatenation
    # would.
    if len(uniques) > 1:
        _, first_idx = np.unique(codes, return_index=True)
        perm = np.argsort(first_idx)
        if not np.array_equal(perm, np.arange(len(perm))):
            inv = np.empty_like(perm)
            inv[perm] = np.arange(len(perm))
            codes = inv[codes].astype(np.int32)
            uniques = uniques[perm]
    return codes.astype(np.int32), uniques


class ChunkedVocabEncoder:
    """Incremental first-occurrence vocabulary encoding across chunks.

    Feeding chunks in order yields exactly the codes columnar.factorize
    would assign to the concatenation — on the pandas path and on the
    vectorized numpy fallback, including NaN unification (all NaN keys
    share one code, kept out of the sorted vocabulary where comparisons
    would mis-place it) and cross-chunk dtype promotion (a later chunk
    with a wider string / finer numeric dtype widens the stored
    vocabulary instead of truncating new keys). Per chunk: factorization
    (C speed) followed by a vectorized remap of the chunk's uniques
    against a sorted copy of the vocabulary (searchsorted + insert,
    O(V + new·log new)). Only key types numpy cannot order fall back to
    a per-unique dict loop, which canonicalizes NaN through the same
    shared sentinel columnar.factorize's last-resort branch uses.
    """

    def __init__(self):
        self._index = None  # pandas Index (fast path)
        self._sorted_vocab = None  # numpy fallback: sorted non-NaN uniques
        self._sorted_codes = None  # global code of each sorted entry
        self._nan_code: Optional[int] = None  # shared code for NaN keys
        self._next_code = 0  # total codes assigned on the numpy fallback
        self._dict: Optional[dict] = None  # unorderable-key last resort

    def encode(self, raw) -> np.ndarray:
        # _as_key_array inside chunk_factorize: np.asarray first would
        # explode composite (tuple) keys into a 2-D array instead of
        # object elements.
        return self.merge(*chunk_factorize(raw))

    def merge(self, codes: np.ndarray, uniques: np.ndarray) -> np.ndarray:
        """Sequential half of encode(): remaps one chunk's local codes
        (with uniques in first-occurrence order, from chunk_factorize)
        into the global vocabulary. Feeding chunks in stream order keeps
        the global codes identical to a single factorize over the
        concatenation — the pipelined encode calls this on the consumer
        while workers factorize chunks ahead."""
        if _pd is not None:
            uniques = _pd.Index(uniques)
            if self._index is None:
                self._index = uniques
                return codes.astype(np.int32)
            mapped = self._index.get_indexer(uniques)
            is_new = mapped == -1
            if is_new.any():
                mapped[is_new] = len(self._index) + np.arange(
                    int(is_new.sum()))
                self._index = self._index.append(uniques[is_new])
            return mapped.astype(np.int32)[codes]
        if self._dict is not None:
            return self._remap_dict(codes, uniques)
        try:
            return self._remap_sorted(codes, uniques)
        except TypeError:  # unorderable mixed-type keys
            self._spill_to_dict()
            return self._remap_dict(codes, uniques)

    def _remap_sorted(self, codes: np.ndarray,
                      uniques: np.ndarray) -> np.ndarray:
        """Vectorized remap of chunk uniques (first-occurrence order)
        against the sorted global vocabulary."""
        n_u = len(uniques)
        if self._sorted_vocab is None:
            self._sorted_vocab = np.empty(0, uniques.dtype)
            self._sorted_codes = np.empty(0, np.int64)
        elif len(self._sorted_vocab):
            # Mixed number/string chunks must spill to the dict path
            # (where 1.5 and '1.5' stay distinct keys, matching pandas):
            # numpy would otherwise silently STRINGIFY numbers via dtype
            # promotion instead of raising.
            a = _kind_group(self._sorted_vocab.dtype)
            b = _kind_group(uniques.dtype)
            if "obj" not in (a, b) and a != b:
                raise TypeError(
                    f"cannot mix {a} and {b} keys in the sorted vocab")
        # NaN never matches itself under searchsorted/==, so NaN keys are
        # tracked by a dedicated code and kept out of the sorted array
        # (where they would also corrupt later binary searches). Object
        # arrays get the per-element check: an all-float object chunk
        # compares without raising, so it would NOT spill to the dict path.
        if uniques.dtype.kind == "f":
            is_nan = np.isnan(uniques)
        elif uniques.dtype.kind == "O" and n_u:
            is_nan = np.fromiter(
                (_dict_key(k) is _NAN_KEY for k in uniques), bool, count=n_u)
        else:
            is_nan = np.zeros(n_u, bool)
        nan_idx = np.nonzero(is_nan)[0]
        remap = np.empty(n_u, np.int64)
        known = np.zeros(n_u, bool)
        if len(nan_idx) and self._nan_code is not None:
            known[nan_idx] = True
            remap[nan_idx] = self._nan_code
        reg_idx = np.nonzero(~is_nan)[0]
        u = uniques[reg_idx]
        n_vocab = len(self._sorted_vocab)
        if n_vocab and len(u):
            pos = np.searchsorted(self._sorted_vocab, u)  # may TypeError
            pos_c = np.minimum(pos, n_vocab - 1)
            found = (pos < n_vocab) & (self._sorted_vocab[pos_c] == u)
            known[reg_idx[found]] = True
            remap[reg_idx[found]] = self._sorted_codes[pos_c[found]]
        # New codes in first-occurrence order of the chunk (uniques are
        # already ordered that way) = the order a global factorize would
        # meet them. Duplicate NaN uniques (factorize now unifies NaN on
        # every branch, so this is defensive) alias to one representative.
        assign_new = ~known
        nan_is_new = bool(len(nan_idx)) and self._nan_code is None
        if nan_is_new:
            assign_new[nan_idx[1:]] = False
        new_idx = np.nonzero(assign_new)[0]
        remap[new_idx] = self._next_code + np.arange(len(new_idx))
        new_nan_code = None
        if nan_is_new:
            new_nan_code = int(remap[nan_idx[0]])
            remap[nan_idx] = new_nan_code
        new_reg = new_idx[~is_nan[new_idx]]
        if len(new_reg):
            new_u, new_c = uniques[new_reg], remap[new_reg]
            # Widen first: np.insert would silently cast new keys to the
            # stored dtype (truncating e.g. '<U5' into a '<U2' vocab).
            dt = np.promote_types(self._sorted_vocab.dtype,
                                  new_u.dtype)  # may TypeError
            if dt != new_u.dtype:
                new_u = new_u.astype(dt)
            no = np.argsort(new_u, kind="stable")  # may TypeError
            new_u, new_c = new_u[no], new_c[no]
            vocab = self._sorted_vocab
            if dt != vocab.dtype:
                vocab = vocab.astype(dt)
            ins = np.searchsorted(vocab, new_u)  # may TypeError
            # All TypeError-prone ops are done — commit state (a raise
            # above must leave the encoder untouched so the dict spill
            # rebuilds from a consistent vocabulary).
            self._sorted_vocab = np.insert(vocab, ins, new_u)
            self._sorted_codes = np.insert(self._sorted_codes, ins, new_c)
        self._next_code += len(new_idx)
        if nan_is_new:
            self._nan_code = new_nan_code
        return remap[codes].astype(np.int32)

    def _spill_to_dict(self) -> None:
        """Migrates the sorted-vocab state into the dict fallback when a
        chunk introduces keys numpy cannot order."""
        self._dict = {}
        if self._sorted_vocab is not None:
            for key, code in zip(self._sorted_vocab, self._sorted_codes):
                self._dict[key] = int(code)
            if self._nan_code is not None:
                self._dict[_NAN_KEY] = self._nan_code
            # Re-key by code order is unnecessary: dict lookups are by key.
            self._sorted_vocab = self._sorted_codes = None

    def _remap_dict(self, codes: np.ndarray,
                    uniques: np.ndarray) -> np.ndarray:
        remap = np.empty(len(uniques), np.int64)
        for j, key in enumerate(uniques):
            remap[j] = self._dict.setdefault(_dict_key(key),
                                             len(self._dict))
        return remap[codes].astype(np.int32)

    @property
    def vocabulary(self) -> Sequence[Any]:
        if self._index is not None:
            return np.asarray(self._index)
        if self._sorted_vocab is not None:
            dt = self._sorted_vocab.dtype
            if self._nan_code is not None:
                if dt.kind in "biu":
                    dt = np.promote_types(dt, np.float64)
                elif dt.kind != "f":
                    # A string/object vocab cannot hold a float NaN;
                    # promotion to '<U..' would store the STRING 'nan'.
                    dt = np.dtype(object)
            out = np.empty(self._next_code, dtype=dt)
            out[self._sorted_codes] = self._sorted_vocab
            if self._nan_code is not None:
                out[self._nan_code] = np.nan
            return out
        if self._dict:
            vocab = np.empty(len(self._dict), dtype=object)
            for key, code in self._dict.items():
                vocab[code] = np.nan if key is _NAN_KEY else key
            return vocab
        return np.empty(0, dtype=object)

    def __len__(self) -> int:
        if self._index is not None:
            return len(self._index)
        if self._sorted_vocab is not None:
            return self._next_code
        return len(self._dict or ())


@dataclasses.dataclass
class _PreparedChunk:
    """One chunk's thread-pool encode output: chunk-local vocab codes +
    uniques (first-occurrence order) awaiting the sequential merge."""
    pid_codes: np.ndarray
    pid_uniques: np.ndarray
    pk_codes: np.ndarray  # vocab-final when publicly encoded
    pk_uniques: Optional[np.ndarray]  # None when pk was publicly encoded
    values: np.ndarray


def _prepare_chunk(chunk, partition_vocab, nonfinite,
                   value_dtype) -> _PreparedChunk:
    """Order-independent host encode of one chunk (runs on the encode
    thread pool): factorize keys, validate values. The sequential
    vocabulary merge happens on the consumer (ChunkedVocabEncoder.merge),
    so parallel workers can never reorder code assignment."""
    pid_raw, pk_raw, values = chunk
    pid_codes, pid_uniques = chunk_factorize(pid_raw)
    if partition_vocab is not None:
        pk_codes = columnar.encode_with_vocab(
            columnar._as_key_array(pk_raw), partition_vocab)
        pk_uniques = None
    else:
        pk_codes, pk_uniques = chunk_factorize(pk_raw)
    values = np.asarray(values, dtype=value_dtype)
    bad = columnar.nonfinite_value_rows(values, nonfinite)
    if bad is not None:
        pk_codes = np.where(bad, np.int32(-1), pk_codes).astype(np.int32)
        mask = bad if values.ndim == 1 else bad[:, None]
        values = np.where(mask, 0.0, values).astype(value_dtype)
    return _PreparedChunk(pid_codes, pid_uniques, pk_codes, pk_uniques,
                          values)


def _pad_chunk_rows(pid, pk, values, cap: int, fills=(0, -1, 0)):
    """Pads one chunk to `cap` rows with the accumulator's pad values
    (executor.pad_rows' pid 0 / pk -1 / values 0 on the host-encoded
    route; hash sentinels on the hash-device route) for the donating
    device accumulator."""
    n = len(pid)
    if cap == n:
        return pid, pk, values
    pad = cap - n
    pid = np.concatenate(
        [pid, np.full((pad,) + pid.shape[1:], fills[0], pid.dtype)])
    pk = np.concatenate(
        [pk, np.full((pad,) + pk.shape[1:], fills[1], pk.dtype)])
    values = np.concatenate(
        [values,
         np.full((pad,) + values.shape[1:], fills[2], values.dtype)])
    return pid, pk, values


# --- Hash-keyed encode (the host half of encode_mode="hash_device") --------
#
# The device-resident encode mode replaces the sequential vocabulary
# stitch with on-device hash factorization (device_encode.py): chunk
# workers only HASH raw keys to uint64 — vectorized, order-independent,
# perfectly parallel — and the dense integer codes are assigned inside
# jit from the hash columns. Everything below is that host half: two
# independent 64-bit hash lanes per key (lane 1 exists solely so the
# collision detector can tell "same key twice" from "two keys, one
# hash"), per-chunk unique triples feeding the deferred decode table,
# and NaN/dtype canonicalization that keeps hash identity aligned with
# the host encoder's key equality (all NaNs share one code; 3 and 3.0
# unify when both fit a float64 exactly).

# pandas hash_array keys must be exactly 16 bytes; one per hash lane.
_HASH_PD_KEYS = ("pdp_tpu_hash_ln0", "pdp_tpu_hash_ln1")
_HASH_SENTINEL64 = np.uint64((1 << 64) - 1)


def _splitmix64(x: np.ndarray, lane: int) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 bit patterns — a
    BIJECTION on 64 bits, so fixed-width numeric keys can never collide
    (only canonicalization-intended merges). Lane-salted by an input
    xor; used when pandas' C hash is unavailable."""
    x = x ^ np.uint64((0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F)[lane])
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _stable_hash_elements(raw: np.ndarray, lane: int) -> np.ndarray:
    """Per-element stable hash of keys no vectorized path can handle
    (mixed/composite object keys) — the hash counterpart of
    columnar.factorize's dict-loop last resort. Deterministic across
    processes (blake2b, never Python's salted hash); numbers
    canonicalize through float64 so 3, 3.0 and True==1 unify exactly as
    dict keys do."""
    import hashlib
    import pickle

    salt = _HASH_PD_KEYS[lane].encode()
    out = np.empty(len(raw), np.uint64)
    for i, key in enumerate(raw):
        canon = _dict_key(key)
        if canon is _NAN_KEY:
            payload = b"\x00nan"
        elif isinstance(canon, (bool, int, float, np.bool_, np.integer,
                                np.floating)) and \
                float(canon) == canon and abs(float(canon)) < 2.0**53:
            payload = b"\x01" + repr(float(canon)).encode()
        else:
            try:
                payload = pickle.dumps(canon, protocol=4)
            except Exception:  # noqa: BLE001 - unpicklable exotic keys hash by repr; any failure mode here must not kill ingest, only weaken hash quality for that key
                payload = repr(canon).encode()
        digest = hashlib.blake2b(payload, digest_size=8,
                                 key=salt).digest()
        out[i] = np.frombuffer(digest, np.uint64)[0]
    return out


def _canonical_numeric(raw: np.ndarray) -> np.ndarray:
    """Numeric keys canonicalized for hashing: float64 when every value
    is exactly representable (so int 3 and float 3.0 hash identically,
    matching host-encoder key equality), int64 bit patterns otherwise;
    NaNs collapse to the one canonical NaN, -0.0 to +0.0."""
    if raw.dtype.kind in "biu":
        as_f = raw.astype(np.float64)
        # Integers below 2^53 are exact in float64 — unify with floats.
        if bool((np.abs(as_f) < 2.0**53).all()):
            return as_f + 0.0
        return raw.astype(np.int64).view(np.float64)
    x = raw.astype(np.float64)
    x = np.where(np.isnan(x), np.float64("nan"), x)
    return x + 0.0  # -0.0 -> +0.0


_FNV_OFFSETS = (np.uint64(0xCBF29CE484222325),
                np.uint64(0x9AE16A3B2F90404F))
_FNV_PRIME = np.uint64(0x100000001B3)


def _vector_hash_fixed_width(raw: np.ndarray) -> Tuple[np.ndarray,
                                                       np.ndarray]:
    """Both hash lanes of a fixed-width 'U'/'S' key column in ONE pass
    over the character matrix: vectorized FNV-1a over the code units
    (one multiply-xor per character column per lane) finished with the
    splitmix64 bijection. ~50x the throughput of a per-row C hash —
    this is what keeps the hash-device mode's host work to 'read the
    bytes once'."""
    n = len(raw)
    raw = np.ascontiguousarray(raw)
    if raw.dtype.kind == "U":
        width = raw.dtype.itemsize // 4
        mat = raw.view(np.uint32).reshape(n, width) if width else None
    else:
        width = raw.dtype.itemsize
        mat = raw.view(np.uint8).reshape(n, width) if width else None
    h0 = np.full(n, _FNV_OFFSETS[0])
    h1 = np.full(n, _FNV_OFFSETS[1])
    if mat is not None:
        for j in range(mat.shape[1]):
            col = mat[:, j].astype(np.uint64)
            # Zero code units (the fixed-width padding) must not touch
            # the hash: the same key hashes identically whatever array
            # width it arrived in — numpy itself strips trailing NULs,
            # so skipping them mirrors its key equality. The position
            # salt keeps interior characters order-sensitive.
            live = col != 0
            step0 = (h0 ^ (col + np.uint64(0x9E3779B9 * (j + 1)))) * \
                _FNV_PRIME
            step1 = (h1 ^ (col + np.uint64(0xC2B2AE35 * (j + 2)))) * \
                _FNV_PRIME
            h0 = np.where(live, step0, h0)
            h1 = np.where(live, step1, h1)
    return _splitmix64(h0, 0), _splitmix64(h1, 1)


def hash_key_column_pair(raw) -> Tuple[np.ndarray, np.ndarray]:
    """Both deterministic uint64 hash lanes of a key column.

    THE key hash of encode_mode="hash_device": lane 0 is the partition /
    privacy-unit identity the device factorize groups by, lane 1 an
    independent family feeding only the collision detector (computing
    both in one content pass makes the detector ~free). Stable across
    processes and runs (vectorized FNV/splitmix or blake2b — never
    Python's salted hash()), with the uint64 maximum remapped away so
    the device pad sentinel is unreachable from data. Key identity
    follows the host encoder's equality: numeric keys canonicalize
    through float64 (3 == 3.0 == True-as-1), every NaN is one key.
    """
    raw = columnar._as_key_array(raw)
    if len(raw) == 0:
        return np.empty(0, np.uint64), np.empty(0, np.uint64)
    kind = raw.dtype.kind
    pair = None
    if kind in "biuf":
        bits = _canonical_numeric(raw).view(np.uint64)
        pair = (_splitmix64(bits, 0), _splitmix64(bits, 1))
    elif kind in "SU":
        pair = _vector_hash_fixed_width(raw)
    elif kind == "O" and _pd is not None:
        # Gate on a C-speed dtype inference: mixed object arrays (int 1
        # next to "1", tuples, ...) must go to the per-element stable
        # hash, never be silently stringified.
        inferred = _pd.api.types.infer_dtype(raw, skipna=False)
        if inferred == "string":
            pair = _vector_hash_fixed_width(raw.astype(np.str_))
        elif inferred in ("integer", "boolean"):
            bits = _canonical_numeric(raw.astype(np.int64)
                                      if inferred == "integer" else
                                      raw.astype(bool)).view(np.uint64)
            pair = (_splitmix64(bits, 0), _splitmix64(bits, 1))
        elif inferred in ("floating", "mixed-integer-float"):
            bits = _canonical_numeric(
                raw.astype(np.float64)).view(np.uint64)
            pair = (_splitmix64(bits, 0), _splitmix64(bits, 1))
    if pair is None:
        pair = (_stable_hash_elements(raw, 0),
                _stable_hash_elements(raw, 1))
    top = _HASH_SENTINEL64 - np.uint64(1)
    return (np.where(pair[0] == _HASH_SENTINEL64, top, pair[0]),
            np.where(pair[1] == _HASH_SENTINEL64, top, pair[1]))


def hash_key_column(raw, lane: int = 0) -> np.ndarray:
    """One lane of hash_key_column_pair (see there)."""
    return hash_key_column_pair(raw)[lane]


def _hash_uniques(h1: np.ndarray, h2: np.ndarray, raw):
    """Chunk-local distinct (h1, h2) pairs + one representative raw key
    per pair (first occurrence) — the order-independent per-chunk
    contribution to collision detection and the deferred decode table.
    One lexsort over the chunk, no global state."""
    if len(h1) == 0:
        empty = np.empty(0, np.uint64)
        return empty, empty, (raw[:0] if raw is not None else None), \
            np.empty(0, np.int64)
    order = np.lexsort((h2, h1))
    s1, s2 = h1[order], h2[order]
    new = np.empty(len(s1), bool)
    new[0] = True
    new[1:] = (s1[1:] != s1[:-1]) | (s2[1:] != s2[:-1])
    # Representative row per pair: the first occurrence IN CHUNK ORDER
    # (lexsort is stable, so within a pair run row indices ascend).
    first = order[new]
    return s1[new], s2[new], (raw[first] if raw is not None else None), \
        first.astype(np.int64)


@dataclasses.dataclass
class _HashChunk:
    """One chunk's hash-encode output: (n, 3) uint32 hash-row columns
    ([hash_hi, hash_lo, valid]) ready for the device accumulator, plus
    the chunk-local unique triples the consumer stashes (never merges)
    for collision detection and deferred decode."""
    pid_hash: np.ndarray  # (n, 3) uint32
    pid_u1: np.ndarray
    pid_u2: np.ndarray
    pid_pos: np.ndarray  # chunk-local first positions
    pk_col: np.ndarray  # (n, 3) uint32, or int32[n] when public-encoded
    pk_u1: Optional[np.ndarray]
    pk_u2: Optional[np.ndarray]
    pk_keys: Optional[np.ndarray]
    pk_pos: Optional[np.ndarray]  # chunk-local first positions
    values: np.ndarray

    @property
    def n_rows(self) -> int:
        return len(self.pid_hash)


def _prepare_hash_chunk(chunk, partition_vocab, nonfinite,
                        value_dtype) -> _HashChunk:
    """Hash-mode chunk worker (thread-pool safe, no shared state): hash
    both key columns on two lanes, record the chunk's unique pairs,
    validate values. The expensive vocabulary work this replaces
    (_prepare_chunk + the sequential merge) never happens."""
    from pipelinedp_tpu import device_encode

    pid_raw, pk_raw, values = chunk
    pid_raw = columnar._as_key_array(pid_raw)
    pid_h1, pid_h2 = hash_key_column_pair(pid_raw)
    pid_u1, pid_u2, _, pid_pos = _hash_uniques(pid_h1, pid_h2, None)
    if partition_vocab is not None:
        pk_col = columnar.encode_with_vocab(
            columnar._as_key_array(pk_raw), partition_vocab)
        pk_u1 = pk_u2 = pk_keys = pk_pos = None
    else:
        pk_raw = columnar._as_key_array(pk_raw)
        pk_h1, pk_h2 = hash_key_column_pair(pk_raw)
        pk_u1, pk_u2, pk_keys, pk_pos = _hash_uniques(pk_h1, pk_h2,
                                                      pk_raw)
    values = np.asarray(values, dtype=value_dtype)
    bad = columnar.nonfinite_value_rows(values, nonfinite)
    pk_valid = None
    if bad is not None:
        # Same invalid marks as the host route: the row drops out of its
        # partition (pk code -> -1) but BOTH key columns keep their real
        # hashes — the host encoder factorizes the raw columns before
        # rows are invalidated, so even a key seen only on dropped rows
        # claims its vocabulary slot and every later code stays
        # bit-aligned.
        if partition_vocab is not None:
            pk_col = np.where(bad, np.int32(-1), pk_col).astype(np.int32)
        else:
            pk_valid = ~bad
        mask = bad if values.ndim == 1 else bad[:, None]
        values = np.where(mask, 0.0, values).astype(value_dtype)
    if partition_vocab is None:
        pk_col = device_encode.pack_hash_rows(pk_h1, pk_valid)
    return _HashChunk(device_encode.pack_hash_rows(pid_h1), pid_u1,
                      pid_u2, pid_pos, pk_col, pk_u1, pk_u2, pk_keys,
                      pk_pos, values)


def stream_encode_columns(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error",
        encode_threads: int = 0,
        pipeline_depth: Optional[int] = None,
        encode_mode: str = "host") -> columnar.EncodedData:
    """Encodes and uploads (pid_raw, pk_raw, values) column chunks,
    overlapping each chunk's device copy with the next chunk's parsing.

    encode_threads=0 (the default) is the serial path: one loop,
    device copies overlapping the next chunk's parse only through jax's
    async dispatch. encode_threads >= 1 routes through the streaming
    executor (runtime/pipeline.py): chunk parse/factorize runs on a host
    thread pool feeding a bounded staging queue (window =
    ``pipeline_depth``, default the shared PIPELINE_DEPTH), the
    sequential vocabulary merge and device accumulation run on the
    consumer, and rows accumulate into persistent device buffers
    (power-of-two row buckets, donated across appends). Both paths
    yield bit-identical kernel inputs — the pipelined EncodedData
    arrives pre-padded to exactly the executor.pad_rows bucket.

    Non-finite VALUES are rejected per chunk (nonfinite="error", the
    default) or dropped with a warning (nonfinite="drop") — a NaN/Inf
    survives jnp.clip and would silently poison its partition's sums
    (columnar.nonfinite_value_rows).

    encode_mode="hash_device" replaces the host vocabulary work with
    on-device hash factorization (device_encode.py): chunk workers only
    hash raw keys to uint64, raw hash columns stream host->device once
    through the same accumulator, dense first-occurrence codes are
    assigned inside jit, and partition-key decode is deferred to the
    DP-selected indices (HashVocab). Result parity is bit-exact with
    encode_mode="host" under the same noise keys; a detected 64-bit
    hash collision falls back to this exact host encoder (re-iterable
    sources) or raises HashCollisionError (one-shot iterators).

    Returns a device-resident EncodedData (jax-array columns, values in
    the kernel compute dtype — float32 normally, at half the f64 upload
    volume; float64 when jax_enable_x64 is on, so streamed input loses no
    precision relative to the row-input path).
    """
    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.runtime import trace as rt_trace
    if encode_mode not in ("host", "hash_device"):
        raise ValueError(f"encode_mode must be host|hash_device, "
                         f"got {encode_mode!r}")
    if encode_mode == "hash_device":
        return _stream_encode_hash_device(chunks, public_partitions,
                                          nonfinite, encode_threads,
                                          pipeline_depth)
    value_dtype = np.dtype(executor._ftype())

    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))

    def encoded_data(pid, pk, values):
        return columnar.EncodedData(
            pid=pid, pk=pk, values=values,
            partition_vocab=(partition_vocab
                             if partition_vocab is not None else
                             pk_enc.vocabulary),
            n_privacy_ids=len(pid_enc),
            public_encoded=public_partitions is not None)

    if encode_threads:
        return _stream_encode_pipelined(chunks, partition_vocab, nonfinite,
                                        value_dtype, pid_enc, pk_enc,
                                        encoded_data, encode_threads,
                                        pipeline_depth)

    dev_pid, dev_pk, dev_vals = [], [], []
    # The ingest span covers parse+factorize+upload for the whole stream;
    # its row count attribute lets trace summaries report ingest rate.
    with rt_trace.span("ingest") as ingest_span:
        n_rows = 0
        for pid_raw, pk_raw, values in chunks:
            pid = pid_enc.encode(pid_raw)
            if partition_vocab is not None:
                pk = columnar.encode_with_vocab(
                    columnar._as_key_array(pk_raw), partition_vocab)
            else:
                pk = pk_enc.encode(pk_raw)
            values = np.asarray(values, dtype=value_dtype)
            bad = columnar.nonfinite_value_rows(values, nonfinite)
            if bad is not None:
                pk = np.where(bad, np.int32(-1), pk).astype(np.int32)
                mask = bad if values.ndim == 1 else bad[:, None]
                values = np.where(mask, 0.0, values).astype(value_dtype)
            n_rows += len(pid)
            # jnp.asarray dispatches the host->device copy asynchronously;
            # the loop continues into the next chunk's parse while it
            # lands.
            dev_pid.append(jnp.asarray(pid))
            dev_pk.append(jnp.asarray(pk))
            dev_vals.append(jnp.asarray(values))
        if not dev_pid:
            empty = jnp.zeros(0, jnp.int32)
            dev_pid, dev_pk = [empty], [empty]
            dev_vals = [jnp.zeros(0, value_dtype)]
        ingest_span.set(rows=n_rows)
        return encoded_data(jnp.concatenate(dev_pid),
                            jnp.concatenate(dev_pk),
                            jnp.concatenate(dev_vals))


def _stream_encode_pipelined(chunks, partition_vocab, nonfinite,
                             value_dtype, pid_enc, pk_enc, encoded_data,
                             encode_threads: int,
                             pipeline_depth: Optional[int]
                             ) -> columnar.EncodedData:
    """The pipelined body of stream_encode_columns: thread-pool chunk
    factorization -> bounded staging queue -> sequential vocab merge ->
    device-resident bucket accumulation (runtime/pipeline.py)."""
    import functools

    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.runtime import pipeline as rt_pipeline
    from pipelinedp_tpu.runtime import trace as rt_trace

    acc = rt_pipeline.DeviceRowAccumulator(
        batch_rows=rt_pipeline.APPEND_BATCH_ROWS)
    worker = functools.partial(_prepare_chunk,
                               partition_vocab=partition_vocab,
                               nonfinite=nonfinite,
                               value_dtype=value_dtype)
    with rt_trace.span("ingest", threads=encode_threads) as ingest_span:
        n_rows = 0
        for idx, prep in enumerate(
                rt_pipeline.map_overlapped(chunks, worker, encode_threads,
                                           pipeline_depth)):
            # Sequential merge in stream order: global codes are exactly
            # what the serial encode assigns.
            pid = pid_enc.merge(prep.pid_codes, prep.pid_uniques)
            if partition_vocab is not None:
                pk = prep.pk_codes
            else:
                pk = pk_enc.merge(prep.pk_codes, prep.pk_uniques)
            n = len(pid)
            n_rows += n
            values = prep.values
            if n == 0:
                continue
            if acc.donating and not acc.batch_rows:
                pid, pk, values = _pad_chunk_rows(
                    pid, pk, values, executor.row_bucket(n))
            acc.append(pid, pk, values, n, chunk=idx)
        ingest_span.set(rows=n_rows)
        bufs = acc.finalize()
        if bufs is None:
            empty = jnp.zeros(0, jnp.int32)
            return encoded_data(empty, empty, jnp.zeros(0, value_dtype))
        return encoded_data(*bufs)


def _hash_empty_encoded(public: bool, value_dtype,
                        partition_vocab) -> columnar.EncodedData:
    """Empty-stream encoding of the hash route (mirrors the host one)."""
    import jax.numpy as jnp

    from pipelinedp_tpu import device_encode
    empty = jnp.zeros(0, jnp.int32)
    if public:
        vocab = partition_vocab
    else:
        nohash = np.empty(0, np.uint64)
        vocab = device_encode.HashVocab(
            0, nohash, np.empty(0, object),
            hash_by_code_host=nohash)
    return columnar.EncodedData(pid=empty, pk=empty,
                                values=jnp.zeros(0, value_dtype),
                                partition_vocab=vocab, n_privacy_ids=0,
                                public_encoded=public)


def _stream_encode_hash_device(chunks, public_partitions, nonfinite,
                               encode_threads: int,
                               pipeline_depth: Optional[int]
                               ) -> columnar.EncodedData:
    """The encode_mode="hash_device" body of stream_encode_columns.

    Chunk workers hash (thread pool when encode_threads >= 1, exactly
    like the host pipelined route), raw (n, 3) hash rows accumulate into
    the donated device buffers, the consumer stashes per-chunk uniques
    with NO sequential merge, and the dense codes come out of ONE device
    factorize per key column at finalize. Collision detection runs
    (vectorized, over uniques) before any device code is trusted; a trip
    increments ``ingest_hash_collisions`` and falls back to the exact
    host encoder when the source can be re-iterated.
    """
    import functools

    from pipelinedp_tpu import device_encode, executor
    from pipelinedp_tpu.runtime import pipeline as rt_pipeline
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime import trace as rt_trace

    value_dtype = np.dtype(executor._ftype())
    public = public_partitions is not None
    partition_vocab = (list(dict.fromkeys(public_partitions))
                       if public else None)
    # Re-iterability decides the collision-fallback story up front,
    # before the stream is consumed.
    reiterable = iter(chunks) is not chunks
    sent32 = int(device_encode._U32_MAX)
    fills = (sent32, -1 if public else sent32, 0)
    acc = rt_pipeline.DeviceRowAccumulator(
        fills=fills, batch_rows=rt_pipeline.APPEND_BATCH_ROWS)
    pid_u1, pid_u2, pid_pos = [], [], []
    pk_u1, pk_u2, pk_keys, pk_pos = [], [], [], []
    worker = functools.partial(_prepare_hash_chunk,
                               partition_vocab=partition_vocab,
                               nonfinite=nonfinite,
                               value_dtype=value_dtype)
    with rt_trace.span("ingest", encode="hash_device",
                       threads=encode_threads) as ingest_span:
        n_rows = 0
        if encode_threads:
            prepared = rt_pipeline.map_overlapped(chunks, worker,
                                                  encode_threads,
                                                  pipeline_depth)
        else:
            prepared = map(worker, chunks)
        for idx, prep in enumerate(prepared):
            n = prep.n_rows
            pid_u1.append(prep.pid_u1)
            pid_u2.append(prep.pid_u2)
            # Chunk-local first positions -> stream positions (the
            # consumer sees chunks in stream order).
            pid_pos.append(prep.pid_pos + n_rows)
            if not public:
                pk_u1.append(prep.pk_u1)
                pk_u2.append(prep.pk_u2)
                pk_keys.append(prep.pk_keys)
                pk_pos.append(prep.pk_pos + n_rows)
            n_rows += n
            if n == 0:
                continue
            pid_col, pk_col, values = (prep.pid_hash, prep.pk_col,
                                       prep.values)
            if acc.donating and not acc.batch_rows:
                pid_col, pk_col, values = _pad_chunk_rows(
                    pid_col, pk_col, values, executor.row_bucket(n),
                    fills)
            acc.append(pid_col, pk_col, values, n, chunk=idx)
            rt_telemetry.record("pipeline_device_encode_chunks",
                                chunk=idx)
        ingest_span.set(rows=n_rows)
        # Collision safety gate: nothing derived from the device codes
        # is released past this point unless every primary hash maps to
        # exactly one (secondary hash, key) identity.
        try:
            with rt_trace.span("ingest.unique_merge"):
                pid_table = device_encode.merge_hash_uniques(
                    pid_u1, pid_u2, None, pid_pos, what="privacy-id")
                pk_table = None
                if not public:
                    pk_table = device_encode.merge_hash_uniques(
                        pk_u1, pk_u2, pk_keys, pk_pos, what="partition")
        except device_encode.HashCollisionError as err:
            rt_telemetry.record("ingest_hash_collisions")
            logging.warning(
                "hash-device encode detected a 64-bit key-hash "
                "collision (%s); %s", err,
                "falling back to the exact host encoder." if reiterable
                else "the chunk source is a one-shot iterator, so the "
                "exact-encoder fallback cannot re-read it.")
            if not reiterable:
                raise device_encode.HashCollisionError(
                    f"{err} — and the chunk source is a one-shot "
                    f"iterator, so the exact host-encoder fallback "
                    f"cannot re-read it. Pass a re-iterable source "
                    f"(list / factory) or encode_mode='host'.") from err
            return stream_encode_columns(
                chunks, public_partitions=public_partitions,
                nonfinite=nonfinite, encode_threads=encode_threads,
                pipeline_depth=pipeline_depth, encode_mode="host")
        bufs = acc.finalize()
        if bufs is None:
            return _hash_empty_encoded(public, value_dtype,
                                       partition_vocab)
        pid_hash, pk_col, values = bufs
        return _finalize_hash_codes(pid_hash, pk_col, values, public,
                                    partition_vocab, pid_table, pk_table)


def _finalize_hash_codes(pid_hash, pk_col, values, public: bool,
                         partition_vocab, pid_table, pk_table
                         ) -> columnar.EncodedData:
    """Device code assignment + deferred-decode vocabulary of the hash
    stream route (runs inside the ingest span, under its own sub-span
    so the e2e phase breakdown separates in-jit code assignment from
    the host hashing)."""
    import jax.numpy as jnp

    from pipelinedp_tpu import device_encode
    from pipelinedp_tpu.parallel import mesh as mesh_lib
    from pipelinedp_tpu.runtime import trace as rt_trace

    with rt_trace.span("ingest.device_codes"):
        # Two interchangeable in-jit code-assignment kernels (identical
        # codes): the self-contained sort/unique factorize on
        # accelerators, the host-table binary-search lookup on CPU,
        # where XLA's comparator sort is the wrong tool — see
        # device_encode.prefers_lookup_codes.
        lookup = device_encode.prefers_lookup_codes()
        if lookup:
            pid_codes = device_encode.lookup_codes(
                pid_hash,
                *device_encode.build_lookup_table(pid_table[0],
                                                  pid_table[3]))
            n_privacy_ids = pid_table[2]
        else:
            pid_codes, n_pid_dev = device_encode.factorize_codes(
                pid_hash)
        if public:
            if not lookup:
                n_privacy_ids = int(mesh_lib.host_fetch(n_pid_dev))
            vocab = partition_vocab
            pk = pk_col
        else:
            s1, keys, n_pk, pos = pk_table
            if lookup:
                pk = device_encode.lookup_codes(
                    pk_col, *device_encode.build_lookup_table(s1, pos))
            else:
                pk, n_pk_dev = device_encode.factorize_codes(pk_col)
                n_stats = mesh_lib.host_fetch(jnp.stack([n_pid_dev,
                                                         n_pk_dev]))
                n_privacy_ids = int(n_stats[0])
                if int(n_stats[1]) != n_pk:
                    raise RuntimeError(
                        f"device factorize found {int(n_stats[1])} "
                        f"distinct partition hashes but the host unique "
                        f"merge found {n_pk} (internal invariant)")
            # Code order (global first occurrence) is host-derivable
            # from the chunk uniques' positions — decode then needs
            # zero device->host traffic.
            vocab = device_encode.HashVocab(
                n_pk, s1, keys,
                hash_by_code_host=s1[np.argsort(pos, kind="stable")])
        # Pad rows factorize to -1; the pad_rows convention is pid 0.
        pid = jnp.maximum(pid_codes, 0)
        return columnar.EncodedData(pid=pid, pk=pk, values=values,
                                    partition_vocab=vocab,
                                    n_privacy_ids=n_privacy_ids,
                                    public_encoded=public)


# --- Multi-host ingest -----------------------------------------------------
#
# The reference scales unbounded IO by handing it to Beam/Spark workers
# (pipeline_dp/pipeline_backend.py:223-374). The TPU-native equivalent is
# host-sharded ingest: in a multi-host deployment each host process parses
# and vocab-encodes ITS contiguous shard of the input independently
# (encode_shard — pure numpy, no device), the per-host vocabularies are
# merged with one pass of the same incremental encoder
# (merge_host_vocabularies — the returned codes ARE each host's
# local->global remap), and each host remaps + uploads only its own rows
# to its local devices, so the only cross-host (DCN) traffic is the
# vocabularies and O(uniques) remap vectors — never row data. With hosts
# owning contiguous shards in stream order, the merged codes are exactly
# what a single-process factorize of the whole stream would assign.


@dataclasses.dataclass
class ShardEncoding:
    """One host's locally-encoded shard: int32 code columns + the local
    vocabularies they index. Picklable (pure numpy) so worker processes
    can ship it back to the coordinator."""
    pid: np.ndarray
    pk: np.ndarray
    values: np.ndarray
    pid_vocab: np.ndarray
    pk_vocab: Optional[np.ndarray]  # None when pk was publicly encoded


def encode_shard(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error") -> ShardEncoding:
    """Host-local chunked encoding of one input shard (no device work).

    The multi-host counterpart of stream_encode_columns' parse+factorize
    stage: runs in each ingest process over its own chunk iterator. The
    same per-chunk non-finite value policy applies (each ingest worker
    rejects/drops at its own boundary, so poisoned rows never travel).
    """
    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
    pids, pks, vals = [], [], []
    for pid_raw, pk_raw, values in chunks:
        pids.append(pid_enc.encode(pid_raw))
        if partition_vocab is not None:
            pks.append(
                columnar.encode_with_vocab(columnar._as_key_array(pk_raw),
                                           partition_vocab))
        else:
            pks.append(pk_enc.encode(pk_raw))
        values = np.asarray(values, dtype=np.float64)
        bad = columnar.nonfinite_value_rows(values, nonfinite)
        if bad is not None:
            pks[-1] = np.where(bad, np.int32(-1), pks[-1]).astype(np.int32)
            mask = bad if values.ndim == 1 else bad[:, None]
            values = np.where(mask, 0.0, values)
        vals.append(values)
    empty = np.zeros(0, np.int32)
    return ShardEncoding(
        pid=np.concatenate(pids) if pids else empty,
        pk=np.concatenate(pks) if pks else empty,
        values=(np.concatenate(vals) if vals else np.zeros(0)),
        pid_vocab=np.asarray(pid_enc.vocabulary),
        pk_vocab=(None if partition_vocab is not None else np.asarray(
            pk_enc.vocabulary)))


def merge_host_vocabularies(
        vocabs: Sequence[Sequence[Any]]
) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Merges per-host vocabularies into one global first-occurrence
    vocabulary (host order = stream order).

    The merge primitive is the incremental encoder itself: feeding host
    h's vocabulary (in local code order) as one "chunk" returns the
    global code of each local code — i.e. the remap vector
    ``global_code = remap[local_code]``.

    Returns (global_vocabulary, [remap_int32 per host]).
    """
    enc = ChunkedVocabEncoder()
    remaps = []
    for vocab in vocabs:
        vocab = columnar._as_key_array(vocab)
        remaps.append(
            enc.encode(vocab) if len(vocab) else np.zeros(0, np.int32))
    return np.asarray(enc.vocabulary), remaps


def merge_shards(shards: Sequence[ShardEncoding],
                 public_partitions: Optional[Sequence[Any]] = None
                 ) -> columnar.EncodedData:
    """Coordinator step: merge per-host shard encodings into one
    device-resident EncodedData.

    Row columns are remapped with each host's O(local uniques) remap
    vector and uploaded shard-by-shard (each shard's device copy overlaps
    the next shard's remap, as in stream_encode_columns). In a real
    multi-host deployment the remap vectors travel to the hosts instead
    of the rows travelling here — see the module docstring's DCN note;
    this single-process form is the semantics (and the dryrun target) of
    that deployment.
    """
    import jax.numpy as jnp

    from pipelinedp_tpu import executor

    value_dtype = np.dtype(executor._ftype())
    pid_vocab, pid_remaps = merge_host_vocabularies(
        [s.pid_vocab for s in shards])
    public = public_partitions is not None
    if public:
        for s in shards:
            if s.pk_vocab is not None:
                raise ValueError(
                    "shard was encoded without public partitions but "
                    "merge_shards was called with them — the shard's pk "
                    "codes index its private vocabulary, not the public "
                    "one")
        partition_vocab = list(dict.fromkeys(public_partitions))
        pk_remaps = None
    else:
        for s in shards:
            if s.pk_vocab is None:
                raise ValueError(
                    "shard was encoded with public partitions but "
                    "merge_shards was called without them")
        partition_vocab, pk_remaps = merge_host_vocabularies(
            [s.pk_vocab for s in shards])
    dev_pid, dev_pk, dev_vals = [], [], []
    for h, s in enumerate(shards):
        dev_pid.append(jnp.asarray(pid_remaps[h][s.pid]))
        dev_pk.append(
            jnp.asarray(s.pk if public else pk_remaps[h][s.pk]))
        dev_vals.append(jnp.asarray(s.values.astype(value_dtype)))
    if not dev_pid:
        empty = jnp.zeros(0, jnp.int32)
        dev_pid, dev_pk = [empty], [empty]
        dev_vals = [jnp.zeros(0, value_dtype)]
    return columnar.EncodedData(
        pid=jnp.concatenate(dev_pid),
        pk=jnp.concatenate(dev_pk),
        values=jnp.concatenate(dev_vals),
        partition_vocab=partition_vocab,
        n_privacy_ids=len(pid_vocab),
        public_encoded=public)


# --- Multi-controller (pod) ingest ----------------------------------------
#
# The live form of the design above: under jax.distributed, EACH process
# runs encode_shard over its own chunk iterator (host-local parse +
# factorize, no device work, no cross-host rows), the per-process
# vocabularies — O(uniques), not O(rows) — are exchanged once over the
# collective fabric, every process derives the identical global
# vocabulary + remap vectors (merge_host_vocabularies is deterministic in
# process order), and each process uploads ONLY its remapped shard to its
# local devices, assembled into one global mesh-sharded array
# (jax.make_array_from_process_local_data). The only DCN traffic before
# the driver's all_to_all is the vocabulary exchange.


@dataclasses.dataclass
class _ShardMeta:
    """The per-process facts the vocabulary exchange moves: local vocabs
    (pure numpy, picklable) + the process's row count."""
    n_rows: int
    pid_vocab: np.ndarray
    pk_vocab: Optional[np.ndarray]


def _collective_allgather_bytes(payload: bytes) -> List[bytes]:
    """All-gathers one bytes payload per process (process order), via two
    device collectives: a length gather fixes the pad, then the padded
    uint8 payloads gather. O(vocabulary) bytes — never rows."""
    import jax
    import numpy as np_  # local alias: keep module-level np for rows
    from jax.experimental import multihost_utils

    length = np_.asarray([len(payload)], np_.int64)
    lengths = np_.asarray(
        multihost_utils.process_allgather(length)).reshape(-1)
    cap = int(lengths.max()) if len(lengths) else 0
    padded = np_.zeros(max(cap, 1), np_.uint8)
    padded[:len(payload)] = np_.frombuffer(payload, np_.uint8)
    gathered = np_.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(int(jax.process_count()), -1)
    return [gathered[p, :int(lengths[p])].tobytes()
            for p in range(gathered.shape[0])]


def merge_shard_metas(metas: Sequence[_ShardMeta],
                      public: bool
                      ) -> Tuple[List[np.ndarray],
                                 Optional[List[np.ndarray]],
                                 np.ndarray, Sequence[Any]]:
    """Deterministic global merge every process runs identically:
    (pid remaps, pk remaps or None, global pid vocab, partition vocab)."""
    pid_vocab, pid_remaps = merge_host_vocabularies(
        [m.pid_vocab for m in metas])
    if public:
        return pid_remaps, None, pid_vocab, []
    pk_vocab, pk_remaps = merge_host_vocabularies(
        [m.pk_vocab for m in metas])
    return pid_remaps, pk_remaps, pid_vocab, pk_vocab


def _padded_local_rows(shard: ShardEncoding, pid_remap: np.ndarray,
                       pk_remap: Optional[np.ndarray], cap: int,
                       value_dtype) -> Tuple[np.ndarray, np.ndarray,
                                             np.ndarray]:
    """One process's remapped rows padded to its device capacity with the
    standard invalid marks (pid 0, pk -1 -> EncodedData.valid False)."""
    pid = (pid_remap[shard.pid] if len(shard.pid) else
           shard.pid).astype(np.int32)
    pk = shard.pk if pk_remap is None else (
        pk_remap[shard.pk] if len(shard.pk) else shard.pk)
    pk = np.asarray(pk, np.int32)
    values = np.asarray(shard.values, dtype=value_dtype)
    n = len(pid)
    pad = cap - n
    if pad:
        pid = np.concatenate([pid, np.zeros(pad, np.int32)])
        pk = np.concatenate([pk, np.full(pad, -1, np.int32)])
        values = np.concatenate(
            [values,
             np.zeros((pad,) + values.shape[1:], values.dtype)])
    return pid, pk, values


def _pod_row_capacity(n_rows_by_process, mesh) -> Tuple[int, bool]:
    """One shared per-device row capacity every pod process derives
    identically (from the exchanged row counts and the mesh alone): the
    largest per-device row load across processes, capacity-rounded so
    repeated pods of similar size reuse compiled shapes. Returns
    (per_device_capacity, simulated) — `simulated` marks the injected-
    exchange single-process simulation of a pod."""
    from pipelinedp_tpu.parallel import mesh as mesh_lib
    from pipelinedp_tpu.parallel.mesh import device_process, round_capacity

    n_dev = int(mesh.devices.size)
    devs_of = collections_counter(
        device_process(d) for d in mesh.devices.flat)
    simulated = (mesh_lib.process_count() == 1 and
                 len(n_rows_by_process) > 1)
    per_dev = 1
    for p, n_rows in enumerate(n_rows_by_process):
        if simulated:
            # Injected-exchange simulation of a pod inside one process:
            # pretend an even device split across the simulated hosts.
            n_p = max(n_dev // len(n_rows_by_process), 1)
        else:
            n_p = devs_of.get(p, 0)
        if n_rows and not n_p:
            raise ValueError(
                f"process {p} encoded {n_rows} rows but owns no device "
                f"of the mesh — every ingesting process must hold a mesh "
                f"slice to upload to")
        if n_p:
            per_dev = max(per_dev, -(-n_rows // n_p))
    return round_capacity(per_dev), simulated


def encode_local_shard_to_mesh(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        mesh,
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error",
        exchange=None,
        encode_mode: str = "host") -> columnar.EncodedData:
    """Pod-scale ingest: this process encodes ONLY its own input shard.

    Runs encode_shard over `chunks` (host-local), exchanges the
    per-process vocabularies + row counts (`exchange(payload_bytes) ->
    [payload_bytes per process]`, default the collective all-gather —
    injectable so single-process tests can simulate a pod), merges them
    into the global vocabulary every process derives identically, remaps
    the local rows, and uploads them as this process's slice of one
    global mesh-sharded array set (jax.make_array_from_process_local_data
    over `mesh`'s row sharding). Per-process rows pad to a common
    per-device capacity (pk -1 -> EncodedData.valid False), so the global
    layout is an even leading-axis split the meshed drivers consume
    without any further eager cross-process reshaping.

    Rows never cross hosts here: the collective reshard inside the driver
    (hash(pid) mod D over the SAME global vocabulary codes) is what
    co-locates each privacy id, exactly as in the single-process path.
    Process order = stream order, so the merged codes equal a serial
    stream_encode_columns over the concatenated stream (proven in
    tests/test_multihost.py).

    encode_mode="hash_device" replaces the pickled host-vocabulary merge
    with the device collective factorize: each process only HASHES its
    shard, the compacted per-shard hash uniques cross the mesh in one
    ``lax.all_gather`` (device_encode.mesh_factorize_codes), and every
    process derives identical global first-occurrence codes on device.
    The byte exchange then carries only the O(uniques) collision /
    decode metadata — no vocabulary remap work rides it.
    """
    import pickle

    import jax
    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.parallel import mesh as mesh_lib
    from pipelinedp_tpu.runtime import trace as rt_trace

    if encode_mode not in ("host", "hash_device"):
        raise ValueError(f"encode_mode must be host|hash_device, "
                         f"got {encode_mode!r}")
    if encode_mode == "hash_device":
        return _encode_local_shard_hash(chunks, mesh, public_partitions,
                                        nonfinite, exchange)
    value_dtype = np.dtype(executor._ftype())
    public = public_partitions is not None
    with rt_trace.span("ingest.local_shard") as sp:
        shard = encode_shard(chunks, public_partitions, nonfinite)
        sp.set(rows=int(len(shard.pid)))
    meta = _ShardMeta(n_rows=int(len(shard.pid)),
                      pid_vocab=np.asarray(shard.pid_vocab),
                      pk_vocab=(None if shard.pk_vocab is None else
                                np.asarray(shard.pk_vocab)))
    if exchange is None:
        if mesh_lib.process_count() == 1:
            exchange = lambda payload: [payload]  # noqa: E731 - trivial single-process identity
        else:
            exchange = _collective_allgather_bytes
    with rt_trace.span("ingest.vocab_exchange") as sp:
        payload = pickle.dumps(meta)
        sp.set(bytes=len(payload))
        metas = [pickle.loads(p) for p in exchange(payload)]
    my_p = mesh_lib.process_index()
    if not 0 <= my_p < len(metas):
        raise ValueError(
            f"vocabulary exchange returned {len(metas)} shard metas but "
            f"this is process {my_p} — every pod process must "
            f"participate exactly once")
    pid_remaps, pk_remaps, pid_vocab, pk_vocab = merge_shard_metas(
        metas, public)
    if public:
        partition_vocab = list(dict.fromkeys(public_partitions))
    else:
        partition_vocab = pk_vocab
    n_local_dev = max(len(mesh_lib.local_devices(mesh)), 1)
    n_dev = int(mesh.devices.size)
    # One shared per-device capacity (every process must agree on the
    # global shape, so it is derived purely from the exchanged metas and
    # the mesh).
    cap, _ = _pod_row_capacity([m.n_rows for m in metas], mesh)
    local_rows = cap * n_local_dev
    pid, pk, values = _padded_local_rows(
        shard, pid_remaps[my_p],
        None if pk_remaps is None else pk_remaps[my_p], local_rows,
        value_dtype)
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec(mesh_lib.SHARD_AXIS))
    global_rows = cap * n_dev

    def to_global(col):
        if mesh_lib.process_count() == 1:
            return jax.device_put(jnp.asarray(col), sharding)
        return jax.make_array_from_process_local_data(
            sharding, col, (global_rows,) + col.shape[1:])

    return columnar.EncodedData(
        pid=to_global(pid),
        pk=to_global(pk),
        values=to_global(values),
        partition_vocab=partition_vocab,
        n_privacy_ids=len(pid_vocab),
        public_encoded=public)


# --- Multi-controller hash-device ingest -----------------------------------


@dataclasses.dataclass
class _HashShardMeta:
    """The per-process facts the hash-mode byte exchange moves: the row
    count (for the shared capacity) plus O(uniques) hash metadata —
    collision lanes for both key columns, and the partition uniques'
    first-occurrence positions + raw keys from which every process
    derives the identical decode table. NO vocabulary remap work rides
    this exchange; codes are assigned by the device collective."""
    n_rows: int
    pid_u1: np.ndarray
    pid_u2: np.ndarray
    pk_u1: Optional[np.ndarray]
    pk_u2: Optional[np.ndarray]
    pk_keys: Optional[np.ndarray]
    pk_pos: Optional[np.ndarray]  # shard-local first positions


@dataclasses.dataclass
class _HashShardEncoding:
    """One process's hash-encoded shard: (n, 3) uint32 hash-row columns
    (or int32 pk codes when publicly encoded) + its exchange meta."""
    pid_hash: np.ndarray
    pk_col: np.ndarray
    values: np.ndarray
    meta: _HashShardMeta


def _hash_encode_shard(chunks, public_partitions,
                       nonfinite: str) -> _HashShardEncoding:
    """Host-local hash encode of one input shard (no device work): the
    hash-mode counterpart of encode_shard — chunk hashing only, chunk
    uniques collected with shard-local first positions, no merge."""
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
    from pipelinedp_tpu import device_encode, executor
    value_dtype = np.dtype(executor._ftype())
    pid_cols, pk_cols, vals = [], [], []
    pid_u1, pid_u2 = [], []
    pk_u1, pk_u2, pk_keys, pk_pos = [], [], [], []
    offset = 0
    for chunk in chunks:
        pid_raw, pk_raw, values = chunk
        pid_raw = columnar._as_key_array(pid_raw)
        h1, h2 = hash_key_column_pair(pid_raw)
        u1, u2, _, _ = _hash_uniques(h1, h2, None)
        pid_u1.append(u1)
        pid_u2.append(u2)
        pk_valid = None
        if partition_vocab is not None:
            pk_col = columnar.encode_with_vocab(
                columnar._as_key_array(pk_raw), partition_vocab)
        else:
            pk_raw = columnar._as_key_array(pk_raw)
            k1, k2 = hash_key_column_pair(pk_raw)
            ku1, ku2, keys, first = _hash_uniques(k1, k2, pk_raw)
            pk_u1.append(ku1)
            pk_u2.append(ku2)
            pk_keys.append(keys)
            pk_pos.append(first + offset)
        values = np.asarray(values, dtype=value_dtype)
        bad = columnar.nonfinite_value_rows(values, nonfinite)
        if bad is not None:
            if partition_vocab is not None:
                pk_col = np.where(bad, np.int32(-1),
                                  pk_col).astype(np.int32)
            else:
                pk_valid = ~bad
            mask = bad if values.ndim == 1 else bad[:, None]
            values = np.where(mask, 0.0, values).astype(value_dtype)
        if partition_vocab is None:
            pk_col = device_encode.pack_hash_rows(k1, pk_valid)
        pid_cols.append(device_encode.pack_hash_rows(h1))
        pk_cols.append(pk_col)
        vals.append(values)
        offset += len(pid_raw)
    public = partition_vocab is not None
    empty_hash = np.empty((0, 3), np.uint32)
    pid_hash = np.concatenate(pid_cols) if pid_cols else empty_hash
    if pk_cols:
        pk_col = np.concatenate(pk_cols)
    else:
        pk_col = np.empty(0, np.int32) if public else empty_hash
    values = np.concatenate(vals) if vals else np.zeros(0, value_dtype)
    meta = _HashShardMeta(
        n_rows=int(len(pid_hash)),
        pid_u1=_concat_u64(pid_u1), pid_u2=_concat_u64(pid_u2),
        pk_u1=None if public else _concat_u64(pk_u1),
        pk_u2=None if public else _concat_u64(pk_u2),
        pk_keys=None if public else (np.concatenate(pk_keys)
                                     if pk_keys else np.empty(0, object)),
        pk_pos=None if public else (np.concatenate(pk_pos)
                                    if pk_pos else np.empty(0, np.int64)))
    return _HashShardEncoding(pid_hash, pk_col, values, meta)


def _concat_u64(arrays) -> np.ndarray:
    arrays = [a for a in arrays if len(a)]
    return np.concatenate(arrays) if arrays else np.empty(0, np.uint64)


def _pad_rows_to(col: np.ndarray, cap: int, fill, dtype) -> np.ndarray:
    out = np.full((cap,) + col.shape[1:], fill, dtype)
    out[:len(col)] = col
    return out


def _encode_local_shard_hash(chunks, mesh, public_partitions, nonfinite,
                             exchange) -> columnar.EncodedData:
    """The encode_mode="hash_device" body of encode_local_shard_to_mesh.

    This process hashes ONLY its own shard (no vocabulary work at all),
    the byte exchange moves O(uniques) collision/decode metadata, the
    padded (n, 3) hash rows upload as this process's slice of the global
    mesh-sharded array, and the dense first-occurrence codes come out of
    the device collective factorize (device_encode.mesh_factorize_codes:
    one all_gather of compacted per-shard uniques + a replicated merge
    every shard computes identically). A detected hash collision is
    derived identically by every process from the same exchanged metas,
    so all processes fall back to the host encoder together.
    """
    import pickle

    import jax
    import jax.numpy as jnp

    from pipelinedp_tpu import device_encode, executor
    from pipelinedp_tpu.parallel import mesh as mesh_lib
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime import trace as rt_trace

    value_dtype = np.dtype(executor._ftype())
    public = public_partitions is not None
    reiterable = iter(chunks) is not chunks
    with rt_trace.span("ingest.local_shard", encode="hash_device") as sp:
        shard = _hash_encode_shard(chunks, public_partitions, nonfinite)
        sp.set(rows=shard.meta.n_rows)
        rt_telemetry.record("pipeline_device_encode_chunks")
    if exchange is None:
        if mesh_lib.process_count() == 1:
            exchange = lambda payload: [payload]  # noqa: E731 - trivial single-process identity
        else:
            exchange = _collective_allgather_bytes
    with rt_trace.span("ingest.vocab_exchange", encode="hash_device") as sp:
        payload = pickle.dumps(shard.meta)
        sp.set(bytes=len(payload))
        metas = [pickle.loads(p) for p in exchange(payload)]
    my_p = mesh_lib.process_index()
    if not 0 <= my_p < len(metas):
        raise ValueError(
            f"vocabulary exchange returned {len(metas)} shard metas but "
            f"this is process {my_p} — every pod process must "
            f"participate exactly once")
    # Global collision gate — identical on every process (same metas),
    # so the fallback decision can never diverge across the pod.
    try:
        _, _, n_pid_global, _ = device_encode.merge_hash_uniques(
            [m.pid_u1 for m in metas], [m.pid_u2 for m in metas],
            what="privacy-id")
        pk_table = None
        if not public:
            # Positions become global by offsetting each process's
            # shard-local first positions with its stream offset.
            offsets = np.cumsum([0] + [m.n_rows for m in metas[:-1]])
            pk_table = device_encode.merge_hash_uniques(
                [m.pk_u1 for m in metas], [m.pk_u2 for m in metas],
                [m.pk_keys for m in metas],
                [m.pk_pos + off for m, off in zip(metas, offsets)],
                what="partition")
    except device_encode.HashCollisionError as err:
        rt_telemetry.record("ingest_hash_collisions")
        logging.warning(
            "hash-device pod ingest detected a 64-bit key-hash "
            "collision (%s); every process falls back to the exact "
            "host encoder together.", err)
        if not reiterable:
            raise device_encode.HashCollisionError(
                f"{err} — and the chunk source is a one-shot iterator, "
                f"so the exact host-encoder fallback cannot re-read it. "
                f"Pass a re-iterable source or encode_mode='host'."
            ) from err
        return encode_local_shard_to_mesh(
            chunks, mesh, public_partitions=public_partitions,
            nonfinite=nonfinite, exchange=exchange, encode_mode="host")
    n_local_dev = max(len(mesh_lib.local_devices(mesh)), 1)
    n_dev = int(mesh.devices.size)
    cap, simulated = _pod_row_capacity([m.n_rows for m in metas], mesh)
    local_rows = cap * n_local_dev
    global_rows = cap * n_dev
    sent32 = int(device_encode._U32_MAX)
    pid_local = _pad_rows_to(shard.pid_hash, local_rows, sent32,
                             np.uint32)
    if public:
        pk_local = _pad_rows_to(shard.pk_col, local_rows, -1, np.int32)
    else:
        pk_local = _pad_rows_to(shard.pk_col, local_rows, sent32,
                                np.uint32)
    values_local = _pad_rows_to(shard.values, local_rows, 0, value_dtype)
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec(mesh_lib.SHARD_AXIS))

    def to_global(col):
        if mesh_lib.process_count() == 1:
            return jax.device_put(jnp.asarray(col), sharding)
        return jax.make_array_from_process_local_data(
            sharding, col, (global_rows,) + col.shape[1:])

    pid_codes, n_pid_dev = device_encode.mesh_factorize_codes(
        mesh, to_global(pid_local))
    if public:
        pk = to_global(pk_local)
        vocab = list(dict.fromkeys(public_partitions))
    else:
        pk, n_pk_dev = device_encode.mesh_factorize_codes(
            mesh, to_global(pk_local))
        if not simulated and n_pk_dev != pk_table[2]:
            raise RuntimeError(
                f"device collective factorize found {n_pk_dev} distinct "
                f"partition hashes but the exchanged metas merge to "
                f"{pk_table[2]} (internal invariant)")
        # Code order (global first occurrence) is host-derivable from
        # the exchanged positions, so the decode table covers codes
        # whose rows live on other hosts too.
        s1, keys, n_pk, pos = pk_table
        code_hashes = s1[np.argsort(pos, kind="stable")]
        vocab = device_encode.HashVocab(n_pk, s1, keys,
                                        hash_by_code_host=code_hashes)
    pid = jnp.maximum(pid_codes, 0)
    return columnar.EncodedData(
        pid=pid,
        pk=pk,
        values=to_global(values_local),
        partition_vocab=vocab,
        n_privacy_ids=int(n_pid_global),
        public_encoded=public)
