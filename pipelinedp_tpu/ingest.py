"""Chunked, overlapped host->device ingest.

The reference delegates unbounded input to Beam/Spark IO
(pipeline_dp/pipeline_backend.py:223-374); the TPU build's equivalent is a
streaming host pipeline: parse -> factorize -> upload proceeds chunk by
chunk, and because device copies dispatch asynchronously, the upload of
chunk i overlaps the host parse/factorization of chunk i+1. On the 1-core
bench host that overlap — not host parallelism — is what moves end-to-end
time toward max(host encode, device transfer) instead of their sum.

The result is a device-resident EncodedData whose columns are jax arrays;
the executor pads it on device (executor.pad_rows) and the engine accepts
it directly in place of a row collection (columnar.encode passthrough), so

    encoded = ingest.stream_encode_columns(chunk_iter)
    result = engine.aggregate(encoded, params, extractors)

is the bulk-file counterpart of handing the engine Python rows.

Contribution bounding is global per privacy id, so the fused kernel still
runs over the full device-resident dataset — streaming here bounds HOST
memory and overlaps transfer, not device memory (the blocked large-P path
owns that axis).
"""

from typing import Any, Iterable, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import columnar

try:
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is in the standard image
    _pd = None


class ChunkedVocabEncoder:
    """Incremental first-occurrence vocabulary encoding across chunks.

    Feeding chunks in order yields exactly the codes columnar.factorize
    would assign to the concatenation: per-chunk factorization (C speed)
    followed by a remap of the chunk's uniques against the growing global
    vocabulary — O(chunk + new uniques) per chunk, never O(total).
    """

    def __init__(self):
        self._index = None  # pandas Index (fast path)
        self._dict: Optional[dict] = None  # fallback vocab

    def encode(self, raw) -> np.ndarray:
        raw = columnar._as_key_array(np.asarray(raw))
        if _pd is not None:
            codes, uniques = _pd.factorize(raw, use_na_sentinel=False)
            uniques = _pd.Index(uniques)
            if self._index is None:
                self._index = uniques
                return codes.astype(np.int32)
            mapped = self._index.get_indexer(uniques)
            is_new = mapped == -1
            if is_new.any():
                mapped[is_new] = len(self._index) + np.arange(
                    int(is_new.sum()))
                self._index = self._index.append(uniques[is_new])
            return mapped.astype(np.int32)[codes]
        # No pandas: chunk-local factorize + dict remap of uniques.
        codes, uniques = columnar.factorize(raw)
        if self._dict is None:
            self._dict = {}
        remap = np.empty(len(uniques), np.int32)
        for j, key in enumerate(uniques):
            remap[j] = self._dict.setdefault(key, len(self._dict))
        return remap[codes]

    @property
    def vocabulary(self) -> Sequence[Any]:
        if self._index is not None:
            return np.asarray(self._index)
        return np.fromiter(self._dict or (), dtype=object,
                           count=len(self._dict or ()))

    def __len__(self) -> int:
        if self._index is not None:
            return len(self._index)
        return len(self._dict or ())


def stream_encode_columns(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None
) -> columnar.EncodedData:
    """Encodes and uploads (pid_raw, pk_raw, values) column chunks,
    overlapping each chunk's device copy with the next chunk's parsing.

    Returns a device-resident EncodedData (jax-array columns, float32
    values — the kernel compute dtype, at half the f64 upload volume).
    """
    import jax.numpy as jnp

    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
    dev_pid, dev_pk, dev_vals = [], [], []
    for pid_raw, pk_raw, values in chunks:
        pid = pid_enc.encode(pid_raw)
        if partition_vocab is not None:
            pk = columnar.encode_with_vocab(
                columnar._as_key_array(np.asarray(pk_raw)), partition_vocab)
        else:
            pk = pk_enc.encode(pk_raw)
        # jnp.asarray dispatches the host->device copy asynchronously; the
        # loop continues into the next chunk's parse while it lands.
        dev_pid.append(jnp.asarray(pid))
        dev_pk.append(jnp.asarray(pk))
        dev_vals.append(
            jnp.asarray(np.asarray(values, dtype=np.float32)))
    if not dev_pid:
        empty = jnp.zeros(0, jnp.int32)
        dev_pid, dev_pk = [empty], [empty]
        dev_vals = [jnp.zeros(0, jnp.float32)]
    return columnar.EncodedData(
        pid=jnp.concatenate(dev_pid),
        pk=jnp.concatenate(dev_pk),
        values=jnp.concatenate(dev_vals),
        partition_vocab=(partition_vocab if partition_vocab is not None else
                         pk_enc.vocabulary),
        n_privacy_ids=len(pid_enc),
        public_encoded=public_partitions is not None)
