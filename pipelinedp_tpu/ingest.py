"""Chunked, overlapped host->device ingest.

The reference delegates unbounded input to Beam/Spark IO
(pipeline_dp/pipeline_backend.py:223-374); the TPU build's equivalent is a
streaming host pipeline: parse -> factorize -> upload proceeds chunk by
chunk, and because device copies dispatch asynchronously, the upload of
chunk i overlaps the host parse/factorization of chunk i+1. On the 1-core
bench host that overlap — not host parallelism — is what moves end-to-end
time toward max(host encode, device transfer) instead of their sum.

With encode_threads >= 1 the same entry point routes through the
device-resident streaming executor (runtime/pipeline.py): the heavy,
order-independent half of vocabulary encoding (chunk_factorize) runs per
chunk on a host thread pool feeding a bounded staging queue, the cheap
sequential half (ChunkedVocabEncoder.merge) stitches the global
vocabulary in stream order on the consumer, and rows accumulate into
persistent, buffer-donated device buffers (DeviceRowAccumulator) sized
to the executor.pad_rows power-of-two buckets — so the pipelined
encoding is bit-identical to the serial one, down to the padded kernel
input arrays.

The result is a device-resident EncodedData whose columns are jax arrays;
the executor pads it on device (executor.pad_rows) and the engine accepts
it directly in place of a row collection (columnar.encode passthrough), so

    encoded = ingest.stream_encode_columns(chunk_iter)
    result = engine.aggregate(encoded, params, extractors)

is the bulk-file counterpart of handing the engine Python rows.

Contribution bounding is global per privacy id, so the fused kernel still
runs over the full device-resident dataset — streaming here bounds HOST
memory and overlaps transfer, not device memory (the blocked large-P path
owns that axis).
"""

import dataclasses
from collections import Counter as collections_counter
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import columnar

try:
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is in the standard image
    _pd = None

# Shared NaN canonicalization (columnar.factorize's dict fallback uses the
# same sentinel, so spilled state and chunk factorization agree).
_NAN_KEY = columnar._NAN_KEY
_dict_key = columnar._canonical_key


def _kind_group(dtype) -> str:
    """Coarse dtype family for the sorted-vocab compatibility check."""
    if dtype.kind in "biuf":
        return "num"
    if dtype.kind in "SU":
        return "str"
    return "obj"


def chunk_factorize(raw) -> Tuple[np.ndarray, np.ndarray]:
    """Chunk-local factorization: (int32 codes, uniques in
    first-occurrence order).

    The order-independent, C-speed half of ChunkedVocabEncoder.encode —
    pure and thread-safe, so the streaming executor
    (runtime/pipeline.py) can run it per chunk on the host thread pool
    while the cheap sequential half (``ChunkedVocabEncoder.merge``)
    stitches the global vocabulary in stream order on the consumer.
    """
    raw = columnar._as_key_array(raw)
    if _pd is not None:
        codes, uniques = _pd.factorize(raw, use_na_sentinel=False)
        return codes.astype(np.int32), np.asarray(uniques)
    codes, uniques = columnar.factorize(raw)
    uniques = np.asarray(uniques)
    # Normalize the chunk's uniques to first-occurrence order
    # (factorize's np.unique branch yields sorted order) so new global
    # codes are assigned exactly as one factorize over the concatenation
    # would.
    if len(uniques) > 1:
        _, first_idx = np.unique(codes, return_index=True)
        perm = np.argsort(first_idx)
        if not np.array_equal(perm, np.arange(len(perm))):
            inv = np.empty_like(perm)
            inv[perm] = np.arange(len(perm))
            codes = inv[codes].astype(np.int32)
            uniques = uniques[perm]
    return codes.astype(np.int32), uniques


class ChunkedVocabEncoder:
    """Incremental first-occurrence vocabulary encoding across chunks.

    Feeding chunks in order yields exactly the codes columnar.factorize
    would assign to the concatenation — on the pandas path and on the
    vectorized numpy fallback, including NaN unification (all NaN keys
    share one code, kept out of the sorted vocabulary where comparisons
    would mis-place it) and cross-chunk dtype promotion (a later chunk
    with a wider string / finer numeric dtype widens the stored
    vocabulary instead of truncating new keys). Per chunk: factorization
    (C speed) followed by a vectorized remap of the chunk's uniques
    against a sorted copy of the vocabulary (searchsorted + insert,
    O(V + new·log new)). Only key types numpy cannot order fall back to
    a per-unique dict loop, which canonicalizes NaN through the same
    shared sentinel columnar.factorize's last-resort branch uses.
    """

    def __init__(self):
        self._index = None  # pandas Index (fast path)
        self._sorted_vocab = None  # numpy fallback: sorted non-NaN uniques
        self._sorted_codes = None  # global code of each sorted entry
        self._nan_code: Optional[int] = None  # shared code for NaN keys
        self._next_code = 0  # total codes assigned on the numpy fallback
        self._dict: Optional[dict] = None  # unorderable-key last resort

    def encode(self, raw) -> np.ndarray:
        # _as_key_array inside chunk_factorize: np.asarray first would
        # explode composite (tuple) keys into a 2-D array instead of
        # object elements.
        return self.merge(*chunk_factorize(raw))

    def merge(self, codes: np.ndarray, uniques: np.ndarray) -> np.ndarray:
        """Sequential half of encode(): remaps one chunk's local codes
        (with uniques in first-occurrence order, from chunk_factorize)
        into the global vocabulary. Feeding chunks in stream order keeps
        the global codes identical to a single factorize over the
        concatenation — the pipelined encode calls this on the consumer
        while workers factorize chunks ahead."""
        if _pd is not None:
            uniques = _pd.Index(uniques)
            if self._index is None:
                self._index = uniques
                return codes.astype(np.int32)
            mapped = self._index.get_indexer(uniques)
            is_new = mapped == -1
            if is_new.any():
                mapped[is_new] = len(self._index) + np.arange(
                    int(is_new.sum()))
                self._index = self._index.append(uniques[is_new])
            return mapped.astype(np.int32)[codes]
        if self._dict is not None:
            return self._remap_dict(codes, uniques)
        try:
            return self._remap_sorted(codes, uniques)
        except TypeError:  # unorderable mixed-type keys
            self._spill_to_dict()
            return self._remap_dict(codes, uniques)

    def _remap_sorted(self, codes: np.ndarray,
                      uniques: np.ndarray) -> np.ndarray:
        """Vectorized remap of chunk uniques (first-occurrence order)
        against the sorted global vocabulary."""
        n_u = len(uniques)
        if self._sorted_vocab is None:
            self._sorted_vocab = np.empty(0, uniques.dtype)
            self._sorted_codes = np.empty(0, np.int64)
        elif len(self._sorted_vocab):
            # Mixed number/string chunks must spill to the dict path
            # (where 1.5 and '1.5' stay distinct keys, matching pandas):
            # numpy would otherwise silently STRINGIFY numbers via dtype
            # promotion instead of raising.
            a = _kind_group(self._sorted_vocab.dtype)
            b = _kind_group(uniques.dtype)
            if "obj" not in (a, b) and a != b:
                raise TypeError(
                    f"cannot mix {a} and {b} keys in the sorted vocab")
        # NaN never matches itself under searchsorted/==, so NaN keys are
        # tracked by a dedicated code and kept out of the sorted array
        # (where they would also corrupt later binary searches). Object
        # arrays get the per-element check: an all-float object chunk
        # compares without raising, so it would NOT spill to the dict path.
        if uniques.dtype.kind == "f":
            is_nan = np.isnan(uniques)
        elif uniques.dtype.kind == "O" and n_u:
            is_nan = np.fromiter(
                (_dict_key(k) is _NAN_KEY for k in uniques), bool, count=n_u)
        else:
            is_nan = np.zeros(n_u, bool)
        nan_idx = np.nonzero(is_nan)[0]
        remap = np.empty(n_u, np.int64)
        known = np.zeros(n_u, bool)
        if len(nan_idx) and self._nan_code is not None:
            known[nan_idx] = True
            remap[nan_idx] = self._nan_code
        reg_idx = np.nonzero(~is_nan)[0]
        u = uniques[reg_idx]
        n_vocab = len(self._sorted_vocab)
        if n_vocab and len(u):
            pos = np.searchsorted(self._sorted_vocab, u)  # may TypeError
            pos_c = np.minimum(pos, n_vocab - 1)
            found = (pos < n_vocab) & (self._sorted_vocab[pos_c] == u)
            known[reg_idx[found]] = True
            remap[reg_idx[found]] = self._sorted_codes[pos_c[found]]
        # New codes in first-occurrence order of the chunk (uniques are
        # already ordered that way) = the order a global factorize would
        # meet them. Duplicate NaN uniques (factorize now unifies NaN on
        # every branch, so this is defensive) alias to one representative.
        assign_new = ~known
        nan_is_new = bool(len(nan_idx)) and self._nan_code is None
        if nan_is_new:
            assign_new[nan_idx[1:]] = False
        new_idx = np.nonzero(assign_new)[0]
        remap[new_idx] = self._next_code + np.arange(len(new_idx))
        new_nan_code = None
        if nan_is_new:
            new_nan_code = int(remap[nan_idx[0]])
            remap[nan_idx] = new_nan_code
        new_reg = new_idx[~is_nan[new_idx]]
        if len(new_reg):
            new_u, new_c = uniques[new_reg], remap[new_reg]
            # Widen first: np.insert would silently cast new keys to the
            # stored dtype (truncating e.g. '<U5' into a '<U2' vocab).
            dt = np.promote_types(self._sorted_vocab.dtype,
                                  new_u.dtype)  # may TypeError
            if dt != new_u.dtype:
                new_u = new_u.astype(dt)
            no = np.argsort(new_u, kind="stable")  # may TypeError
            new_u, new_c = new_u[no], new_c[no]
            vocab = self._sorted_vocab
            if dt != vocab.dtype:
                vocab = vocab.astype(dt)
            ins = np.searchsorted(vocab, new_u)  # may TypeError
            # All TypeError-prone ops are done — commit state (a raise
            # above must leave the encoder untouched so the dict spill
            # rebuilds from a consistent vocabulary).
            self._sorted_vocab = np.insert(vocab, ins, new_u)
            self._sorted_codes = np.insert(self._sorted_codes, ins, new_c)
        self._next_code += len(new_idx)
        if nan_is_new:
            self._nan_code = new_nan_code
        return remap[codes].astype(np.int32)

    def _spill_to_dict(self) -> None:
        """Migrates the sorted-vocab state into the dict fallback when a
        chunk introduces keys numpy cannot order."""
        self._dict = {}
        if self._sorted_vocab is not None:
            for key, code in zip(self._sorted_vocab, self._sorted_codes):
                self._dict[key] = int(code)
            if self._nan_code is not None:
                self._dict[_NAN_KEY] = self._nan_code
            # Re-key by code order is unnecessary: dict lookups are by key.
            self._sorted_vocab = self._sorted_codes = None

    def _remap_dict(self, codes: np.ndarray,
                    uniques: np.ndarray) -> np.ndarray:
        remap = np.empty(len(uniques), np.int64)
        for j, key in enumerate(uniques):
            remap[j] = self._dict.setdefault(_dict_key(key),
                                             len(self._dict))
        return remap[codes].astype(np.int32)

    @property
    def vocabulary(self) -> Sequence[Any]:
        if self._index is not None:
            return np.asarray(self._index)
        if self._sorted_vocab is not None:
            dt = self._sorted_vocab.dtype
            if self._nan_code is not None:
                if dt.kind in "biu":
                    dt = np.promote_types(dt, np.float64)
                elif dt.kind != "f":
                    # A string/object vocab cannot hold a float NaN;
                    # promotion to '<U..' would store the STRING 'nan'.
                    dt = np.dtype(object)
            out = np.empty(self._next_code, dtype=dt)
            out[self._sorted_codes] = self._sorted_vocab
            if self._nan_code is not None:
                out[self._nan_code] = np.nan
            return out
        if self._dict:
            vocab = np.empty(len(self._dict), dtype=object)
            for key, code in self._dict.items():
                vocab[code] = np.nan if key is _NAN_KEY else key
            return vocab
        return np.empty(0, dtype=object)

    def __len__(self) -> int:
        if self._index is not None:
            return len(self._index)
        if self._sorted_vocab is not None:
            return self._next_code
        return len(self._dict or ())


@dataclasses.dataclass
class _PreparedChunk:
    """One chunk's thread-pool encode output: chunk-local vocab codes +
    uniques (first-occurrence order) awaiting the sequential merge."""
    pid_codes: np.ndarray
    pid_uniques: np.ndarray
    pk_codes: np.ndarray  # vocab-final when publicly encoded
    pk_uniques: Optional[np.ndarray]  # None when pk was publicly encoded
    values: np.ndarray


def _prepare_chunk(chunk, partition_vocab, nonfinite,
                   value_dtype) -> _PreparedChunk:
    """Order-independent host encode of one chunk (runs on the encode
    thread pool): factorize keys, validate values. The sequential
    vocabulary merge happens on the consumer (ChunkedVocabEncoder.merge),
    so parallel workers can never reorder code assignment."""
    pid_raw, pk_raw, values = chunk
    pid_codes, pid_uniques = chunk_factorize(pid_raw)
    if partition_vocab is not None:
        pk_codes = columnar.encode_with_vocab(
            columnar._as_key_array(pk_raw), partition_vocab)
        pk_uniques = None
    else:
        pk_codes, pk_uniques = chunk_factorize(pk_raw)
    values = np.asarray(values, dtype=value_dtype)
    bad = columnar.nonfinite_value_rows(values, nonfinite)
    if bad is not None:
        pk_codes = np.where(bad, np.int32(-1), pk_codes).astype(np.int32)
        mask = bad if values.ndim == 1 else bad[:, None]
        values = np.where(mask, 0.0, values).astype(value_dtype)
    return _PreparedChunk(pid_codes, pid_uniques, pk_codes, pk_uniques,
                          values)


def _pad_chunk_rows(pid, pk, values, cap: int):
    """Pads one chunk to `cap` rows with the executor.pad_rows pad values
    (pid 0, pk -1, values 0) for the donating device accumulator."""
    n = len(pid)
    if cap == n:
        return pid, pk, values
    pad = cap - n
    pid = np.concatenate([pid, np.zeros(pad, np.int32)])
    pk = np.concatenate([pk, np.full(pad, -1, np.int32)])
    values = np.concatenate(
        [values, np.zeros((pad,) + values.shape[1:], values.dtype)])
    return pid, pk, values


def stream_encode_columns(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error",
        encode_threads: int = 0,
        pipeline_depth: Optional[int] = None
) -> columnar.EncodedData:
    """Encodes and uploads (pid_raw, pk_raw, values) column chunks,
    overlapping each chunk's device copy with the next chunk's parsing.

    encode_threads=0 (the default) is the serial path: one loop,
    device copies overlapping the next chunk's parse only through jax's
    async dispatch. encode_threads >= 1 routes through the streaming
    executor (runtime/pipeline.py): chunk parse/factorize runs on a host
    thread pool feeding a bounded staging queue (window =
    ``pipeline_depth``, default the shared PIPELINE_DEPTH), the
    sequential vocabulary merge and device accumulation run on the
    consumer, and rows accumulate into persistent device buffers
    (power-of-two row buckets, donated across appends). Both paths
    yield bit-identical kernel inputs — the pipelined EncodedData
    arrives pre-padded to exactly the executor.pad_rows bucket.

    Non-finite VALUES are rejected per chunk (nonfinite="error", the
    default) or dropped with a warning (nonfinite="drop") — a NaN/Inf
    survives jnp.clip and would silently poison its partition's sums
    (columnar.nonfinite_value_rows).

    Returns a device-resident EncodedData (jax-array columns, values in
    the kernel compute dtype — float32 normally, at half the f64 upload
    volume; float64 when jax_enable_x64 is on, so streamed input loses no
    precision relative to the row-input path).
    """
    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.runtime import trace as rt_trace
    value_dtype = np.dtype(executor._ftype())

    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))

    def encoded_data(pid, pk, values):
        return columnar.EncodedData(
            pid=pid, pk=pk, values=values,
            partition_vocab=(partition_vocab
                             if partition_vocab is not None else
                             pk_enc.vocabulary),
            n_privacy_ids=len(pid_enc),
            public_encoded=public_partitions is not None)

    if encode_threads:
        return _stream_encode_pipelined(chunks, partition_vocab, nonfinite,
                                        value_dtype, pid_enc, pk_enc,
                                        encoded_data, encode_threads,
                                        pipeline_depth)

    dev_pid, dev_pk, dev_vals = [], [], []
    # The ingest span covers parse+factorize+upload for the whole stream;
    # its row count attribute lets trace summaries report ingest rate.
    with rt_trace.span("ingest") as ingest_span:
        n_rows = 0
        for pid_raw, pk_raw, values in chunks:
            pid = pid_enc.encode(pid_raw)
            if partition_vocab is not None:
                pk = columnar.encode_with_vocab(
                    columnar._as_key_array(pk_raw), partition_vocab)
            else:
                pk = pk_enc.encode(pk_raw)
            values = np.asarray(values, dtype=value_dtype)
            bad = columnar.nonfinite_value_rows(values, nonfinite)
            if bad is not None:
                pk = np.where(bad, np.int32(-1), pk).astype(np.int32)
                mask = bad if values.ndim == 1 else bad[:, None]
                values = np.where(mask, 0.0, values).astype(value_dtype)
            n_rows += len(pid)
            # jnp.asarray dispatches the host->device copy asynchronously;
            # the loop continues into the next chunk's parse while it
            # lands.
            dev_pid.append(jnp.asarray(pid))
            dev_pk.append(jnp.asarray(pk))
            dev_vals.append(jnp.asarray(values))
        if not dev_pid:
            empty = jnp.zeros(0, jnp.int32)
            dev_pid, dev_pk = [empty], [empty]
            dev_vals = [jnp.zeros(0, value_dtype)]
        ingest_span.set(rows=n_rows)
        return encoded_data(jnp.concatenate(dev_pid),
                            jnp.concatenate(dev_pk),
                            jnp.concatenate(dev_vals))


def _stream_encode_pipelined(chunks, partition_vocab, nonfinite,
                             value_dtype, pid_enc, pk_enc, encoded_data,
                             encode_threads: int,
                             pipeline_depth: Optional[int]
                             ) -> columnar.EncodedData:
    """The pipelined body of stream_encode_columns: thread-pool chunk
    factorization -> bounded staging queue -> sequential vocab merge ->
    device-resident bucket accumulation (runtime/pipeline.py)."""
    import functools

    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.runtime import pipeline as rt_pipeline
    from pipelinedp_tpu.runtime import trace as rt_trace

    acc = rt_pipeline.DeviceRowAccumulator()
    worker = functools.partial(_prepare_chunk,
                               partition_vocab=partition_vocab,
                               nonfinite=nonfinite,
                               value_dtype=value_dtype)
    with rt_trace.span("ingest", threads=encode_threads) as ingest_span:
        n_rows = 0
        for idx, prep in enumerate(
                rt_pipeline.map_overlapped(chunks, worker, encode_threads,
                                           pipeline_depth)):
            # Sequential merge in stream order: global codes are exactly
            # what the serial encode assigns.
            pid = pid_enc.merge(prep.pid_codes, prep.pid_uniques)
            if partition_vocab is not None:
                pk = prep.pk_codes
            else:
                pk = pk_enc.merge(prep.pk_codes, prep.pk_uniques)
            n = len(pid)
            n_rows += n
            values = prep.values
            if n == 0:
                continue
            if acc.donating:
                pid, pk, values = _pad_chunk_rows(
                    pid, pk, values, executor.row_bucket(n))
            acc.append(pid, pk, values, n, chunk=idx)
        ingest_span.set(rows=n_rows)
        bufs = acc.finalize()
        if bufs is None:
            empty = jnp.zeros(0, jnp.int32)
            return encoded_data(empty, empty, jnp.zeros(0, value_dtype))
        return encoded_data(*bufs)


# --- Multi-host ingest -----------------------------------------------------
#
# The reference scales unbounded IO by handing it to Beam/Spark workers
# (pipeline_dp/pipeline_backend.py:223-374). The TPU-native equivalent is
# host-sharded ingest: in a multi-host deployment each host process parses
# and vocab-encodes ITS contiguous shard of the input independently
# (encode_shard — pure numpy, no device), the per-host vocabularies are
# merged with one pass of the same incremental encoder
# (merge_host_vocabularies — the returned codes ARE each host's
# local->global remap), and each host remaps + uploads only its own rows
# to its local devices, so the only cross-host (DCN) traffic is the
# vocabularies and O(uniques) remap vectors — never row data. With hosts
# owning contiguous shards in stream order, the merged codes are exactly
# what a single-process factorize of the whole stream would assign.


@dataclasses.dataclass
class ShardEncoding:
    """One host's locally-encoded shard: int32 code columns + the local
    vocabularies they index. Picklable (pure numpy) so worker processes
    can ship it back to the coordinator."""
    pid: np.ndarray
    pk: np.ndarray
    values: np.ndarray
    pid_vocab: np.ndarray
    pk_vocab: Optional[np.ndarray]  # None when pk was publicly encoded


def encode_shard(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error") -> ShardEncoding:
    """Host-local chunked encoding of one input shard (no device work).

    The multi-host counterpart of stream_encode_columns' parse+factorize
    stage: runs in each ingest process over its own chunk iterator. The
    same per-chunk non-finite value policy applies (each ingest worker
    rejects/drops at its own boundary, so poisoned rows never travel).
    """
    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
    pids, pks, vals = [], [], []
    for pid_raw, pk_raw, values in chunks:
        pids.append(pid_enc.encode(pid_raw))
        if partition_vocab is not None:
            pks.append(
                columnar.encode_with_vocab(columnar._as_key_array(pk_raw),
                                           partition_vocab))
        else:
            pks.append(pk_enc.encode(pk_raw))
        values = np.asarray(values, dtype=np.float64)
        bad = columnar.nonfinite_value_rows(values, nonfinite)
        if bad is not None:
            pks[-1] = np.where(bad, np.int32(-1), pks[-1]).astype(np.int32)
            mask = bad if values.ndim == 1 else bad[:, None]
            values = np.where(mask, 0.0, values)
        vals.append(values)
    empty = np.zeros(0, np.int32)
    return ShardEncoding(
        pid=np.concatenate(pids) if pids else empty,
        pk=np.concatenate(pks) if pks else empty,
        values=(np.concatenate(vals) if vals else np.zeros(0)),
        pid_vocab=np.asarray(pid_enc.vocabulary),
        pk_vocab=(None if partition_vocab is not None else np.asarray(
            pk_enc.vocabulary)))


def merge_host_vocabularies(
        vocabs: Sequence[Sequence[Any]]
) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Merges per-host vocabularies into one global first-occurrence
    vocabulary (host order = stream order).

    The merge primitive is the incremental encoder itself: feeding host
    h's vocabulary (in local code order) as one "chunk" returns the
    global code of each local code — i.e. the remap vector
    ``global_code = remap[local_code]``.

    Returns (global_vocabulary, [remap_int32 per host]).
    """
    enc = ChunkedVocabEncoder()
    remaps = []
    for vocab in vocabs:
        vocab = columnar._as_key_array(vocab)
        remaps.append(
            enc.encode(vocab) if len(vocab) else np.zeros(0, np.int32))
    return np.asarray(enc.vocabulary), remaps


def merge_shards(shards: Sequence[ShardEncoding],
                 public_partitions: Optional[Sequence[Any]] = None
                 ) -> columnar.EncodedData:
    """Coordinator step: merge per-host shard encodings into one
    device-resident EncodedData.

    Row columns are remapped with each host's O(local uniques) remap
    vector and uploaded shard-by-shard (each shard's device copy overlaps
    the next shard's remap, as in stream_encode_columns). In a real
    multi-host deployment the remap vectors travel to the hosts instead
    of the rows travelling here — see the module docstring's DCN note;
    this single-process form is the semantics (and the dryrun target) of
    that deployment.
    """
    import jax.numpy as jnp

    from pipelinedp_tpu import executor

    value_dtype = np.dtype(executor._ftype())
    pid_vocab, pid_remaps = merge_host_vocabularies(
        [s.pid_vocab for s in shards])
    public = public_partitions is not None
    if public:
        for s in shards:
            if s.pk_vocab is not None:
                raise ValueError(
                    "shard was encoded without public partitions but "
                    "merge_shards was called with them — the shard's pk "
                    "codes index its private vocabulary, not the public "
                    "one")
        partition_vocab = list(dict.fromkeys(public_partitions))
        pk_remaps = None
    else:
        for s in shards:
            if s.pk_vocab is None:
                raise ValueError(
                    "shard was encoded with public partitions but "
                    "merge_shards was called without them")
        partition_vocab, pk_remaps = merge_host_vocabularies(
            [s.pk_vocab for s in shards])
    dev_pid, dev_pk, dev_vals = [], [], []
    for h, s in enumerate(shards):
        dev_pid.append(jnp.asarray(pid_remaps[h][s.pid]))
        dev_pk.append(
            jnp.asarray(s.pk if public else pk_remaps[h][s.pk]))
        dev_vals.append(jnp.asarray(s.values.astype(value_dtype)))
    if not dev_pid:
        empty = jnp.zeros(0, jnp.int32)
        dev_pid, dev_pk = [empty], [empty]
        dev_vals = [jnp.zeros(0, value_dtype)]
    return columnar.EncodedData(
        pid=jnp.concatenate(dev_pid),
        pk=jnp.concatenate(dev_pk),
        values=jnp.concatenate(dev_vals),
        partition_vocab=partition_vocab,
        n_privacy_ids=len(pid_vocab),
        public_encoded=public)


# --- Multi-controller (pod) ingest ----------------------------------------
#
# The live form of the design above: under jax.distributed, EACH process
# runs encode_shard over its own chunk iterator (host-local parse +
# factorize, no device work, no cross-host rows), the per-process
# vocabularies — O(uniques), not O(rows) — are exchanged once over the
# collective fabric, every process derives the identical global
# vocabulary + remap vectors (merge_host_vocabularies is deterministic in
# process order), and each process uploads ONLY its remapped shard to its
# local devices, assembled into one global mesh-sharded array
# (jax.make_array_from_process_local_data). The only DCN traffic before
# the driver's all_to_all is the vocabulary exchange.


@dataclasses.dataclass
class _ShardMeta:
    """The per-process facts the vocabulary exchange moves: local vocabs
    (pure numpy, picklable) + the process's row count."""
    n_rows: int
    pid_vocab: np.ndarray
    pk_vocab: Optional[np.ndarray]


def _collective_allgather_bytes(payload: bytes) -> List[bytes]:
    """All-gathers one bytes payload per process (process order), via two
    device collectives: a length gather fixes the pad, then the padded
    uint8 payloads gather. O(vocabulary) bytes — never rows."""
    import jax
    import numpy as np_  # local alias: keep module-level np for rows
    from jax.experimental import multihost_utils

    length = np_.asarray([len(payload)], np_.int64)
    lengths = np_.asarray(
        multihost_utils.process_allgather(length)).reshape(-1)
    cap = int(lengths.max()) if len(lengths) else 0
    padded = np_.zeros(max(cap, 1), np_.uint8)
    padded[:len(payload)] = np_.frombuffer(payload, np_.uint8)
    gathered = np_.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(int(jax.process_count()), -1)
    return [gathered[p, :int(lengths[p])].tobytes()
            for p in range(gathered.shape[0])]


def merge_shard_metas(metas: Sequence[_ShardMeta],
                      public: bool
                      ) -> Tuple[List[np.ndarray],
                                 Optional[List[np.ndarray]],
                                 np.ndarray, Sequence[Any]]:
    """Deterministic global merge every process runs identically:
    (pid remaps, pk remaps or None, global pid vocab, partition vocab)."""
    pid_vocab, pid_remaps = merge_host_vocabularies(
        [m.pid_vocab for m in metas])
    if public:
        return pid_remaps, None, pid_vocab, []
    pk_vocab, pk_remaps = merge_host_vocabularies(
        [m.pk_vocab for m in metas])
    return pid_remaps, pk_remaps, pid_vocab, pk_vocab


def _padded_local_rows(shard: ShardEncoding, pid_remap: np.ndarray,
                       pk_remap: Optional[np.ndarray], cap: int,
                       value_dtype) -> Tuple[np.ndarray, np.ndarray,
                                             np.ndarray]:
    """One process's remapped rows padded to its device capacity with the
    standard invalid marks (pid 0, pk -1 -> EncodedData.valid False)."""
    pid = (pid_remap[shard.pid] if len(shard.pid) else
           shard.pid).astype(np.int32)
    pk = shard.pk if pk_remap is None else (
        pk_remap[shard.pk] if len(shard.pk) else shard.pk)
    pk = np.asarray(pk, np.int32)
    values = np.asarray(shard.values, dtype=value_dtype)
    n = len(pid)
    pad = cap - n
    if pad:
        pid = np.concatenate([pid, np.zeros(pad, np.int32)])
        pk = np.concatenate([pk, np.full(pad, -1, np.int32)])
        values = np.concatenate(
            [values,
             np.zeros((pad,) + values.shape[1:], values.dtype)])
    return pid, pk, values


def encode_local_shard_to_mesh(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        mesh,
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error",
        exchange=None) -> columnar.EncodedData:
    """Pod-scale ingest: this process encodes ONLY its own input shard.

    Runs encode_shard over `chunks` (host-local), exchanges the
    per-process vocabularies + row counts (`exchange(payload_bytes) ->
    [payload_bytes per process]`, default the collective all-gather —
    injectable so single-process tests can simulate a pod), merges them
    into the global vocabulary every process derives identically, remaps
    the local rows, and uploads them as this process's slice of one
    global mesh-sharded array set (jax.make_array_from_process_local_data
    over `mesh`'s row sharding). Per-process rows pad to a common
    per-device capacity (pk -1 -> EncodedData.valid False), so the global
    layout is an even leading-axis split the meshed drivers consume
    without any further eager cross-process reshaping.

    Rows never cross hosts here: the collective reshard inside the driver
    (hash(pid) mod D over the SAME global vocabulary codes) is what
    co-locates each privacy id, exactly as in the single-process path.
    Process order = stream order, so the merged codes equal a serial
    stream_encode_columns over the concatenated stream (proven in
    tests/test_multihost.py).
    """
    import pickle

    import jax
    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    from pipelinedp_tpu.parallel import mesh as mesh_lib
    from pipelinedp_tpu.runtime import trace as rt_trace

    value_dtype = np.dtype(executor._ftype())
    public = public_partitions is not None
    with rt_trace.span("ingest.local_shard") as sp:
        shard = encode_shard(chunks, public_partitions, nonfinite)
        sp.set(rows=int(len(shard.pid)))
    meta = _ShardMeta(n_rows=int(len(shard.pid)),
                      pid_vocab=np.asarray(shard.pid_vocab),
                      pk_vocab=(None if shard.pk_vocab is None else
                                np.asarray(shard.pk_vocab)))
    if exchange is None:
        if mesh_lib.process_count() == 1:
            exchange = lambda payload: [payload]  # noqa: E731 - trivial single-process identity
        else:
            exchange = _collective_allgather_bytes
    with rt_trace.span("ingest.vocab_exchange") as sp:
        payload = pickle.dumps(meta)
        sp.set(bytes=len(payload))
        metas = [pickle.loads(p) for p in exchange(payload)]
    my_p = mesh_lib.process_index()
    if not 0 <= my_p < len(metas):
        raise ValueError(
            f"vocabulary exchange returned {len(metas)} shard metas but "
            f"this is process {my_p} — every pod process must "
            f"participate exactly once")
    pid_remaps, pk_remaps, pid_vocab, pk_vocab = merge_shard_metas(
        metas, public)
    if public:
        partition_vocab = list(dict.fromkeys(public_partitions))
    else:
        partition_vocab = pk_vocab
    n_local_dev = max(len(mesh_lib.local_devices(mesh)), 1)
    n_dev = int(mesh.devices.size)
    # One shared per-device capacity (every process must agree on the
    # global shape, so it is derived purely from the exchanged metas and
    # the mesh): the largest per-device row load across processes —
    # each process's rows divided by ITS device count in the mesh —
    # bucketed so repeated pods of similar size reuse compiled shapes.
    from pipelinedp_tpu.parallel.mesh import device_process, round_capacity
    devs_of = collections_counter(
        device_process(d) for d in mesh.devices.flat)
    simulated = mesh_lib.process_count() == 1 and len(metas) > 1
    per_dev = 1
    for p, m in enumerate(metas):
        if simulated:
            # Injected-exchange simulation of a pod inside one process:
            # pretend an even device split across the simulated hosts.
            n_p = max(n_dev // len(metas), 1)
        else:
            n_p = devs_of.get(p, 0)
        if m.n_rows and not n_p:
            raise ValueError(
                f"process {p} encoded {m.n_rows} rows but owns no device "
                f"of the mesh — every ingesting process must hold a mesh "
                f"slice to upload to")
        if n_p:
            per_dev = max(per_dev, -(-m.n_rows // n_p))
    cap = round_capacity(per_dev)
    local_rows = cap * n_local_dev
    pid, pk, values = _padded_local_rows(
        shard, pid_remaps[my_p],
        None if pk_remaps is None else pk_remaps[my_p], local_rows,
        value_dtype)
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec(mesh_lib.SHARD_AXIS))
    global_rows = cap * n_dev

    def to_global(col):
        if mesh_lib.process_count() == 1:
            return jax.device_put(jnp.asarray(col), sharding)
        return jax.make_array_from_process_local_data(
            sharding, col, (global_rows,) + col.shape[1:])

    return columnar.EncodedData(
        pid=to_global(pid),
        pk=to_global(pk),
        values=to_global(values),
        partition_vocab=partition_vocab,
        n_privacy_ids=len(pid_vocab),
        public_encoded=public)
