"""Chunked, overlapped host->device ingest.

The reference delegates unbounded input to Beam/Spark IO
(pipeline_dp/pipeline_backend.py:223-374); the TPU build's equivalent is a
streaming host pipeline: parse -> factorize -> upload proceeds chunk by
chunk, and because device copies dispatch asynchronously, the upload of
chunk i overlaps the host parse/factorization of chunk i+1. On the 1-core
bench host that overlap — not host parallelism — is what moves end-to-end
time toward max(host encode, device transfer) instead of their sum.

The result is a device-resident EncodedData whose columns are jax arrays;
the executor pads it on device (executor.pad_rows) and the engine accepts
it directly in place of a row collection (columnar.encode passthrough), so

    encoded = ingest.stream_encode_columns(chunk_iter)
    result = engine.aggregate(encoded, params, extractors)

is the bulk-file counterpart of handing the engine Python rows.

Contribution bounding is global per privacy id, so the fused kernel still
runs over the full device-resident dataset — streaming here bounds HOST
memory and overlaps transfer, not device memory (the blocked large-P path
owns that axis).
"""

from typing import Any, Iterable, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu import columnar

try:
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is in the standard image
    _pd = None


class ChunkedVocabEncoder:
    """Incremental first-occurrence vocabulary encoding across chunks.

    Feeding chunks in order yields exactly the codes columnar.factorize
    would assign to the concatenation, on every path: per-chunk
    factorization (C speed) followed by a remap of the chunk's uniques
    against the growing global vocabulary — O(chunk + new uniques) per
    chunk, never O(total). Without pandas the remap runs vectorized
    against a sorted copy of the vocabulary (searchsorted + insert,
    O(V + new·log new) per chunk); only key types numpy cannot order
    fall back to a per-unique dict loop.
    """

    def __init__(self):
        self._index = None  # pandas Index (fast path)
        self._sorted_vocab = None  # numpy fallback: sorted uniques
        self._sorted_codes = None  # global code of each sorted entry
        self._dict: Optional[dict] = None  # unorderable-key last resort

    def encode(self, raw) -> np.ndarray:
        # _as_key_array directly: np.asarray first would explode composite
        # (tuple) keys into a 2-D array instead of object elements.
        raw = columnar._as_key_array(raw)
        if _pd is not None:
            codes, uniques = _pd.factorize(raw, use_na_sentinel=False)
            uniques = _pd.Index(uniques)
            if self._index is None:
                self._index = uniques
                return codes.astype(np.int32)
            mapped = self._index.get_indexer(uniques)
            is_new = mapped == -1
            if is_new.any():
                mapped[is_new] = len(self._index) + np.arange(
                    int(is_new.sum()))
                self._index = self._index.append(uniques[is_new])
            return mapped.astype(np.int32)[codes]
        # No pandas: chunk-local factorize, then a vectorized remap.
        codes, uniques = columnar.factorize(raw)
        uniques = np.asarray(uniques)
        # Normalize the chunk's uniques to first-occurrence order
        # (factorize's np.unique branch yields sorted order) so new global
        # codes are assigned exactly as one factorize over the
        # concatenation would.
        if len(uniques) > 1:
            _, first_idx = np.unique(codes, return_index=True)
            perm = np.argsort(first_idx)
            if not np.array_equal(perm, np.arange(len(perm))):
                inv = np.empty_like(perm)
                inv[perm] = np.arange(len(perm))
                codes = inv[codes].astype(np.int32)
                uniques = uniques[perm]
        if self._dict is not None:
            return self._remap_dict(codes, uniques)
        try:
            return self._remap_sorted(codes, uniques)
        except TypeError:  # unorderable mixed-type keys
            self._spill_to_dict()
            return self._remap_dict(codes, uniques)

    def _remap_sorted(self, codes: np.ndarray,
                      uniques: np.ndarray) -> np.ndarray:
        """Vectorized remap of chunk uniques (first-occurrence order)
        against the sorted global vocabulary."""
        if self._sorted_vocab is None or not len(self._sorted_vocab):
            order = np.argsort(uniques, kind="stable")  # may TypeError
            self._sorted_vocab = uniques[order]
            self._sorted_codes = order.astype(np.int64)
            return codes.astype(np.int32)
        n_old = len(self._sorted_vocab)
        pos = np.searchsorted(self._sorted_vocab, uniques)
        pos_c = np.minimum(pos, n_old - 1)
        found = (pos < n_old) & (self._sorted_vocab[pos_c] == uniques)
        remap = np.empty(len(uniques), np.int64)
        remap[found] = self._sorted_codes[pos_c[found]]
        new_mask = ~found
        n_new = int(new_mask.sum())
        # uniques are in first-occurrence order, so arange over the new
        # ones IS the order a global factorize would meet them.
        remap[new_mask] = n_old + np.arange(n_new)
        if n_new:
            new_u, new_c = uniques[new_mask], remap[new_mask]
            no = np.argsort(new_u, kind="stable")
            new_u, new_c = new_u[no], new_c[no]
            ins = np.searchsorted(self._sorted_vocab, new_u)
            self._sorted_vocab = np.insert(self._sorted_vocab, ins, new_u)
            self._sorted_codes = np.insert(self._sorted_codes, ins, new_c)
        return remap[codes].astype(np.int32)

    def _spill_to_dict(self) -> None:
        """Migrates the sorted-vocab state into the dict fallback when a
        chunk introduces keys numpy cannot order."""
        self._dict = {}
        if self._sorted_vocab is not None:
            for key, code in zip(self._sorted_vocab, self._sorted_codes):
                self._dict[key] = int(code)
            # Re-key by code order is unnecessary: dict lookups are by key.
            self._sorted_vocab = self._sorted_codes = None

    def _remap_dict(self, codes: np.ndarray,
                    uniques: np.ndarray) -> np.ndarray:
        remap = np.empty(len(uniques), np.int64)
        for j, key in enumerate(uniques):
            remap[j] = self._dict.setdefault(key, len(self._dict))
        return remap[codes].astype(np.int32)

    @property
    def vocabulary(self) -> Sequence[Any]:
        if self._index is not None:
            return np.asarray(self._index)
        if self._sorted_vocab is not None:
            out = np.empty(len(self._sorted_vocab),
                           dtype=self._sorted_vocab.dtype)
            out[self._sorted_codes] = self._sorted_vocab
            return out
        if self._dict:
            vocab = np.empty(len(self._dict), dtype=object)
            for key, code in self._dict.items():
                vocab[code] = key
            return vocab
        return np.empty(0, dtype=object)

    def __len__(self) -> int:
        if self._index is not None:
            return len(self._index)
        if self._sorted_vocab is not None:
            return len(self._sorted_vocab)
        return len(self._dict or ())


def stream_encode_columns(
        chunks: Iterable[Tuple[Sequence[Any], Sequence[Any],
                               Sequence[float]]],
        public_partitions: Optional[Sequence[Any]] = None
) -> columnar.EncodedData:
    """Encodes and uploads (pid_raw, pk_raw, values) column chunks,
    overlapping each chunk's device copy with the next chunk's parsing.

    Returns a device-resident EncodedData (jax-array columns, values in
    the kernel compute dtype — float32 normally, at half the f64 upload
    volume; float64 when jax_enable_x64 is on, so streamed input loses no
    precision relative to the row-input path).
    """
    import jax.numpy as jnp

    from pipelinedp_tpu import executor
    value_dtype = np.dtype(executor._ftype())

    pid_enc = ChunkedVocabEncoder()
    pk_enc = ChunkedVocabEncoder()
    partition_vocab = None
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
    dev_pid, dev_pk, dev_vals = [], [], []
    for pid_raw, pk_raw, values in chunks:
        pid = pid_enc.encode(pid_raw)
        if partition_vocab is not None:
            pk = columnar.encode_with_vocab(
                columnar._as_key_array(pk_raw), partition_vocab)
        else:
            pk = pk_enc.encode(pk_raw)
        # jnp.asarray dispatches the host->device copy asynchronously; the
        # loop continues into the next chunk's parse while it lands.
        dev_pid.append(jnp.asarray(pid))
        dev_pk.append(jnp.asarray(pk))
        dev_vals.append(
            jnp.asarray(np.asarray(values, dtype=value_dtype)))
    if not dev_pid:
        empty = jnp.zeros(0, jnp.int32)
        dev_pid, dev_pk = [empty], [empty]
        dev_vals = [jnp.zeros(0, value_dtype)]
    return columnar.EncodedData(
        pid=jnp.concatenate(dev_pid),
        pk=jnp.concatenate(dev_pk),
        values=jnp.concatenate(dev_vals),
        partition_vocab=(partition_vocab if partition_vocab is not None else
                         pk_enc.vocabulary),
        n_privacy_ids=len(pid_enc),
        public_encoded=public_partitions is not None)
