"""Host-side columnar encoding: Python rows -> struct-of-arrays.

The device engine operates on columnar arrays:
    pid:    int32[n]  contiguous privacy-unit ids (vocab-encoded)
    pk:     int32[n]  partition ids in [0, n_partitions); -1 = dropped row
    values: float[n]  scalar contribution values
            (or float[n, d] for vector-valued aggregations, e.g. VECTOR_SUM)

The host keeps the string-key vocabularies (partition id <-> original key),
which is exactly the host/device split called for in SURVEY.md §5: the
device never sees Python objects.

Large-scale users skip this module entirely and feed integer/float arrays
straight to executor.aggregate_arrays.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from pipelinedp_tpu.data_extractors import DataExtractors


@dataclass
class EncodedData:
    """Columnar dataset + decode vocabularies."""
    pid: np.ndarray  # int32[n]
    pk: np.ndarray  # int32[n], -1 marks rows in no (public) partition
    values: np.ndarray  # float64[n] (or float64[n, d] for vector values)
    partition_vocab: List[Any]  # partition id -> original partition key
    n_privacy_ids: int

    @property
    def n_rows(self) -> int:
        return len(self.pid)

    @property
    def n_partitions(self) -> int:
        return len(self.partition_vocab)

    @property
    def valid(self) -> np.ndarray:
        return self.pk >= 0


def encode(col,
           data_extractors: DataExtractors,
           public_partitions: Optional[Sequence[Any]] = None) -> EncodedData:
    """Extracts and integer-encodes (privacy_id, partition_key, value) rows.

    With public partitions, the partition vocabulary is fixed to them and
    rows in other partitions are marked invalid (pk = -1) — the columnar
    analogue of DPEngine._drop_partitions + _add_empty_public_partitions
    (empty public partitions exist as all-zero columns).
    """
    pid_extractor = data_extractors.privacy_id_extractor or (lambda row: 0)
    pk_extractor = data_extractors.partition_extractor
    value_extractor = data_extractors.value_extractor or (lambda row: 0.0)

    pid_vocab: Dict[Any, int] = {}
    pk_vocab: Dict[Any, int] = {}
    partition_vocab: List[Any] = []
    if public_partitions is not None:
        for pk in public_partitions:
            if pk not in pk_vocab:
                pk_vocab[pk] = len(partition_vocab)
                partition_vocab.append(pk)
    public = public_partitions is not None

    pids, pks, values = [], [], []
    for row in col:
        pid_raw = pid_extractor(row)
        pk_raw = pk_extractor(row)
        pid_id = pid_vocab.setdefault(pid_raw, len(pid_vocab))
        if public:
            pk_id = pk_vocab.get(pk_raw, -1)
        else:
            pk_id = pk_vocab.setdefault(pk_raw, len(partition_vocab))
            if pk_id == len(partition_vocab):
                partition_vocab.append(pk_raw)
        pids.append(pid_id)
        pks.append(pk_id)
        values.append(value_extractor(row))

    return EncodedData(pid=np.asarray(pids, dtype=np.int32),
                       pk=np.asarray(pks, dtype=np.int32),
                       values=np.asarray(values, dtype=np.float64),
                       partition_vocab=partition_vocab,
                       n_privacy_ids=len(pid_vocab))
