"""Host-side columnar encoding: Python rows -> struct-of-arrays.

The device engine operates on columnar arrays:
    pid:    int32[n]  contiguous privacy-unit ids (vocab-encoded)
    pk:     int32[n]  partition ids in [0, n_partitions); -1 = dropped row
    values: float[n]  scalar contribution values
            (or float[n, d] for vector-valued aggregations, e.g. VECTOR_SUM)

The host keeps the string-key vocabularies (partition id <-> original key),
which is exactly the host/device split called for in SURVEY.md §5: the
device never sees Python objects.

Encoding is vectorized: extraction is one pass building object arrays, and
vocabulary assignment is hash factorization at C speed (pandas.factorize
when available, np.unique otherwise) instead of a per-row Python dict loop —
the difference between hours and seconds of host time at 10^9 rows. Callers
that already hold raw columns (e.g. file readers) should use
``encode_columns`` and skip per-row extractor calls entirely; large-scale
users can feed integer/float arrays straight to executor.aggregate_arrays.
"""

import logging
from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu.data_extractors import DataExtractors

try:
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is in the standard image
    _pd = None


@dataclass
class EncodedData:
    """Columnar dataset + decode vocabularies."""
    pid: np.ndarray  # int32[n]
    pk: np.ndarray  # int32[n], -1 marks rows in no (public) partition
    values: np.ndarray  # float64[n] (or float64[n, d] for vector values)
    # partition id -> original partition key (list or ndarray)
    partition_vocab: Sequence[Any]
    n_privacy_ids: int
    # True when pk was encoded against a FIXED public-partition vocabulary
    # (rows elsewhere already dropped): such data must be aggregated WITH
    # those public partitions, never under private selection.
    public_encoded: bool = False

    @property
    def n_rows(self) -> int:
        return len(self.pid)

    @property
    def n_partitions(self) -> int:
        return len(self.partition_vocab)

    @property
    def valid(self) -> np.ndarray:
        return self.pk >= 0


def _as_key_array(x) -> np.ndarray:
    """1-D key array; composite keys (tuples) stay single object elements."""
    if isinstance(x, np.ndarray) and x.ndim == 1:
        return x
    x = list(x)
    arr = np.fromiter(x, dtype=object, count=len(x))
    return arr


_NAN_KEY = object()  # canonical dict key for NaN (NaN != NaN breaks lookup)


def _canonical_key(key):
    """NaN keys canonicalize to one sentinel: every float('nan') object is
    distinct under ==, so a raw dict would give each its own code."""
    try:
        if key != key:  # NaN is the only self-unequal value
            return _NAN_KEY
    except Exception:  # noqa: BLE001 - exotic user __ne__ may raise anything; treat as an ordinary (non-NaN) key
        pass
    return key


def _object_array_has_nan(raw: np.ndarray) -> bool:
    return any(_canonical_key(key) is _NAN_KEY for key in raw)


def factorize(raw: np.ndarray) -> Tuple[np.ndarray, Sequence[Any]]:
    """First-occurrence-order integer encoding of a key column (C speed).

    Returns (codes int32[n], vocabulary array). None/NaN are ordinary keys
    (use_na_sentinel=False) — a None partition key forms a partition, same
    as any dict-based grouping would; all NaN keys share ONE code on every
    path. Falls back to np.unique (sorted vocabulary order — equally
    valid, ids are internal), and to a Python dict loop for key types
    neither library can handle.
    """
    if _pd is not None:
        codes, uniques = _pd.factorize(raw, use_na_sentinel=False)
        # Keep the vocabulary as an array: boxing 10^6+ uniques into a
        # Python list costs more than the factorization itself.
        return codes.astype(np.int32), np.asarray(uniques)
    # No pandas: the native open-addressing encoder handles fixed-width
    # dtypes at ~5x np.unique's sort-based speed.
    from pipelinedp_tpu import native
    if not raw.dtype.hasobject:
        encoded = native.vocab_encode(raw)
        if encoded is not None:
            codes, first_rows = encoded
            return codes, raw[first_rows]
    try:
        uniques, inverse = np.unique(raw, return_inverse=True)
        if raw.dtype.hasobject and _object_array_has_nan(uniques):
            # np.unique's sort-adjacency dedup breaks when NaN sits among
            # object keys (NaN comparisons scramble the sort, so equal
            # regular keys can land non-adjacent and get TWO codes). Any
            # NaN in raw survives into uniques (it never equals its sort
            # neighbor), so scanning the small uniques array suffices.
            raise TypeError("NaN among object keys")
        return inverse.astype(np.int32), uniques
    except TypeError:  # unorderable mixed-type keys (or object NaN)
        vocab: dict = {}
        first_keys = []
        codes = np.empty(len(raw), dtype=np.int32)
        for i, key in enumerate(raw):
            canon = _canonical_key(key)
            code = vocab.setdefault(canon, len(vocab))
            if code == len(first_keys):
                first_keys.append(key)  # original object, incl. real NaN
            codes[i] = code
        out = np.empty(len(first_keys), dtype=object)
        for j, key in enumerate(first_keys):
            out[j] = key  # per-element: composite keys stay one object
        return codes, out


def nonfinite_value_rows(values: np.ndarray,
                         policy: str = "error",
                         where: str = "values") -> Optional[np.ndarray]:
    """Validates the VALUE column against NaN/Inf at ingest.

    A NaN or Inf in the value column survives jnp.clip (clip propagates
    non-finite inputs) and silently poisons every sum, mean and variance
    its partition releases — so non-finite values must be dealt with at
    the ingest boundary, explicitly:

      * policy="error" (default): raise ValueError naming the count.
      * policy="drop": return the offending row mask (the caller marks
        those rows invalid) and log one warning with the count.

    Returns None when every value is finite (or the dtype cannot hold a
    non-finite value); otherwise the bool row mask of offending rows.
    Vector-valued rows are offending when ANY coordinate is non-finite.
    """
    if policy not in ("error", "drop"):
        raise ValueError(f"nonfinite policy must be error|drop, "
                         f"got {policy!r}")
    values = np.asarray(values)
    if values.dtype.kind not in "fc":
        return None  # integer/bool values are always finite
    finite = np.isfinite(values)
    if values.ndim > 1:
        finite = finite.all(axis=tuple(range(1, values.ndim)))
    n_bad = int(finite.size - finite.sum())
    if n_bad == 0:
        return None
    if policy == "error":
        raise ValueError(
            f"{n_bad} non-finite entr{'y' if n_bad == 1 else 'ies'} "
            f"(NaN/Inf) in the {where} column: a non-finite value survives "
            f"clipping and silently poisons its partition's aggregates. "
            f"Fix the input, or pass nonfinite='drop' to drop those rows "
            f"with a warning.")
    logging.warning(
        "dropping %d row(s) with non-finite %s (nonfinite='drop'): "
        "NaN/Inf would survive clipping and poison the affected "
        "partitions' aggregates.", n_bad, where)
    return ~finite


def encode_with_vocab(raw: np.ndarray, vocab: Sequence[Any]) -> np.ndarray:
    """Integer-encodes a key column against a FIXED vocabulary; -1 = absent."""
    if _pd is not None:
        return _pd.Index(vocab).get_indexer(raw).astype(np.int32)
    lookup = {key: i for i, key in enumerate(vocab)}
    return np.fromiter((lookup.get(k, -1) for k in raw),
                       dtype=np.int32,
                       count=len(raw))


def encode_columns(
        pid_raw: Sequence[Any],
        pk_raw: Sequence[Any],
        values: Sequence[float],
        public_partitions: Optional[Sequence[Any]] = None,
        nonfinite: str = "error") -> EncodedData:
    """Vectorized encoding of raw key/value COLUMNS (no per-row Python).

    This is the bulk-ingest entry point: file readers hand over whole
    columns (numpy arrays of keys/values) and every vocabulary assignment
    runs as one hash-factorization pass. Non-finite VALUES are rejected
    here (nonfinite="error", the default) or dropped with a warning
    (nonfinite="drop") — see nonfinite_value_rows.
    """
    pid_raw = _as_key_array(pid_raw)
    pk_raw = _as_key_array(pk_raw)
    pid, pid_vocab = factorize(pid_raw)
    if public_partitions is not None:
        partition_vocab = list(dict.fromkeys(public_partitions))
        pk = encode_with_vocab(pk_raw, partition_vocab)
    else:
        pk, partition_vocab = factorize(pk_raw)
    values = np.asarray(values, dtype=np.float64)
    bad = nonfinite_value_rows(values, nonfinite)
    if bad is not None:
        # Dropped rows are marked invalid the same way rows outside the
        # public partitions are: pk = -1 (EncodedData.valid reads pk >= 0).
        pk = np.where(bad, np.int32(-1), pk).astype(np.int32)
        # Zero out the dropped rows' values too: invalid rows never reach
        # a reduction, but a NaN payload must not survive into any
        # downstream array arithmetic either.
        mask = bad if values.ndim == 1 else bad[:, None]
        values = np.where(mask, 0.0, values)
    return EncodedData(pid=pid,
                       pk=pk,
                       values=values,
                       partition_vocab=partition_vocab,
                       n_privacy_ids=len(pid_vocab),
                       public_encoded=public_partitions is not None)


def encode(col,
           data_extractors: DataExtractors,
           public_partitions: Optional[Sequence[Any]] = None) -> EncodedData:
    """Extracts and integer-encodes (privacy_id, partition_key, value) rows.

    With public partitions, the partition vocabulary is fixed to them and
    rows in other partitions are marked invalid (pk = -1) — the columnar
    analogue of DPEngine._drop_partitions + _add_empty_public_partitions
    (empty public partitions exist as all-zero columns).
    """
    if isinstance(col, EncodedData):
        # Pre-encoded input (e.g. ingest.stream_encode_columns): extractors
        # are not consulted; with public partitions the caller must have
        # encoded against that same vocabulary.
        if (public_partitions is not None and
                list(dict.fromkeys(public_partitions)) != list(
                    col.partition_vocab)):
            raise ValueError(
                "Pre-encoded input must be encoded against the same public "
                "partitions passed to aggregate() (ingest."
                "stream_encode_columns(..., public_partitions=...)).")
        if public_partitions is None and col.public_encoded:
            raise ValueError(
                "This input was encoded against a fixed public-partition "
                "vocabulary (rows elsewhere were already dropped); "
                "aggregating it under private partition selection would "
                "silently lose them. Pass the same public_partitions, or "
                "re-encode without them.")
        return col
    pid_extractor = data_extractors.privacy_id_extractor or (lambda row: 0)
    pk_extractor = data_extractors.partition_extractor
    value_extractor = data_extractors.value_extractor or (lambda row: 0.0)
    if not isinstance(col, (list, tuple, np.ndarray)):
        col = list(col)
    # Per-row extractor calls are the only remaining Python loop; all
    # vocabulary work is vectorized in encode_columns.
    pid_raw = [pid_extractor(row) for row in col]
    pk_raw = [pk_extractor(row) for row in col]
    values = [value_extractor(row) for row in col]
    return encode_columns(pid_raw, pk_raw, values, public_partitions)
