"""Input validation helpers shared across the framework.

Semantics match the reference validators (/root/reference/pipeline_dp/
input_validators.py:17-34): epsilon must be a positive finite number, delta a
number in [0, 1).
"""

import math
import numbers


def validate_epsilon_delta(epsilon: float, delta: float, obj_name: str) -> None:
    """Validates that (epsilon, delta) is a well-formed DP budget.

    Raises:
        ValueError: epsilon is not a positive finite number or delta is not in
        [0, 1).
    """
    if not isinstance(epsilon, numbers.Number) or math.isnan(epsilon):
        raise ValueError(f"{obj_name}: epsilon must be a number, but "
                         f"{epsilon} given.")
    if epsilon <= 0 or math.isinf(epsilon):
        raise ValueError(f"{obj_name}: epsilon must be positive and finite, "
                         f"but epsilon={epsilon} given.")
    if not isinstance(delta, numbers.Number) or math.isnan(delta):
        raise ValueError(f"{obj_name}: delta must be a number, but "
                         f"{delta} given.")
    if delta < 0:
        raise ValueError(f"{obj_name}: delta must be non-negative, but "
                         f"delta={delta} given.")
    if delta >= 1:
        raise ValueError(f"{obj_name}: delta must be less than 1, but "
                         f"delta={delta} given.")
