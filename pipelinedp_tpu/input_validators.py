"""Input validation helpers shared across the framework.

Semantics match the reference validators (/root/reference/pipeline_dp/
input_validators.py:17-34): epsilon must be a positive finite number, delta a
number in [0, 1).

The runtime-knob validators (timeout_s, job_id, retry budgets) reject bad
values at the API boundary — TPUBackend construction and the blocked
drivers' entry — with actionable messages, instead of letting a
non-positive deadline silently disable the watchdog or a path-unsafe
job_id fail (or worse, sanitize into a colliding key) deep inside
BlockJournal._path.
"""

import math
import numbers
import re


def validate_epsilon_delta(epsilon: float, delta: float, obj_name: str) -> None:
    """Validates that (epsilon, delta) is a well-formed DP budget.

    Raises:
        ValueError: epsilon is not a positive finite number or delta is not in
        [0, 1).
    """
    if not isinstance(epsilon, numbers.Number) or math.isnan(epsilon):
        raise ValueError(f"{obj_name}: epsilon must be a number, but "
                         f"{epsilon} given.")
    if epsilon <= 0 or math.isinf(epsilon):
        raise ValueError(f"{obj_name}: epsilon must be positive and finite, "
                         f"but epsilon={epsilon} given.")
    if not isinstance(delta, numbers.Number) or math.isnan(delta):
        raise ValueError(f"{obj_name}: delta must be a number, but "
                         f"{delta} given.")
    if delta < 0:
        raise ValueError(f"{obj_name}: delta must be non-negative, but "
                         f"delta={delta} given.")
    if delta >= 1:
        raise ValueError(f"{obj_name}: delta must be less than 1, but "
                         f"delta={delta} given.")


# Journal job ids become file-name components (BlockJournal._path). The
# sanitizer there maps disallowed characters to "_", so two ids differing
# only in unsafe characters would COLLIDE on disk — reject them up front.
_JOB_ID_UNSAFE = re.compile(r"[/\\\x00]|(?:^|[/\\])\.\.(?:[/\\]|$)")


def validate_timeout_s(timeout_s, obj_name: str) -> None:
    """Validates a watchdog deadline: a positive finite number of seconds.

    Raises:
        ValueError: timeout_s is not a positive finite number.
    """
    if (not isinstance(timeout_s, numbers.Number) or
            isinstance(timeout_s, bool) or math.isnan(timeout_s)):
        raise ValueError(f"{obj_name}: timeout_s must be a number of "
                         f"seconds, but {timeout_s!r} given.")
    if timeout_s <= 0 or math.isinf(timeout_s):
        raise ValueError(
            f"{obj_name}: timeout_s must be positive and finite, but "
            f"timeout_s={timeout_s} given — a non-positive deadline would "
            f"expire every block immediately; leave it None to disable "
            f"deadlines instead.")


def validate_job_id(job_id, obj_name: str) -> None:
    """Validates a journal job id: a non-empty, path-safe string.

    Raises:
        ValueError: job_id is empty, not a string, or contains path
        separators / parent-directory references / NUL (which the journal
        file-name sanitizer would fold together, silently colliding two
        different jobs' records).
    """
    if not isinstance(job_id, str):
        raise ValueError(f"{obj_name}: job_id must be a string, but "
                         f"{type(job_id).__name__} given.")
    if not job_id.strip():
        raise ValueError(f"{obj_name}: job_id must be non-empty — it keys "
                         f"this job's journal records; pass a stable "
                         f"identifier (or None to derive one from the "
                         f"kernel config).")
    if len(job_id) > 200:
        raise ValueError(f"{obj_name}: job_id is {len(job_id)} characters; "
                         f"the limit is 200 (it becomes a file-name "
                         f"component).")
    if _JOB_ID_UNSAFE.search(job_id) or job_id in (".", ".."):
        raise ValueError(
            f"{obj_name}: job_id {job_id!r} contains path separators or "
            f"directory references; journal records are files named after "
            f"the job id, so it must be path-safe.")


def validate_elastic(elastic, obj_name: str) -> None:
    """Validates the elastic mesh-degradation switch: a plain bool.

    Raises:
        ValueError: elastic is not a bool (a truthy non-bool — say a
        mesh or a device count passed by mistake — would silently enable
        or disable device-loss tolerance).
    """
    if not isinstance(elastic, bool):
        raise ValueError(f"{obj_name}: elastic must be a bool, but "
                         f"{elastic!r} given (True enables device-loss "
                         f"mesh degradation on the meshed drivers).")


def validate_elastic_grow(elastic_grow, obj_name: str) -> None:
    """Validates the elastic scale-UP switch: a plain bool.

    Raises:
        ValueError: elastic_grow is not a bool (a truthy non-bool — say
        a device list passed by mistake — would silently enable or
        disable join admission).
    """
    if not isinstance(elastic_grow, bool):
        raise ValueError(
            f"{obj_name}: elastic_grow must be a bool, but "
            f"{elastic_grow!r} given (True lets the meshed drivers admit "
            f"announced join candidates at block boundaries and grow the "
            f"mesh — shrink tolerance included, so it implies elastic).")


def validate_min_devices(min_devices, obj_name: str) -> None:
    """Validates the elastic degradation floor: an integer >= 1.

    Raises:
        ValueError: min_devices is not a positive integer.
    """
    if (not isinstance(min_devices, numbers.Number) or
            isinstance(min_devices, bool) or
            min_devices != int(min_devices) or min_devices < 1):
        raise ValueError(
            f"{obj_name}: min_devices must be an integer >= 1, but "
            f"{min_devices!r} given — it is the device count below which "
            f"an elastic run refuses to degrade further and fails with a "
            f"resume pointer instead.")


def validate_trace(trace, obj_name: str) -> None:
    """Validates the tracing switch: a plain bool.

    Raises:
        ValueError: trace is not a bool (a truthy non-bool — say a file
        path passed where dump_trace(path) was meant — would silently
        enable process-wide span recording).
    """
    if not isinstance(trace, bool):
        raise ValueError(
            f"{obj_name}: trace must be a bool, but {trace!r} given "
            f"(True enables span/instant recording; export with "
            f"dump_trace(path)).")


def validate_pipeline_depth(pipeline_depth, obj_name: str) -> None:
    """Validates the streaming-executor staging window: an integer >= 1.

    Raises:
        ValueError: pipeline_depth is not a positive integer (a depth of
        0 would deadlock the staging queue's backpressure semaphore
        before the first chunk).
    """
    if (not isinstance(pipeline_depth, numbers.Number) or
            isinstance(pipeline_depth, bool) or
            pipeline_depth != int(pipeline_depth) or pipeline_depth < 1):
        raise ValueError(
            f"{obj_name}: pipeline_depth must be an integer >= 1, but "
            f"{pipeline_depth!r} given — it bounds how many encoded "
            f"chunks the streaming ingest stages in flight (None takes "
            f"the shared PIPELINE_DEPTH default).")


def validate_encode_threads(encode_threads, obj_name: str) -> None:
    """Validates the host encode pool size: an integer >= 0.

    Raises:
        ValueError: encode_threads is not a non-negative integer (0 is
        the serial encode path; >= 1 enables the pipelined path with
        that many workers).
    """
    if (not isinstance(encode_threads, numbers.Number) or
            isinstance(encode_threads, bool) or
            encode_threads != int(encode_threads) or encode_threads < 0):
        raise ValueError(
            f"{obj_name}: encode_threads must be an integer >= 0, but "
            f"{encode_threads!r} given — 0 keeps the serial chunk "
            f"encode, >= 1 runs chunk factorization on that many host "
            f"threads feeding the staging queue (None auto-sizes).")


def validate_encode_mode(encode_mode, obj_name: str) -> None:
    """Validates the ingest encode mode: "host" or "hash_device".

    Raises:
        ValueError: encode_mode is not one of the two modes ("host" is
        the exact chunked vocabulary encoder; "hash_device" hashes keys
        on the host and factorizes on device, with partition-key decode
        deferred to DP-selected indices).
    """
    if encode_mode not in ("host", "hash_device"):
        raise ValueError(
            f"{obj_name}: encode_mode must be 'host' or 'hash_device', "
            f"but {encode_mode!r} given — 'host' runs the exact chunked "
            f"vocabulary encoder, 'hash_device' the on-device hash "
            f"factorization with decode-at-selected-indices (falls back "
            f"to 'host' on a detected hash collision).")


def validate_numeric_mode(numeric_mode, obj_name: str) -> None:
    """Validates the accumulation numeric mode: "fast" or "safe".

    Raises:
        ValueError: numeric_mode is not one of the two modes ("fast" is
        the historical bit-identical f32 segment reduction; "safe" runs
        the compensated (TwoSum hi/lo) scan — exact for integer-valued
        workloads to ~2^48 — and arms the release sentinel's overflow
        classification).
    """
    if numeric_mode not in ("fast", "safe"):
        raise ValueError(
            f"{obj_name}: numeric_mode must be 'fast' or 'safe', but "
            f"{numeric_mode!r} given — 'fast' keeps the bit-identical "
            f"historical accumulation, 'safe' switches the fused kernels "
            f"to compensated summation and fails closed (typed "
            f"NumericOverflowError, nothing released) on overflow.")


def validate_snap_grid_bits(snap_grid_bits, obj_name: str) -> None:
    """Validates the snapping-grid floor exponent: an integer in [-64, 64].

    Raises:
        ValueError: snap_grid_bits is not an integer in range (it floors
        the power-of-two snapping grid at 2**snap_grid_bits for the
        discrete/snapped mechanisms and the secure-noise tables; a
        float or a bool here is a bug, not a coarser grid).
    """
    if (not isinstance(snap_grid_bits, numbers.Number) or
            isinstance(snap_grid_bits, bool) or
            snap_grid_bits != int(snap_grid_bits) or
            not -64 <= snap_grid_bits <= 64):
        raise ValueError(
            f"{obj_name}: snap_grid_bits must be an integer in "
            f"[-64, 64], but {snap_grid_bits!r} given — releases snap to "
            f"the power-of-two grid max(mechanism grid, "
            f"2**snap_grid_bits), so the exponent must be a bounded "
            f"integer (None disables the floor).")


def validate_metrics_port(metrics_port, obj_name: str) -> None:
    """Validates the live-metrics scrape port: an integer in [0, 65535].

    Raises:
        ValueError: metrics_port is not an integer in range (0 binds an
        ephemeral port — read it back from the exporter; a float or a
        path passed here was probably meant for metrics_path).
    """
    if (not isinstance(metrics_port, numbers.Number) or
            isinstance(metrics_port, bool) or
            metrics_port != int(metrics_port) or
            not 0 <= metrics_port <= 65535):
        raise ValueError(
            f"{obj_name}: metrics_port must be an integer in [0, 65535], "
            f"but {metrics_port!r} given — it binds the Prometheus "
            f"scrape endpoint on 127.0.0.1 (0 picks an ephemeral port; "
            f"use metrics_path for the portless file mode).")


def validate_metrics_path(metrics_path, obj_name: str) -> None:
    """Validates the atomic-file metrics export path: a non-empty string
    naming a file in an existing (or creatable) directory.

    Raises:
        ValueError: metrics_path is not a non-empty string (the portless
        scrape mode re-writes this file atomically on an interval; a
        port number passed here was probably meant for metrics_port).
    """
    if not isinstance(metrics_path, str) or not metrics_path.strip():
        raise ValueError(
            f"{obj_name}: metrics_path must be a non-empty file path "
            f"string, but {metrics_path!r} given — the file-mode "
            f"exporter atomically re-writes the Prometheus text there "
            f"(use metrics_port for the HTTP endpoint).")


def validate_num_processes(num_processes, obj_name: str) -> None:
    """Validates the multi-controller process count: an integer >= 1.

    Raises:
        ValueError: num_processes is not a positive integer (it is the
        jax.distributed job size — every controller must pass the same
        value or the coordinator rejects the late joiners).
    """
    if (not isinstance(num_processes, numbers.Number) or
            isinstance(num_processes, bool) or
            num_processes != int(num_processes) or num_processes < 1):
        raise ValueError(
            f"{obj_name}: num_processes must be an integer >= 1, but "
            f"{num_processes!r} given — it is the total controller count "
            f"of the jax.distributed job (1 = single-process; leave both "
            f"multi-host knobs None to skip distributed bring-up).")


def validate_coordinator_address(coordinator_address, obj_name: str) -> None:
    """Validates a jax.distributed coordinator address: "host:port".

    Raises:
        ValueError: not a non-empty "host:port" string with an integer
        port in [1, 65535] (a bare hostname would make every process
        pick its own default and never rendezvous).
    """
    if not isinstance(coordinator_address, str) or \
            not coordinator_address.strip():
        raise ValueError(
            f"{obj_name}: coordinator_address must be a non-empty "
            f"'host:port' string, but {coordinator_address!r} given.")
    host, sep, port = coordinator_address.rpartition(":")
    if not sep or not host.strip():
        raise ValueError(
            f"{obj_name}: coordinator_address {coordinator_address!r} "
            f"has no host:port separator — every controller must "
            f"rendezvous on one explicit endpoint.")
    try:
        port_n = int(port)
    except ValueError:
        port_n = -1
    if not 1 <= port_n <= 65535:
        raise ValueError(
            f"{obj_name}: coordinator_address port {port!r} is not an "
            f"integer in [1, 65535].")


def validate_max_concurrent_jobs(max_concurrent_jobs, obj_name: str) -> None:
    """Validates the service worker-pool width: an integer >= 1.

    Raises:
        ValueError: max_concurrent_jobs is not a positive integer (it is
        the number of jobs the resident service executes concurrently —
        0 would admit work that no worker can ever run).
    """
    if (not isinstance(max_concurrent_jobs, numbers.Number) or
            isinstance(max_concurrent_jobs, bool) or
            max_concurrent_jobs != int(max_concurrent_jobs) or
            max_concurrent_jobs < 1):
        raise ValueError(
            f"{obj_name}: max_concurrent_jobs must be an integer >= 1, "
            f"but {max_concurrent_jobs!r} given — it sizes the service's "
            f"worker pool; submissions beyond it queue rather than "
            f"rejecting.")


def validate_tenant_budget_epsilon(tenant_budget_epsilon,
                                   obj_name: str) -> None:
    """Validates a tenant's lifetime epsilon budget: a positive number
    (math.inf = unlimited — the ledger still records spend).

    Raises:
        ValueError: tenant_budget_epsilon is not a positive number.
    """
    if (not isinstance(tenant_budget_epsilon, numbers.Number) or
            isinstance(tenant_budget_epsilon, bool) or
            math.isnan(tenant_budget_epsilon) or tenant_budget_epsilon <= 0):
        raise ValueError(
            f"{obj_name}: tenant_budget_epsilon must be a positive "
            f"number, but {tenant_budget_epsilon!r} given — it is the "
            f"lifetime epsilon a tenant's ledger may accumulate before "
            f"submissions are refused (math.inf disables the cap).")


def validate_queue_timeout_s(queue_timeout_s, obj_name: str) -> None:
    """Validates the admission-queue wait bound: a positive finite
    number of seconds.

    Raises:
        ValueError: queue_timeout_s is not a positive finite number (a
        non-positive bound would shed every queued job on dequeue).
    """
    if (not isinstance(queue_timeout_s, numbers.Number) or
            isinstance(queue_timeout_s, bool) or
            math.isnan(queue_timeout_s)):
        raise ValueError(f"{obj_name}: queue_timeout_s must be a number "
                         f"of seconds, but {queue_timeout_s!r} given.")
    if queue_timeout_s <= 0 or math.isinf(queue_timeout_s):
        raise ValueError(
            f"{obj_name}: queue_timeout_s must be positive and finite, "
            f"but queue_timeout_s={queue_timeout_s} given — jobs that "
            f"wait in the admission queue longer than this are shed "
            f"with a retry-after instead of running arbitrarily late.")


def validate_drain_timeout_s(drain_timeout_s, obj_name: str) -> None:
    """Validates the drain bound: a positive finite number of seconds.

    Raises:
        ValueError: drain_timeout_s is not a positive finite number (an
        unbounded drain would let one wedged job stall a rolling
        restart forever).
    """
    if (not isinstance(drain_timeout_s, numbers.Number) or
            isinstance(drain_timeout_s, bool) or
            math.isnan(drain_timeout_s)):
        raise ValueError(f"{obj_name}: drain_timeout_s must be a number "
                         f"of seconds, but {drain_timeout_s!r} given.")
    if drain_timeout_s <= 0 or math.isinf(drain_timeout_s):
        raise ValueError(
            f"{obj_name}: drain_timeout_s must be positive and finite, "
            f"but drain_timeout_s={drain_timeout_s} given — it bounds "
            f"how long drain() waits for running jobs before a "
            f"migration or rolling restart proceeds.")


def validate_deadline_s(deadline_s, obj_name: str) -> None:
    """Validates a job deadline: a positive finite number of seconds.

    Raises:
        ValueError: deadline_s is not a positive finite number (a
        non-positive deadline would cancel every job at dequeue; an
        infinite one is spelled deadline_s=None).
    """
    if (not isinstance(deadline_s, numbers.Number) or
            isinstance(deadline_s, bool) or
            math.isnan(deadline_s)):
        raise ValueError(f"{obj_name}: deadline_s must be a number "
                         f"of seconds, but {deadline_s!r} given.")
    if deadline_s <= 0 or math.isinf(deadline_s):
        raise ValueError(
            f"{obj_name}: deadline_s must be positive and finite, but "
            f"deadline_s={deadline_s} given — it bounds the job's total "
            f"submit-to-finish wall time (queue wait included); a job "
            f"past it settles CANCELLED with JobCancelledError, charges "
            f"nothing and releases its reservation. Use deadline_s=None "
            f"for no deadline.")


def validate_shed_watermark_fraction(shed_watermark_fraction,
                                     obj_name: str) -> None:
    """Validates the load-shed memory threshold: a number in (0, 1].

    Raises:
        ValueError: shed_watermark_fraction is not a number in (0, 1]
        (it is the fraction of the device-memory limit above which the
        service sheds new submissions instead of OOMing running jobs).
    """
    if (not isinstance(shed_watermark_fraction, numbers.Number) or
            isinstance(shed_watermark_fraction, bool) or
            math.isnan(shed_watermark_fraction) or
            not 0 < shed_watermark_fraction <= 1):
        raise ValueError(
            f"{obj_name}: shed_watermark_fraction must be a number in "
            f"(0, 1], but {shed_watermark_fraction!r} given — admissions "
            f"are shed when the live device-memory watermark exceeds "
            f"this fraction of the memory limit.")


def validate_batching(batching, obj_name: str) -> None:
    """Validates the megabatched-serving switch: a plain bool.

    Raises:
        ValueError: batching is not a bool (a truthy non-bool — say a
        window or a lane count passed by mistake — would silently route
        every job's release through the coalescing tier).
    """
    if not isinstance(batching, bool):
        raise ValueError(
            f"{obj_name}: batching must be a bool, but {batching!r} "
            f"given (True coalesces identical-spec concurrent jobs into "
            f"one vmapped release launch; per-job results are "
            f"bit-identical either way).")


def validate_batch_window_ms(batch_window_ms, obj_name: str) -> None:
    """Validates the coalescing window: a positive finite number of
    milliseconds.

    Raises:
        ValueError: batch_window_ms is not a positive finite number (a
        non-positive window would close every batch before a second
        lane could join; an infinite one would park the first job of
        every spec forever).
    """
    if (not isinstance(batch_window_ms, numbers.Number) or
            isinstance(batch_window_ms, bool) or
            math.isnan(batch_window_ms)):
        raise ValueError(f"{obj_name}: batch_window_ms must be a number "
                         f"of milliseconds, but {batch_window_ms!r} "
                         f"given.")
    if batch_window_ms <= 0 or math.isinf(batch_window_ms):
        raise ValueError(
            f"{obj_name}: batch_window_ms must be positive and finite, "
            f"but batch_window_ms={batch_window_ms} given — it is how "
            f"long the first identical-spec job waits for others to "
            f"coalesce before launching (latency floor vs. batch "
            f"occupancy).")


def validate_max_batch_jobs(max_batch_jobs, obj_name: str) -> None:
    """Validates the batch lane cap: an integer >= 2.

    Raises:
        ValueError: max_batch_jobs is not an integer >= 2 (a 1-lane
        "batch" IS the solo path — the coalescer dispatches early once
        this many lanes joined, without waiting out the window).
    """
    if (not isinstance(max_batch_jobs, numbers.Number) or
            isinstance(max_batch_jobs, bool) or
            max_batch_jobs != int(max_batch_jobs) or max_batch_jobs < 2):
        raise ValueError(
            f"{obj_name}: max_batch_jobs must be an integer >= 2, but "
            f"{max_batch_jobs!r} given — it caps the lanes of one "
            f"megabatched launch; a full window dispatches immediately "
            f"(1 lane would just be the solo path with extra waiting).")


def validate_aot(aot, obj_name: str) -> None:
    """Validates the ahead-of-time executable-cache switch: a plain bool.

    Raises:
        ValueError: aot is not a bool (a truthy non-bool — say a cache
        object or a path passed by mistake — would silently route every
        warm dispatch through the AOT executable cache).
    """
    if not isinstance(aot, bool):
        raise ValueError(
            f"{obj_name}: aot must be a bool, but {aot!r} given (True "
            f"routes warm-path entry points through the process-wide "
            f".lower().compile() executable cache, runtime/aot.py).")


def validate_fused_release(fused_release, obj_name: str) -> None:
    """Validates the fused-release-kernel switch: a plain bool.

    Raises:
        ValueError: fused_release is not a bool (a truthy non-bool would
        silently flip the dense routes between the one-program
        compacting release and the unfused kernel + host nonzero chain).
    """
    if not isinstance(fused_release, bool):
        raise ValueError(
            f"{obj_name}: fused_release must be a bool, but "
            f"{fused_release!r} given (True fuses DP selection, noise "
            f"and kept-first compaction into one device program with an "
            f"O(kept) drain; outputs are bit-identical either way).")


def validate_overlap_drain(overlap_drain, obj_name: str) -> None:
    """Validates the compute/drain-overlap switch: a plain bool.

    Raises:
        ValueError: overlap_drain is not a bool (a truthy non-bool —
        say a thread count — would silently choose between the
        drainer-thread and serial consume modes of the blocked
        drivers).
    """
    if not isinstance(overlap_drain, bool):
        raise ValueError(
            f"{obj_name}: overlap_drain must be a bool, but "
            f"{overlap_drain!r} given (True drains block b on a "
            f"dedicated thread while block b+1 dispatches; results are "
            f"bit-identical either way).")


def validate_journal(journal, obj_name: str) -> None:
    """Validates a BlockJournal-shaped object: get/put record accessors.

    Raises:
        ValueError: journal lacks callable get/put (e.g. a directory
        path was passed where runtime.BlockJournal(path) was meant).
    """
    if not (callable(getattr(journal, "get", None)) and
            callable(getattr(journal, "put", None))):
        raise ValueError(
            f"{obj_name}: journal must be a runtime.BlockJournal-like "
            f"object with get/put, but {type(journal).__name__} given "
            f"(pass runtime.BlockJournal(directory), not the directory).")


def validate_watchdog(watchdog, obj_name: str) -> None:
    """Validates a Watchdog-shaped object: guard/resolved_timeout.

    Raises:
        ValueError: watchdog lacks the monitor interface (e.g. a number
        of seconds was passed where timeout_s= was meant).
    """
    if not (callable(getattr(watchdog, "guard", None)) and
            callable(getattr(watchdog, "resolved_timeout", None))):
        raise ValueError(
            f"{obj_name}: watchdog must be a runtime.Watchdog-like "
            f"object with guard/resolved_timeout, but "
            f"{type(watchdog).__name__} given (a plain deadline in "
            f"seconds is the timeout_s= knob).")


def validate_retry_policy(retry, obj_name: str) -> None:
    """Validates a runtime.RetryPolicy-shaped object's budgets.

    Raises:
        ValueError: negative max_retries, or negative/NaN delays.
    """
    max_retries = getattr(retry, "max_retries", None)
    if (not isinstance(max_retries, numbers.Number) or
            isinstance(max_retries, bool) or max_retries < 0 or
            max_retries != int(max_retries)):
        raise ValueError(
            f"{obj_name}: retry.max_retries must be a non-negative "
            f"integer, but {max_retries!r} given (0 disables retries; "
            f"use None for the retry= knob itself to take the default "
            f"policy).")
    for field in ("base_delay", "max_delay"):
        v = getattr(retry, field, 0.0)
        if (not isinstance(v, numbers.Number) or isinstance(v, bool) or
                math.isnan(v) or v < 0):
            raise ValueError(f"{obj_name}: retry.{field} must be a "
                             f"non-negative number of seconds, but "
                             f"{v!r} given.")
    budget = getattr(retry, "max_total_retries", None)
    if budget is not None and (
            not isinstance(budget, numbers.Number) or
            isinstance(budget, bool) or budget < 0 or
            budget != int(budget)):
        raise ValueError(
            f"{obj_name}: retry.max_total_retries must be None (no "
            f"per-job budget) or a non-negative integer, but "
            f"{budget!r} given — it caps the job's TOTAL transient "
            f"retries across every seam (dispatch retry, reshard "
            f"fallback, host fetch), so composed faults cannot spiral "
            f"one job into a retry storm.")


def validate_tenant_accounting(tenant_accounting, obj_name: str) -> None:
    """Validates the tenant-admission accounting mode: the string
    "naive" (admission charges the bit-exact left-to-right epsilon sum,
    the ledger-of-record) or "pld" (admission charges the PLD-composed
    epsilon rebuilt from the odometer trail, with a documented safety
    margin — the capacity multiplier).

    Raises:
        ValueError: tenant_accounting is not "naive" or "pld".
    """
    if tenant_accounting not in ("naive", "pld"):
        raise ValueError(
            f"{obj_name}: tenant_accounting must be 'naive' (admission "
            f"charges the bit-exact epsilon sum) or 'pld' (admission "
            f"charges the PLD-composed spend rebuilt from the odometer "
            f"trail), but {tenant_accounting!r} given.")


def validate_pld_discretization(pld_discretization, obj_name: str) -> None:
    """Validates the PLD loss-grid discretization interval: a finite
    number in [1e-7, 0.5]. Finer than 1e-7 makes million-cell grids
    balloon past the composition engine's coarsening budget; coarser
    than 0.5 gives ceilings too loose to be useful.

    Raises:
        ValueError: pld_discretization is not a number in [1e-7, 0.5].
    """
    if (not isinstance(pld_discretization, numbers.Number) or
            isinstance(pld_discretization, bool) or
            math.isnan(pld_discretization) or
            not 1e-7 <= pld_discretization <= 0.5):
        raise ValueError(
            f"{obj_name}: pld_discretization must be a number in "
            f"[1e-7, 0.5], but {pld_discretization!r} given — it is "
            f"the privacy-loss grid interval; finer grids are more "
            f"accurate but cost memory and FFT time (pessimistic "
            f"ceiling rounding keeps every choice sound).")
