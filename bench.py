#!/usr/bin/env python
"""Benchmark: DP SUM+COUNT throughput at eps=1 on one chip.

Measures the fused columnar kernel (contribution bounding + per-(pid,pk)
aggregation + private partition selection + noise) end-to-end on synthetic
movie_view_ratings-shaped data (BASELINE.json configs[1]/[3] shape), and
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "records/sec/chip", "vs_baseline": N}

vs_baseline is value / north_star (50M records/sec/chip, BASELINE.json).

Data is generated directly as columnar arrays (the large-scale ingestion
path — string-key vocab encoding is a host concern benchmarked separately),
streamed through the kernel in chunks that fit HBM.
"""

import argparse
import json
import time

import numpy as np

NORTH_STAR_RECORDS_PER_SEC = 50e6


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=200_000_000,
                        help="total synthetic rows to push through")
    parser.add_argument("--chunk", type=int, default=0,
                        help="rows per device chunk (0 = auto)")
    parser.add_argument("--partitions", type=int, default=4096)
    parser.add_argument("--users", type=int, default=1_000_000)
    parser.add_argument("--cpu", action="store_true",
                        help="force CPU (debug)")
    args = parser.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import combiners, executor
    from pipelinedp_tpu.aggregate_params import MechanismType
    from pipelinedp_tpu.ops import selection_ops

    device = jax.devices()[0]
    on_tpu = device.platform != "cpu"
    chunk = args.chunk or (2**25 if on_tpu else 2**20)  # 33.5M rows on TPU
    chunk = min(chunk, args.rows)

    # --- Aggregation spec: SUM+COUNT, eps=1, private partition selection. ---
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=4,
                                 max_contributions_per_partition=8,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    selection_budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, selection_budget.eps,
        selection_budget.delta, params.max_partitions_contributed, None)
    cfg = executor.make_kernel_config(params, compound, args.partitions,
                                      private_selection=True,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    min_v, max_v, min_s, max_s, mid = executor.kernel_scalars(params)

    # --- Synthetic data: zipf-ish partition popularity, uniform users. ---
    key = jax.random.PRNGKey(0)

    def make_chunk(k):
        kp, ku, kv = jax.random.split(k, 3)
        # Exponentially-tilted partition popularity.
        u = jax.random.uniform(kp, (chunk,))
        pk = (jnp.power(u, 3.0) * args.partitions).astype(jnp.int32)
        pid = jax.random.randint(ku, (chunk,), 0, args.users, dtype=jnp.int32)
        values = jax.random.uniform(kv, (chunk,), minval=0.0, maxval=5.0)
        valid = jnp.ones((chunk,), dtype=bool)
        return pid, pk, values, valid

    make_chunk = jax.jit(make_chunk)

    def step(k):
        pid, pk, values, valid = make_chunk(jax.random.fold_in(k, 1))
        return executor.aggregate_kernel(pid, pk, values, valid, min_v, max_v,
                                         min_s, max_s, mid, jnp.asarray(stds),
                                         jax.random.fold_in(k, 2), cfg)

    # Warmup / compile. Synchronization is a host fetch of one output
    # scalar, NOT block_until_ready: under remote-tunneled devices the
    # latter can return at dispatch time and overstate throughput.
    outputs, keep, _ = step(key)
    _ = float(outputs["count"][0])

    n_chunks = max(1, args.rows // chunk)
    start = time.perf_counter()
    results = []
    for i in range(n_chunks):
        results.append(step(jax.random.fold_in(key, i)))
    for outputs, keep, _ in results:
        _ = float(outputs["count"][0])  # forces each chunk's execution
    elapsed = time.perf_counter() - start

    total_rows = n_chunks * chunk
    records_per_sec = total_rows / elapsed

    # Noise-distribution fidelity: KS statistic of 1M device noise draws
    # vs the CPU reference distribution at the same calibrated stddev
    # (BASELINE.json metric "noise-dist KS-stat vs CPU ref").
    from scipy import stats as scipy_stats
    from pipelinedp_tpu.ops import noise as noise_ops
    sum_std = float(stds[1])
    draws = np.asarray(
        noise_ops.laplace_noise(jax.random.PRNGKey(7), (1_000_000,),
                                jnp.float32(sum_std)))
    ks = float(
        scipy_stats.kstest(draws,
                           scipy_stats.laplace(scale=sum_std /
                                               np.sqrt(2.0)).cdf).statistic)
    print(
        json.dumps({
            "metric": "DP SUM+COUNT records/sec/chip (eps=1, private "
                      "partition selection, fused kernel)",
            "value": round(records_per_sec),
            "unit": "records/sec/chip",
            "vs_baseline": round(records_per_sec / NORTH_STAR_RECORDS_PER_SEC,
                                 4),
            "detail": {
                "rows": total_rows,
                "chunk": chunk,
                "partitions": args.partitions,
                "users": args.users,
                "elapsed_sec": round(elapsed, 3),
                "device": str(device),
                "kept_partitions": int(np.asarray(keep).sum()),
                "noise_ks_stat_vs_cpu_ref": round(ks, 5),
            },
        }))


if __name__ == "__main__":
    main()
