#!/usr/bin/env python
"""Benchmark: DP SUM+COUNT throughput at eps=1 on one chip.

Measures the fused columnar kernel (contribution bounding + per-(pid,pk)
aggregation + private partition selection + noise) end-to-end on synthetic
movie_view_ratings-shaped data (BASELINE.json configs[1]/[3] shape), and
prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "records/sec/chip", "vs_baseline": N}

vs_baseline is value / north_star (50M records/sec/chip, BASELINE.json).

Data is generated directly as columnar arrays (the large-scale ingestion
path — string-key vocab encoding is a host concern benchmarked separately),
streamed through the kernel in chunks that fit HBM.
"""

import argparse
import json
import sys
import tempfile
import time

import numpy as np

NORTH_STAR_RECORDS_PER_SEC = 50e6


def _log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _probe_backend(timeout_sec):
    """Try backend init in a THROWAWAY subprocess with a hard timeout.

    Backend init can fail two ways: a fast UNAVAILABLE RuntimeError, or an
    indefinite hang inside the PJRT client (observed with remote-tunneled
    chips: jax.devices() blocks in C++ >9 min). The latter cannot be timed
    out in-process (signals don't preempt the blocked C call), so the probe
    runs in a subprocess we can kill. The probe exits on success, releasing
    the chip for the main process.

    Returns (ok, message).
    """
    import subprocess
    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_sec)
    except subprocess.TimeoutExpired:
        return False, f"init hung > {timeout_sec:.0f}s (killed)"
    if r.returncode == 0 and r.stdout.strip():
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or "").strip().splitlines()
    return False, (tail[-1][:300] if tail else f"rc={r.returncode}")


def acquire_device(max_wait_sec=480.0):
    """Initialize a JAX backend, riding through transient TPU-init failures.

    Round-1 failure mode: dying at the first jax.devices() with UNAVAILABLE
    lost the benchmark entirely. Strategy: probe init in killable
    subprocesses (handles both fast failures and hangs), retry with backoff
    until max_wait_sec, and only then fall back to CPU so the run still
    emits a parseable diagnostic line instead of a stack trace.

    Returns (device, fallback_reason) — fallback_reason is None when the
    preferred backend came up, else a short string for the JSON detail.
    """
    import jax

    deadline = time.time() + max_wait_sec
    attempt = 0
    delay = 5.0
    probe_timeout = 90.0
    last_msg = "no attempts made"
    while time.time() < deadline:
        attempt += 1
        budget = max(10.0, deadline - time.time())
        ok, msg = _probe_backend(min(probe_timeout, budget))
        if ok:
            _log(f"probe succeeded on attempt {attempt} (platform={msg}); "
                 f"initializing in-process")
            try:
                dev = jax.devices()[0]
            except RuntimeError as e:
                # Chip grabbed between probe exit and our init. JAX caches
                # the failed backend set, so retrying in this process cannot
                # recover — go straight to the CPU fallback with a reason.
                last_msg = (f"in-process init failed after successful probe: "
                            f"{str(e).splitlines()[0][:200]}")
                break
            if dev.platform == "cpu" and msg != "cpu":
                # Partial init: the TPU factory failed but CPU registered,
                # and the cached backend set hides the failure from now on.
                last_msg = (f"in-process init degraded to cpu "
                            f"(probe saw {msg})")
                break
            return dev, None
        last_msg = msg
        remaining = deadline - time.time()
        if remaining <= delay:
            break
        _log(f"attempt {attempt}: {msg}; retrying in {delay:.0f}s "
             f"({remaining:.0f}s left)")
        time.sleep(delay)
        delay = min(delay * 2, 60.0)
        probe_timeout = min(probe_timeout * 1.5, 240.0)
    # Preferred backend never came up: fall back to CPU so the run still
    # emits a parseable result (marked as fallback) rather than rc=1.
    _log(f"backend init failed permanently after {attempt} attempts: "
         f"{last_msg}")
    _log("falling back to CPU — throughput below will NOT reflect TPU")
    jax.config.update("jax_platforms", "cpu")
    dev = jax.devices("cpu")[0]
    return dev, f"tpu-init-failed: {last_msg[:160]}"


def _builder_receipt_summary():
    """Headline of the newest committed BENCH_*_builder.json, for embedding
    in CPU-fallback receipts: a tunnel-dropped driver run then still
    surfaces the latest device-verified evidence (clearly labeled as the
    committed builder receipt, NOT this run's measurement)."""
    import glob
    import os
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    candidates = sorted(glob.glob(os.path.join(repo,
                                               "BENCH_*_builder.json")))
    if not candidates:
        return None
    path = candidates[-1]  # BENCH_rNN_ sorts by round
    try:
        with open(path) as f:
            receipt = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    committed_at = None
    try:
        r = subprocess.run(
            ["git", "-C", repo, "log", "-1", "--format=%cI", "--", path],
            capture_output=True, text=True, timeout=30)
        committed_at = r.stdout.strip() or None
    except Exception:  # noqa: BLE001 - timestamp is best-effort
        pass
    return {
        "file": os.path.basename(path),
        "value": receipt.get("value"),
        "unit": receipt.get("unit"),
        "vs_baseline": receipt.get("vs_baseline"),
        "device": receipt.get("detail", {}).get("device"),
        "committed_at": committed_at,
    }


def _bench_eps_sweep(jax, jnp, on_tpu):
    """BASELINE config 5: 64-parameter-config utility-analysis ε-sweep,
    vmapped over the config axis in one jit-compiled program
    (analysis/kernels.sweep_kernel)."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.analysis import error_model as em
    from pipelinedp_tpu.analysis import kernels as analysis_kernels

    n_rows = 2**21 if on_tpu else 2**17
    n_partitions = 2**14 if on_tpu else 2**10
    l0_grid = [1, 2, 4, 8, 16, 32, 64, 128]
    linf_grid = [1, 2, 4, 8, 16, 32, 64, 128]
    configs = [
        pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                            noise_kind=pdp.NoiseKind.GAUSSIAN,
                            max_partitions_contributed=l0,
                            max_contributions_per_partition=linf)
        for l0 in l0_grid for linf in linf_grid
    ]
    noise_stds = np.array([[
        em.config_noise_std(p, pdp.Metrics.COUNT, 1.0, 1e-6)
    ] for p in configs])
    cfg = analysis_kernels.build_config_arrays(configs, [pdp.Metrics.COUNT],
                                               noise_stds, (1.0, 1e-6))
    rng = np.random.default_rng(11)
    counts = rng.integers(1, 16, n_rows).astype(np.float64)
    sums = rng.random(n_rows) * 5.0
    contributed = rng.integers(1, 256, n_rows).astype(np.float64)
    pk_idx = rng.integers(0, n_partitions, n_rows).astype(np.int32)

    def run():
        out = analysis_kernels.sweep_kernel(
            counts,
            sums,
            contributed,
            pk_idx,
            cfg,
            n_partitions_total=n_partitions,
            metric_codes=(analysis_kernels.METRIC_CODES[pdp.Metrics.COUNT],),
            public=False,
            return_per_partition=False)
        return float(np.asarray(out["bucket_rows"]).sum())

    run()  # compile
    start = time.perf_counter()
    checksum = run()
    elapsed = time.perf_counter() - start
    del checksum
    return {
        "eps_sweep_configs": len(configs),
        "eps_sweep_rows": n_rows,
        "eps_sweep_partitions": n_partitions,
        "eps_sweep_sec": round(elapsed, 4),
        "eps_sweep_config_rows_per_sec": round(
            len(configs) * n_rows / elapsed),
    }


def _bench_large_p(jax, on_tpu):
    """10^7-partition aggregation in bounded memory via the blocked
    partition-axis path (parallel/large_p.py). Spec + data shared with the
    standalone benchmarks (benchmarks/_common.py) so the numbers stay
    comparable."""
    from benchmarks import _common
    from pipelinedp_tpu.parallel import large_p

    P = 10_000_000
    n = 2**22 if on_tpu else 2**18
    _, cfg, stds, (min_v, max_v, min_s, max_s, mid) = _common.build_spec(P)
    pid, pk, values, valid = _common.zipfish_data(n, P)

    def run(key_seed):
        return large_p.aggregate_blocked(pid,
                                         pk,
                                         values,
                                         valid,
                                         min_v,
                                         max_v,
                                         min_s,
                                         max_s,
                                         mid,
                                         stds,
                                         jax.random.PRNGKey(key_seed),
                                         cfg,
                                         block_partitions=1 << 20)

    run(8)  # warm the jit caches (bounded-rows + block kernels)
    start = time.perf_counter()
    kept, _ = run(9)
    elapsed = time.perf_counter() - start

    # Device-resident regime: rows already in HBM (the streamed-ingest
    # case) — isolates compute+dispatch from the host->device upload that
    # dominates the host-staged number over the tunnel (roofline term 3
    # vs 4, benchmarks/README.md).
    dev = [jax.device_put(c) for c in (pid, pk, values, valid)]
    _common.sync_fetch(dev, all_leaves=True)  # block_until_ready no-ops

    def run_dev(key_seed):
        return large_p.aggregate_blocked(*dev, min_v, max_v, min_s, max_s,
                                         mid, stds,
                                         jax.random.PRNGKey(key_seed), cfg,
                                         block_partitions=1 << 20)

    run_dev(8)
    start = time.perf_counter()
    kept_dev, _ = run_dev(9)
    dev_elapsed = time.perf_counter() - start
    # Both kept counts land in the receipt; a mismatch is surfaced loudly
    # but must not abort the whole run (an assert here once cost an entire
    # receipt over one discrepancy — every other benchmark's numbers died
    # with it).
    if len(kept_dev) != len(kept):
        _log(f"WARNING: large_p kept-count mismatch — host-staged "
             f"{len(kept)} vs device-resident {len(kept_dev)}; recording "
             f"both (same key/seed, so this deserves a look)")
    return {
        "large_p_partitions": P,
        "large_p_rows": n,
        "large_p_sec": round(elapsed, 3),
        "large_p_rows_per_sec": round(n / elapsed),
        "large_p_device_resident_sec": round(dev_elapsed, 3),
        "large_p_device_resident_rows_per_sec": round(n / dev_elapsed),
        "large_p_kept": int(len(kept)),
        "large_p_kept_device_resident": int(len(kept_dev)),
        **({"large_p_kept_mismatch": True}
           if len(kept_dev) != len(kept) else {}),
    }


def _bench_meshed_reshard(on_tpu):
    """Host-staged vs collective (all_to_all) reshard on the 8-device CPU
    mesh (benchmarks/bench_reshard.py in a subprocess: the virtual-device
    mesh needs XLA_FLAGS set before backend init, which this process has
    already done). A single attached chip cannot exchange with itself, so
    the CPU mesh is the only multi-device fabric available either way;
    see benchmarks/README.md for what the CPU numbers do and do not
    bound."""
    import os
    import subprocess
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "bench_reshard.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # let the script set its own device count
    rows = 2**20 if on_tpu else 2**18
    try:
        r = subprocess.run([sys.executable, script, "--rows", str(rows)],
                           capture_output=True, text=True, env=env,
                           timeout=600)
    except subprocess.TimeoutExpired:
        return {"meshed_reshard_error": "timed out after 600s"}
    if r.returncode != 0 or not r.stdout.strip():
        tail = (r.stderr or "").strip().splitlines()
        return {
            "meshed_reshard_error":
                (tail[-1][:200] if tail else f"rc={r.returncode}")
        }
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])
    except json.JSONDecodeError:
        return {"meshed_reshard_error": "unparseable output"}


def _bench_multihost():
    """multihost_* receipt keys (runtime/multihost.multihost_receipt):
    the controller topology this receipt was produced under — process
    count, per-process ingest overlap factor, and the cross-host share
    of the traced collective-reshard exchange bytes. A single-controller
    bench reports processes=1 / 0 cross-host bytes; a pod launcher
    running this same benchmark under jax.distributed gets the real
    numbers with no bench changes. The 2-process correctness gate lives
    in tier-1 (tests/test_multihost.py), not here."""
    try:
        from pipelinedp_tpu.runtime import multihost as rt_multihost
        return rt_multihost.multihost_receipt()
    except Exception as e:  # noqa: BLE001 - the receipt must survive topology introspection failure
        return {"multihost_error": f"{type(e).__name__}: {e}"}


def _bench_service(on_tpu):
    """`service` receipt key: the resident multi-tenant session layer
    driven end to end — one warm job compiles the shared entry points,
    then 3 tenants fan 8 identical-spec jobs over one backend. Reports
    jobs/sec and job-latency percentiles (queue wait included), the jit
    cache misses the REUSE jobs added (0 = every tenant after the first
    hit the warm compile cache), and whether every tenant's ledger
    reconciles bit-exactly with its jobs' accountants."""
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.runtime import trace as rt_trace
    from pipelinedp_tpu.service import DPAggregationService, JobSpec

    try:
        rng = np.random.default_rng(11)
        n_rows, n_partitions = 20_000, 256
        rows = list(zip(rng.integers(0, 2_000, n_rows).tolist(),
                        rng.integers(0, n_partitions, n_rows).tolist(),
                        rng.uniform(0.0, 5.0, n_rows).tolist()))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=0.0, max_value=5.0)

        def spec(seed):
            return JobSpec(params=params, epsilon=1.0, delta=1e-6,
                           noise_seed=seed)

        was_traced = rt_trace.enabled()
        rt_trace.enable()  # the jit probe behind the reuse counts
        try:
            # aot=True: the warm jobs dispatch through the process-wide
            # executable cache — service_aot_retraces measures the AOT
            # compiles the identical-spec REUSE jobs added on their own
            # health records (0 = every tenant after the warm job
            # executed with zero Python retraces).
            with DPAggregationService(pdp.TPUBackend(aot=True),
                                      max_concurrent_jobs=4,
                                      queue_timeout_s=600.0) as svc:
                # Warm job: compiles the shared entry points once.
                svc.submit("tenant-0", spec(0), rows).result(timeout=600)
                handles = []
                start = time.perf_counter()
                for j in range(8):
                    handles.append(
                        svc.submit(f"tenant-{j % 3}", spec(j + 1), rows))
                for handle in handles:
                    handle.result(timeout=600)
                elapsed = time.perf_counter() - start
                latencies = sorted(h.latency_s for h in handles)
                reuse_misses = sum(h.jit_cache_misses or 0
                                   for h in handles)
                from pipelinedp_tpu.runtime import health as rt_health
                aot_retraces = sum(
                    rt_health.for_job(h.job_id).snapshot()
                    ["counters"].get("aot_cache_misses", 0)
                    for h in handles)
                reconciled = svc.ledgers_reconciled()
        finally:
            if not was_traced:
                rt_trace.disable()
        return {
            "service": {
                "service_jobs_per_sec": round(len(handles) / elapsed, 2),
                "service_p50_job_latency_s": round(
                    latencies[len(latencies) // 2], 4),
                "service_p99_job_latency_s": round(
                    latencies[min(len(latencies) - 1,
                                  int(len(latencies) * 0.99))], 4),
                "service_compile_reuse_misses": reuse_misses,
                # AOT compiles added by the 8 identical-spec reuse jobs
                # on their own job records (the warm job paid them all).
                "service_aot_retraces": aot_retraces,
                "service_ledger_reconciled": reconciled,
                "service_jobs": len(handles) + 1,
                "service_tenants": 3,
            }
        }
    except Exception as e:  # noqa: BLE001 - the receipt must survive service-bench breakage; tests/test_service.py owns failing on it
        return {"service": {"error": f"{type(e).__name__}: {e}"}}


def _bench_megabatch(on_tpu):
    """`megabatch` receipt key: the coalescing execution tier under a
    sustained open-loop micro-job load — the regime the per-job path is
    worst at (many small identical-spec jobs, per-launch overhead
    dominating compute). The load is N pre-encoded 64-row columnar
    micro-jobs (a serving front-end hands the service ready payloads;
    `columnar.encode` passes EncodedData through untouched), all with
    one spec fingerprint and one shape class so the coalescer can fill
    whole lane buckets. The same saturated queue drains twice over the
    same worker pool: per-job (batching=False, N release launches) and
    megabatched (batching=True, ~N/max_batch_jobs launches); each path
    takes its best of three trials — on a shared box the open-loop
    drain rate is scheduler-noisy and the max is the honest capacity
    figure. The receipt reports jobs/sec and p50/p99 job latency for
    both paths, the speedup, mean batch occupancy, release launches per
    N jobs, and the single-row-job floor (the latency of the smallest
    possible warm solo job — the fixed cost a batch lane amortizes).

    Note the CPU-backend caveat: with XLA on host cores, kernel
    *execution* releases the GIL and overlaps the host-side work of
    other workers in BOTH paths, so the measured speedup reflects only
    the amortized per-launch dispatch CPU, not the launch-rate ceiling
    a device-queue backend sees. On a real TPU the per-launch cost the
    batch amortizes (dispatch + device round-trip) is the dominant term
    this bench is sized to expose.
    """
    import numpy as np

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import columnar
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.service import DPAggregationService, JobSpec

    try:
        n_jobs, n_rows, workers, lanes, trials = 96, 64, 16, 16, 3
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=0.0, max_value=5.0)

        def job_cols(seed):
            # Every job covers the same 48 partition keys (plus a
            # random tail) so all jobs share one distinct-partition
            # bucket: the timed region re-dispatches ONE compiled
            # program instead of compiling per partition-count.
            r = np.random.default_rng(seed)
            pk = np.concatenate(
                [np.arange(48), r.integers(0, 48, n_rows - 48)])
            pid = np.concatenate(
                [np.arange(48) % 200, r.integers(0, 200, n_rows - 48)])
            return columnar.encode_columns(
                pid, pk, r.uniform(0.0, 5.0, n_rows))

        # Payloads are pre-encoded OUTSIDE the timed region: the bench
        # measures the service drain rate, not numpy data generation.
        data = {i: job_cols(i) for i in range(n_jobs)}
        warm_data = {i: job_cols(10_000 + i) for i in range(workers)}

        def spec(seed):
            return JobSpec(params=params, epsilon=1.0, delta=1e-6,
                           noise_seed=seed)

        def run_load(batching):
            with DPAggregationService(pdp.TPUBackend(),
                                      max_concurrent_jobs=workers,
                                      queue_timeout_s=600.0,
                                      batching=batching,
                                      batch_window_ms=100.0,
                                      max_batch_jobs=lanes) as svc:
                # Warm round: compiles the (lane-stacked) kernels for
                # this shape class so the timed trials measure steady
                # state, not first-compile. The batched warm round
                # fills a whole lane bucket.
                warm = [svc.submit(f"w{i}", spec(900 + i), warm_data[i])
                        for i in range(workers if batching else 2)]
                for h in warm:
                    h.result(timeout=600)
                best = None
                for trial in range(trials):
                    before = rt_telemetry.snapshot()
                    start = time.perf_counter()
                    # Open loop: the whole load submitted up front — a
                    # saturated admission queue; jobs/sec is the drain
                    # rate.
                    handles = [svc.submit(f"tenant-{i % 3}",
                                          spec(trial * 1000 + i),
                                          data[i])
                               for i in range(n_jobs)]
                    for h in handles:
                        h.result(timeout=600)
                    elapsed = time.perf_counter() - start
                    delta = rt_telemetry.delta(before)
                    jps = n_jobs / elapsed
                    if best is None or jps > best[0]:
                        best = (jps, delta,
                                sorted(h.latency_s for h in handles))
                reconciled = svc.ledgers_reconciled()
            jps, delta, latencies = best
            batch_launches = delta.get("service_batch_launches", 0)
            jobs_batched = delta.get("service_jobs_batched", 0)
            return {
                "jobs_per_sec": round(jps, 2),
                "p50_s": round(latencies[len(latencies) // 2], 4),
                "p99_s": round(latencies[min(len(latencies) - 1,
                                             int(len(latencies) * 0.99))],
                               4),
                # Per-N-jobs release launches: batched lanes share one,
                # unbatched jobs pay their own.
                "launches": batch_launches + (n_jobs - jobs_batched),
                "batch_launches": batch_launches,
                "jobs_batched": jobs_batched,
                "occupancy": round(jobs_batched / batch_launches, 2)
                             if batch_launches else 0.0,
                "reconciled": reconciled,
            }

        per_job = run_load(batching=False)
        batched = run_load(batching=True)

        # The floor: a warm single-row job, solo — the fixed per-job
        # cost (admission, graph build, encode, ONE launch, decode,
        # ledger) that megabatching amortizes across lanes.
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1,
                                  queue_timeout_s=600.0) as svc:
            one_row = [(0, 1, 1.0)]
            svc.submit("floor", spec(7001), one_row).result(timeout=600)
            h = svc.submit("floor", spec(7002), one_row)
            h.result(timeout=600)
            floor_s = h.latency_s

        return {
            "megabatch": {
                "service_jobs_per_sec": batched["jobs_per_sec"],
                "service_p50_job_latency_s": batched["p50_s"],
                "service_p99_job_latency_s": batched["p99_s"],
                "service_jobs_per_sec_per_job_path":
                    per_job["jobs_per_sec"],
                "service_p50_job_latency_s_per_job_path":
                    per_job["p50_s"],
                "service_p99_job_latency_s_per_job_path":
                    per_job["p99_s"],
                "megabatch_speedup": round(
                    batched["jobs_per_sec"] /
                    max(per_job["jobs_per_sec"], 1e-9), 2),
                "megabatch_occupancy_mean": batched["occupancy"],
                "megabatch_jobs_batched": batched["jobs_batched"],
                # N jobs -> how many release launches each path paid.
                "launches_per_%d_jobs_batched" % n_jobs:
                    batched["launches"],
                "launches_per_%d_jobs_per_job_path" % n_jobs:
                    per_job["launches"],
                "single_row_job_floor_s": round(floor_s, 4),
                "megabatch_ledgers_reconciled": (per_job["reconciled"]
                                                 and
                                                 batched["reconciled"]),
                "megabatch_jobs": n_jobs,
                "megabatch_lane_cap": lanes,
            }
        }
    except Exception as e:  # noqa: BLE001 - the receipt must survive megabatch-bench breakage; tests/test_service_batching.py owns failing on it
        return {"megabatch": {"error": f"{type(e).__name__}: {e}"}}


def _bench_fleet(on_tpu):
    """`fleet` receipt key: the fleet-operations plane timed end to end.
    A mini elastic scale-UP (half the attached devices grow to the full
    set at a block boundary, outputs bit-compared against the
    fixed-geometry run), a drain-and-migrate (journaled run interrupted,
    adopted into a new controller scope, resumed — blocks replayed from
    the journal, migration counted once), and a 2-wave rolling-restart
    drill with one mid-persist kill. The correctness gates live in
    tier-1 (tests/test_fleet.py, tests/test_multihost.py); the receipt
    reports the wall time each operation costs and the counter deltas a
    fleet controller would watch."""
    import numpy as np

    import jax

    import pipelinedp_tpu as pdp
    from benchmarks import _common
    from pipelinedp_tpu.parallel import large_p, make_mesh
    from pipelinedp_tpu.runtime import BlockJournal
    from pipelinedp_tpu.runtime import drill as rt_drill
    from pipelinedp_tpu.runtime import faults as rt_faults
    from pipelinedp_tpu.runtime import observability as rt_obs
    from pipelinedp_tpu.runtime import retry as rt_retry
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.service import JobSpec

    try:
        n_dev = len(jax.devices())
        P = 1 << 12
        block = 1 << 10
        _, cfg, stds, (min_v, max_v, min_s, max_s, mid) = \
            _common.build_spec(P)
        # Placement-independent integer rows (one row per privacy id,
        # integer values): bounding drops nothing and per-shard partial
        # sums are exact, so the bit-identity verdicts below are
        # geometry-proof — the same construction tests/test_fleet.py
        # gates on.
        dense_parts = (np.arange(12, dtype=np.int64) * 239 + 57) % P
        n_per = 120
        pid = (np.repeat(np.arange(n_per), 12) * 1_000_003 +
               np.tile(np.arange(12), n_per)).astype(np.int32)
        pk = np.tile(dense_parts, n_per).astype(np.int32)
        values = np.random.default_rng(7).integers(
            0, 6, len(pk)).astype(np.float64)
        valid = np.ones(len(pid), bool)
        key = jax.random.PRNGKey(97)
        fast = rt_retry.RetryPolicy(max_retries=2, base_delay=0.0,
                                    max_delay=0.0)

        def run(mesh, **kw):
            return large_p.aggregate_blocked_sharded(
                mesh, pid, pk, values, valid, min_v, max_v, min_s,
                max_s, mid, stds, key, cfg, block_partitions=block,
                **kw)

        out: dict = {"fleet_devices": n_dev}
        before = rt_telemetry.snapshot()

        # Mini scale-UP: half the devices grow to the full set. A
        # single attached chip has nothing to admit — skip, keep keys.
        if n_dev >= 2:
            half = n_dev // 2
            base_kept, base_out = run(make_mesh(n_devices=half))
            rt_retry.announce_join(n_devices=n_dev, block=2)
            try:
                start = time.perf_counter()
                kept_g, out_g = run(make_mesh(n_devices=half),
                                    retry=fast, elastic_grow=True,
                                    job_id="bench-fleet-grow")
                grow_s = time.perf_counter() - start
            finally:
                rt_retry.clear_joins()
            out["fleet_grow_devices"] = f"{half}->{n_dev}"
            out["fleet_grow_sec"] = round(grow_s, 3)
            out["fleet_grow_bit_identical"] = bool(
                np.array_equal(base_kept, kept_g) and all(
                    np.array_equal(np.asarray(base_out[k]),
                                   np.asarray(out_g[k]))
                    for k in ("count", "sum")))
        else:
            base_kept, base_out = run(make_mesh(n_devices=n_dev))
            out["fleet_grow_skipped"] = "single device — nothing to admit"

        # Drain-and-migrate: interrupt at block 2, adopt, resume.
        with tempfile.TemporaryDirectory() as tmp:
            source = BlockJournal(tmp).scoped_to_process(0)
            sched = rt_faults.FaultSchedule(
                [rt_faults.Fault("fatal", block=2)])
            with rt_faults.inject(sched):
                try:
                    run(make_mesh(n_devices=max(1, n_dev // 2)),
                        journal=source, retry=fast,
                        job_id="bench-fleet-migrate")
                except rt_faults.InjectedFatalError:
                    pass
            rt_obs.persist_odometer(source, "bench-fleet-migrate")
            target = BlockJournal(tmp).scoped_to_process(1)
            start = time.perf_counter()
            adopted = target.adopt_job("bench-fleet-migrate")
            kept_m, out_m = run(make_mesh(n_devices=n_dev),
                                journal=target, retry=fast,
                                job_id="bench-fleet-migrate")
            migrate_s = time.perf_counter() - start
            out["fleet_migrate_adopted_blocks"] = int(adopted)
            out["fleet_migrate_odometer_records"] = len(
                rt_obs.load_odometer(target, "bench-fleet-migrate"))
            out["fleet_migrate_resume_sec"] = round(migrate_s, 3)
            out["fleet_migrate_bit_identical"] = bool(
                np.array_equal(base_kept, kept_m) and all(
                    np.array_equal(np.asarray(base_out[k]),
                                   np.asarray(out_m[k]))
                    for k in ("count", "sum")))

        # 2-wave rolling-restart drill, one mid-persist kill.
        rows = [("u1", "A", 1.0), ("u1", "B", 2.0), ("u2", "A", 1.0),
                ("u2", "B", 3.0)]
        ex = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_value=0.0, max_value=5.0)

        def spec(seed):
            return JobSpec(params=params, epsilon=1.0, delta=1e-6,
                           data_extractors=ex, noise_seed=seed,
                           public_partitions=["A", "B"])

        jobs = [rt_drill.LogicalJob(f"drill-j{i}",
                                    "acme" if i % 2 else "beta",
                                    spec(23 + i), rows)
                for i in range(4)]
        with tempfile.TemporaryDirectory() as tmp:
            start = time.perf_counter()
            report = rt_drill.rolling_restart_drill(jobs, tmp, waves=2)
            drill_s = time.perf_counter() - start
        out["fleet_drill_sec"] = round(drill_s, 3)
        out["fleet_drill_zero_loss"] = bool(report["zero_loss"])
        out["fleet_drill_bounces"] = int(report["bounces"])
        out["fleet_drill_injected_failures"] = int(
            report["injected_failures"])
        out["fleet_drill_resubmissions"] = int(report["resubmissions"])

        delta = rt_telemetry.delta(before)
        out["fleet_counters"] = {
            name: delta.get(name, 0)
            for name in ("mesh_expansions", "job_migrations",
                         "rolling_restarts", "journal_replays")
        }
        return {"fleet": out}
    except Exception as e:  # noqa: BLE001 - the receipt must survive fleet-bench breakage; tests/test_fleet.py owns failing on it
        return {"fleet": {"error": f"{type(e).__name__}: {e}"}}


def _bench_chaos(on_tpu):
    """`chaos` receipt key: the chaos-campaign engine timed end to end.
    A small seeded campaign (3 trials, intensity 0.6) runs composed
    fault schedules through the service + journaled-driver workload
    with the full invariant check per trial; the receipt reports the
    wall time a trial costs, what fired, and the storage-seam counter
    deltas. The correctness gates live in tier-1 (tests/test_chaos.py);
    a receipt with invariants_hold=false flags the run loudly."""
    import tempfile
    import time

    from pipelinedp_tpu.runtime import chaos as rt_chaos
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry

    try:
        campaign = rt_chaos.ChaosCampaign(seed=3, trials=3,
                                          intensity=0.6)
        before = rt_telemetry.snapshot()
        with tempfile.TemporaryDirectory() as tmp:
            start = time.perf_counter()
            report = rt_chaos.run_campaign(campaign, tmp)
            chaos_s = time.perf_counter() - start
        delta = rt_telemetry.delta(before)
        return {"chaos": {
            "campaign_seed": report["campaign_seed"],
            "trials": report["trials"],
            "intensity": report["intensity"],
            "total_sec": round(chaos_s, 3),
            "sec_per_trial": round(chaos_s / report["trials"], 3),
            "total_firings": report["total_firings"],
            "fired": report["fired"],
            "bounces": report["bounces"],
            "resubmissions": report["resubmissions"],
            "storage_sheds": report["sheds"],
            "jobs_completed": report["jobs_completed"],
            "invariants_hold": report["invariants_hold"],
            "counters": {
                name: delta.get(name, 0)
                for name in ("chaos_trials", "chaos_invariant_failures",
                             "storage_disk_full",
                             "storage_fsync_failures",
                             "storage_io_errors", "storage_unavailable")
            },
        }}
    except Exception as e:  # noqa: BLE001 - the receipt must survive chaos-bench breakage; tests/test_chaos.py owns failing on it
        return {"chaos": {"error": f"{type(e).__name__}: {e}"}}


def _bench_numeric(on_tpu):
    """`numeric` receipt key: the numeric-armor arc priced.

    Three figures: the warm fused-release cost of numeric_mode="safe"
    relative to the default path on identical rows (what compensated
    accumulation charges); the accumulation error against a float64
    oracle on a 1M-row integer-valued stream — sequential f32, XLA's
    log-depth f32 scan, and the compensated scan, in f32 ULPs at the
    oracle; and the per-draw cost of the floating-point-safe noise
    (snapped Laplace + geometric). Correctness gates live in tier-1
    (tests/test_numeric_armor.py); this receipt says what the armor
    costs."""
    import dataclasses
    import time

    import numpy as np

    import jax
    import jax.numpy as jnp

    from benchmarks import _common
    from pipelinedp_tpu import dp_computations as dp
    from pipelinedp_tpu import executor
    from pipelinedp_tpu.ops import segment_ops

    try:
        # --- safe vs fast: the dense fused release, warm. ---
        n = 2**20 if on_tpu else 2**17
        n_partitions = 1 << 12
        _, cfg, stds, (min_v, max_v, min_s, max_s, mid) = \
            _common.build_spec(n_partitions)
        pid, pk, values, valid = _common.zipfish_data(n, n_partitions)
        key = jax.random.PRNGKey(3)

        def run(cfg_):
            out = executor.aggregate_release_kernel(
                pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                stds, key, cfg_)
            return jax.block_until_ready(out)

        def timed(cfg_):
            run(cfg_)  # compile
            start = time.perf_counter()
            run(cfg_)
            return time.perf_counter() - start

        fast_s = timed(cfg)
        safe_s = timed(dataclasses.replace(cfg, numeric_mode="safe"))

        # --- accumulation error vs a float64 oracle at 1M rows:
        # sequential f32 (the classic running accumulator), XLA's
        # log-depth f32 scan (the fast path's shape), and the
        # compensated scan (the safe path). ULPs at the oracle. ---
        m = 1 << 20
        rng = np.random.default_rng(7)
        x = rng.integers(0, 1 << 22, m).astype(np.float32)
        xj = jnp.asarray(x)
        oracle = float(np.cumsum(x.astype(np.float64))[-1])
        seq = float(np.cumsum(x)[-1])
        xla = float(np.asarray(jnp.cumsum(xj, dtype=xj.dtype))[-1])
        hi, lo = segment_ops.compensated_cumsum(xj)
        starts = jnp.asarray([0, m], dtype=jnp.int32)
        comp = float(np.asarray(
            segment_ops.compensated_segment_diff(hi, lo, starts))[0])
        ulp = float(np.spacing(np.float32(oracle)))

        # --- floating-point-safe noise draw cost (threefry-keyed,
        # scalar release path — the per-draw price the host pays). ---
        draws = 500
        snap = dp.SnappedLaplaceMechanism(1.0, 1.0,
                                          key=jax.random.PRNGKey(9))
        start = time.perf_counter()
        for v in range(draws):
            snap.add_noise(float(v))
        snap_s = time.perf_counter() - start
        geo = dp.GeometricMechanism(1.0, 1, key=jax.random.PRNGKey(10))
        start = time.perf_counter()
        for v in range(draws):
            geo.add_noise(v)
        geo_s = time.perf_counter() - start

        return {"numeric": {
            "rows": n,
            "fast_sec": round(fast_s, 4),
            "safe_sec": round(safe_s, 4),
            "safe_vs_fast": round(safe_s / fast_s, 3),
            "cumsum_rows": m,
            "sequential_f32_error_ulps": round(abs(seq - oracle) / ulp, 1),
            "xla_scan_f32_error_ulps": round(abs(xla - oracle) / ulp, 2),
            "compensated_error_ulps": round(abs(comp - oracle) / ulp, 2),
            "snap_grid": snap.grid,
            "snapped_laplace_draws_per_sec": round(draws / snap_s),
            "geometric_draws_per_sec": round(draws / geo_s),
        }}
    except Exception as e:  # noqa: BLE001 - the receipt must survive numeric-bench breakage; tests/test_numeric_armor.py owns failing on it
        return {"numeric": {"error": f"{type(e).__name__}: {e}"}}


def _bench_pld(on_tpu):
    """`pld` receipt key: the fast-composition engine priced.

    Four figures: the one-shot batched frequency-domain composition vs
    the sequential pairwise chain at k=1000 heterogeneous mechanisms
    (compositions/sec both ways — the >=10x acceptance bar); the
    epsilon a tenant gets back from PLD composition at k=100 identical
    Gaussian jobs (naive sum / composed epsilon); the spectrum-cache
    hit rate over a 3-tenant identical-spec run; and the admission
    capacity multiplier — jobs admitted on ONE fixed tenant budget
    under pld vs naive accounting. Correctness gates live in tier-1
    (tests/test_pld_compose.py); this receipt says what the engine
    buys."""
    import time

    import numpy as np

    from pipelinedp_tpu import dp_computations as dpc
    from pipelinedp_tpu.accounting import compose as eng
    from pipelinedp_tpu.accounting import pld as pldlib
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime.journal import BlockJournal
    from pipelinedp_tpu.service.errors import TenantBudgetExceededError
    from pipelinedp_tpu.service.ledger import TenantLedger

    try:
        # --- batched vs sequential pairwise at k=1000 heterogeneous
        # mechanisms (8 distinct Gaussian scales x 125 each; 1e-2 grid
        # keeps the sequential chain's quadratic cost sufferable). ---
        disc = 1e-2
        scales = [0.8 + 0.15 * i for i in range(8)]
        plds = [pldlib.from_gaussian_mechanism(s, disc) for s in scales]
        counts = [125] * len(scales)
        k_total = sum(counts)
        start = time.perf_counter()
        batched = eng.compose_plds(plds, counts)
        batched_s = time.perf_counter() - start
        start = time.perf_counter()
        seq = None
        for p, c in zip(plds, counts):
            for _ in range(c):
                seq = p if seq is None else seq.compose(p)
        sequential_s = time.perf_counter() - start
        parity = float(np.max(np.abs(batched.probs - seq.probs)))

        # --- epsilon saved at k=100 identical Gaussian jobs: the naive
        # sum of shares vs the composed epsilon at the same delta. ---
        eps_j, delta_j = 0.05, 1e-8
        std = dpc.gaussian_sigma(eps_j, delta_j, 1.0)
        record = {"mechanism_kind": "MechanismType.GAUSSIAN",
                  "eps": eps_j, "delta": delta_j, "sensitivity": 1.0,
                  "count": 1, "noise_std": std}
        composed_eps, _ = eng.composed_epsilon_from_records(
            [record] * 100, discretization=1e-3)
        saved_ratio = (100 * eps_j) / composed_eps

        # --- spectrum-cache hit rate over a 3-tenant identical-spec
        # run: each tenant charges the same mechanism spec, so only the
        # first rebuild discretizes. ---
        eng.CACHE.clear()  # hit rate measured from a cold cache
        before = rt_telemetry.snapshot()
        for tenant in ("bench-t1", "bench-t2", "bench-t3"):
            led = TenantLedger(tenant, 10.0, BlockJournal(None),
                               accounting_mode="pld",
                               pld_discretization=1e-3)
            for i in range(4):
                job = f"{tenant}--j{i + 1}"
                led.reserve(job, eps_j)
                led.charge(job, [dict(record, seq=0, job_id=None,
                                      metric="count", weight=1.0,
                                      process_index=0)])
            led.pld_spent_epsilon()
        diff = rt_telemetry.delta(before)
        hits = diff.get("pld_cache_hits", 0)
        misses = diff.get("pld_cache_misses", 0)
        hit_rate = hits / (hits + misses) if hits + misses else 0.0

        # --- admission capacity multiplier: jobs admitted on one fixed
        # budget, naive vs pld (capped — the pld ledger would admit far
        # past the floor the receipt needs to show). ---
        budget, cap = 2.0, 200

        def admitted(mode):
            led = TenantLedger(f"bench-cap-{mode}", budget,
                               BlockJournal(None), accounting_mode=mode,
                               pld_discretization=1e-3)
            n = 0
            while n < cap:
                job = f"bench-cap-{mode}--j{n + 1}"
                try:
                    led.reserve(job, eps_j)
                except TenantBudgetExceededError:
                    break
                led.charge(job, [dict(record, seq=0, job_id=None,
                                      metric="count", weight=1.0,
                                      process_index=0)])
                n += 1
            return n

        n_naive = admitted("naive")
        n_pld = admitted("pld")

        return {"pld": {
            "k_mechanisms": k_total,
            "batched_sec": round(batched_s, 4),
            "sequential_sec": round(sequential_s, 4),
            "pld_compositions_per_sec": {
                "batched": round(k_total / batched_s),
                "sequential": round(k_total / sequential_s),
            },
            "batched_speedup": round(sequential_s / batched_s, 1),
            "batched_vs_pairwise_parity": parity,
            "pld_epsilon_saved_ratio": round(saved_ratio, 3),
            "pld_cache_hit_rate": round(hit_rate, 3),
            "jobs_admitted_naive": n_naive,
            "jobs_admitted_pld": n_pld,
            "pld_admission_capacity_multiplier": round(n_pld / n_naive, 2),
        }}
    except Exception as e:  # noqa: BLE001 - the receipt must survive pld-bench breakage; tests/test_pld_compose.py owns failing on it
        return {"pld": {"error": f"{type(e).__name__}: {e}"}}


def _bench_select_partitions(jax, on_tpu):
    """Standalone DP partition selection at P = 10^7 via the O(kept)
    blocked route (parallel/large_p.select_partitions_blocked): neither a
    dense count vector nor a bool[P] keep vector exists on device or
    host."""
    from benchmarks import _common
    from pipelinedp_tpu.parallel import large_p

    P = 10_000_000
    n = 2**22 if on_tpu else 2**18
    params, _, _, _ = _common.build_spec(P)
    selection = _common.build_selection(params)
    pid, pk, _, valid = _common.zipfish_data(n, P)

    def run(seed):
        return large_p.select_partitions_blocked(
            pid, pk, valid, jax.random.PRNGKey(seed),
            params.max_partitions_contributed, P, selection,
            block_partitions=1 << 20)

    run(8)  # warm the pass-1 + block kernels
    start = time.perf_counter()
    kept = run(9)
    elapsed = time.perf_counter() - start
    return {
        "select_partitions_p": P,
        "select_partitions_rows": n,
        "select_partitions_sec": round(elapsed, 3),
        "select_partitions_rows_per_sec": round(n / elapsed),
        "select_partitions_kept": int(len(kept)),
    }


def _device_zipfish(jax, jnp, n, n_partitions, n_users):
    """Device-side synthetic rows: exponentially-tilted partition
    popularity, uniform users — benchmarks/_common.zipfish_data's
    on-device twin, generated in HBM so device benchmarks never pay a
    host upload. Returns a jitted key -> (pid, pk, values, valid)."""

    @jax.jit
    def make(k):
        kp, ku, kv = jax.random.split(k, 3)
        u = jax.random.uniform(kp, (n,))
        pk = (jnp.power(u, 3.0) * n_partitions).astype(jnp.int32)
        pid = jax.random.randint(ku, (n,), 0, n_users, dtype=jnp.int32)
        values = jax.random.uniform(kv, (n,), minval=0.0, maxval=5.0)
        return pid, pk, values, jnp.ones((n,), bool)

    return make


def _bench_baseline_configs(jax, jnp, on_tpu):
    """BASELINE.md configs 1-3, measured (the reference publishes no
    numbers — BASELINE.json `published: {}` — so these are the reference
    points its table lists as 'TBD (measure)').

    Config 1: movie_view_ratings-shaped COUNT on LocalBackend, the
    reference's own host execution model
    (/root/reference/examples/movie_view_ratings/run_without_frameworks.py:1).
    Config 2: SUM+MEAN, Gaussian mechanism, public partitions.
    Config 3: CompoundCombiner COUNT+SUM+PRIVACY_ID_COUNT, private
    selection (/root/reference/pipeline_dp/combiners.py CompoundCombiner).
    """
    import pipelinedp_tpu as pdp
    from benchmarks import _common
    from pipelinedp_tpu import executor
    detail = {}

    # --- Config 1: LocalBackend COUNT (the CPU ground-truth engine). ----
    n1 = 200_000 if on_tpu else 50_000
    rng = np.random.default_rng(0)
    rows = list(
        zip(rng.integers(0, 10_000, n1).tolist(),
            rng.integers(0, 500, n1).tolist()))
    acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
    engine = pdp.DPEngine(acc, pdp.LocalBackend())
    params1 = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                  noise_kind=pdp.NoiseKind.LAPLACE,
                                  max_partitions_contributed=4,
                                  max_contributions_per_partition=8)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: 1.0)
    start = time.perf_counter()
    result = engine.aggregate(rows, params1, extractors)
    acc.compute_budgets()
    kept1 = sum(1 for _ in result)
    elapsed = time.perf_counter() - start
    detail["config1_local_count_rows"] = n1
    detail["config1_local_count_rows_per_sec"] = round(n1 / elapsed)
    detail["config1_local_count_kept"] = kept1

    # --- Configs 2 and 3: device kernel variants on shared data. --------
    P = 4096
    n = 2**24 if on_tpu else 2**18
    key = jax.random.PRNGKey(0)
    data = _device_zipfish(jax, jnp, n, P, 1_000_000)(key)
    _ = float(data[0][0])  # sync (block_until_ready no-ops over the tunnel)

    def timed_kernel(metrics, noise_kind, private, tag):
        _, cfg, stds, (min_v, max_v, min_s, max_s, mid) = \
            _common.build_spec(P, metrics=metrics, noise_kind=noise_kind,
                               private=private)

        def step(k):
            return executor.aggregate_kernel(*data, min_v, max_v, min_s,
                                             max_s, mid, jnp.asarray(stds),
                                             k, cfg)

        outputs, _, _ = step(jax.random.fold_in(key, 1))
        first = next(iter(outputs))
        _ = float(outputs[first][0])  # warm + sync
        start = time.perf_counter()
        outputs, keep, _ = step(jax.random.fold_in(key, 2))
        _ = float(outputs[first][0])
        elapsed = time.perf_counter() - start
        detail[f"{tag}_rows"] = n
        detail[f"{tag}_rows_per_sec"] = round(n / elapsed)
        detail[f"{tag}_outputs"] = sorted(outputs)

    timed_kernel([pdp.Metrics.SUM, pdp.Metrics.MEAN],
                 pdp.NoiseKind.GAUSSIAN, False,
                 "config2_gaussian_public_sum_mean")
    timed_kernel([pdp.Metrics.COUNT, pdp.Metrics.SUM,
                  pdp.Metrics.PRIVACY_ID_COUNT],
                 pdp.NoiseKind.LAPLACE, True,
                 "config3_compound_private")
    return detail


# Span names whose exclusive time is device-side work (or the wait for
# it): the fused-kernel dispatch/drain pair, the streaming accumulator's
# append/grow, and every probed jit entry point.
_DEVICE_SPANS = ("dispatch", "drain", "pipeline_append", "pipeline_grow")


def _probed_dispatches(summary):
    """Device-dispatch events in a trace summary: every jit:* (traced
    dispatch) and aot:* (cached-executable dispatch) entry-point call,
    plus every pipeline_append (one host->device chunk landing — the
    staged CPU accumulator dispatches transfers, not jit calls, so the
    probe alone would under-count the ingest half). THE dispatch bill
    of a warm run — what the fused release kernels, the batched appends
    and the AOT cache exist to shrink."""
    return sum(stats["count"]
               for name, stats in summary.get("spans", {}).items()
               if name.startswith(("jit:", "aot:")) or
               name == "pipeline_append")


def _overlap_efficiency(summary, total_s):
    """Device-busy fraction of a pipelined run, from span exclusive
    times: the share of total wall time spent in device-side spans
    (dispatch/drain/append/grow + jit:* probes). 1.0 means the device
    never waited on host encode — the streaming executor's target; the
    serial path's value is bounded by the host-encode share. Worker
    -thread encode spans run on their own threads, so they do NOT
    deflate this figure — overlap shows up as device spans covering
    wall time that a serial run would spend blocked in `ingest`."""
    if not total_s:
        return None
    busy = sum(stats["exclusive_s"]
               for name, stats in summary["spans"].items()
               if name in _DEVICE_SPANS or name.startswith("jit:"))
    return round(min(busy / total_s, 1.0), 4)


def _phase_breakdown(summary, total_s):
    """e2e phase breakdown from a trace summary: exclusive (self) wall
    seconds per span name. Every span in the traced run nests under the
    e2e root span, so the exclusive times PARTITION the root's inclusive
    time — the per-phase seconds reconcile against total wall time by
    construction (the residual is host time between instrumented
    stages, reported as unattributed_s, plus clock skew)."""
    phases = {
        name: round(stats["exclusive_s"], 4)
        for name, stats in summary["spans"].items()
    }
    attributed = sum(phases.values())
    return {
        "total_wall_s": round(total_s, 4),
        "phases": phases,
        "attributed_s": round(attributed, 4),
        "unattributed_s": round(max(total_s - attributed, 0.0), 4),
        "attributed_frac": (round(attributed / total_s, 4)
                            if total_s else None),
        "transfer_bytes": summary["transfer_bytes"],
        "compile": summary["compile"],
    }


def _bench_end_to_end(on_tpu):
    """File -> DP result on the Netflix-format path: chunked parse ->
    incremental factorize -> overlapped upload (pipelinedp_tpu.ingest) ->
    fused kernel. The honest whole-pipeline number the kernel-only figure
    above excludes (host encode at ~3.5M rows/s on the 1-core host bounds
    it; the overlap hides the device-transfer term).

    The WARM run executes with tracing enabled under an "e2e" root span:
    the receipt gains e2e_phase_breakdown (per-phase exclusive seconds
    that reconcile against total wall time, with transfer-byte and jit
    compile attribution) and trace_summary, and the full Perfetto trace
    is dumped next to the system tempdir — the decomposition of the
    kernel-vs-end-to-end gap the ROADMAP's engine-pipeline refactor will
    be judged against."""
    import os
    import tempfile

    import pipelinedp_tpu as pdp
    from examples.movie_view_ratings import netflix_format
    from pipelinedp_tpu import ingest
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    from pipelinedp_tpu.runtime import trace as rt_trace

    n = 8_000_000 if on_tpu else 400_000
    path = os.path.join(tempfile.mkdtemp(), "views.txt")
    netflix_format.generate_file(path, n, n_users=200_000, n_movies=4000)

    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                          pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=4,
                                 max_contributions_per_partition=8,
                                 min_value=0.0,
                                 max_value=5.0)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])

    def run_once():
        start = time.perf_counter()
        chunk_iter = ((u, m, r.astype(np.float32)) for u, m, r in
                      netflix_format.parse_file_chunks(path))
        encoded = ingest.stream_encode_columns(chunk_iter)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.TPUBackend(noise_seed=13))
        result = engine.aggregate(encoded, params, extractors)
        accountant.compute_budgets()
        n_kept = sum(1 for _ in result)
        return time.perf_counter() - start, n_kept

    # Cold includes jit compilation of every kernel shape (minutes over the
    # tunnel); warm re-runs the identical shapes against the compile cache
    # and is the steady-state number a long-running pipeline sees.
    cold_sec, n_kept = run_once()
    # Warm run under a fresh trace epoch: spans attribute the steady-state
    # wall time; tracing is restored to its prior state afterwards so the
    # remaining benchmarks measure the untraced hot path.
    rt_trace.reset()
    with rt_trace.scoped():
        with rt_trace.span("e2e"):
            warm_sec, n_kept_warm = run_once()
        summary = rt_trace.trace_summary()
        trace_path = os.path.join(tempfile.gettempdir(),
                                  "pipelinedp_tpu_e2e_trace.json")
        rt_trace.dump(trace_path)
    breakdown = _phase_breakdown(summary, warm_sec)
    rt_trace.reset()

    # --- Pipelined end-to-end: the device-resident streaming executor
    # (ChunkSource -> thread-pool encode -> bounded staging queue ->
    # donated device accumulator). The serial warm number above stays in
    # the receipt as the comparison baseline. Two warm runs: the first
    # warms the pipeline-specific jit entries (append/grow), the second
    # measures steady state AND proves the persistent compile cache —
    # its jit_cache_misses delta must be 0 (bucketed padding lands every
    # row shape on the bucket the serial warm run already compiled).
    def run_pipelined():
        start = time.perf_counter()
        chunks = ((u, m, r.astype(np.float32)) for u, m, r in
                  netflix_format.parse_file_chunks(path))
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(
            accountant,
            pdp.TPUBackend(noise_seed=13, encode_threads=2))
        result = engine.aggregate(pdp.ChunkSource(chunks), params,
                                  extractors)
        accountant.compute_budgets()
        n_kept = sum(1 for _ in result)
        return time.perf_counter() - start, n_kept

    with rt_trace.scoped():
        pipelined_warm1_sec, _ = run_pipelined()
    rt_trace.reset()
    misses_before = rt_telemetry.snapshot()
    with rt_trace.scoped():
        with rt_trace.span("e2e_pipelined"):
            pipelined_sec, n_kept_pipelined = run_pipelined()
        pipelined_summary = rt_trace.trace_summary()
    second_warm_misses = rt_telemetry.delta(misses_before).get(
        "jit_cache_misses", 0)
    rt_trace.reset()

    # --- Device-resident encode (encode_mode="hash_device") vs the
    # host encoder, same data, both warm. The netflix shape above is
    # the wrong comparator for ENCODE work (integer keys factorize at
    # memcpy speed and file parsing dominates its wall), so this
    # section uses the heavy host-encode shape the streaming dryrun
    # established — composite string keys, a ~300K-entry user
    # vocabulary, fine-grained 4K-row chunks (network-granularity
    # streaming): there the host route's sequential vocabulary stitch
    # (per-chunk remap + index rebuild over the full vocabulary) is the
    # wall the ROADMAP names, and the hash route replaces it with
    # vectorized hashing + in-jit code assignment. Byte-arrival
    # boundary: chunks are pre-materialized raw columns, so both modes
    # time exactly "everything after byte arrival".
    n_de = 800_000 if not on_tpu else 8_000_000
    de_chunk = 4_000
    rng_de = np.random.default_rng(23)
    de_pid = np.char.add(
        np.char.add("user_",
                    rng_de.integers(0, 300_000, n_de).astype(str)),
        np.char.add("_sess", rng_de.integers(0, 3, n_de).astype(str)))
    de_pk = np.char.add("movie_",
                        rng_de.integers(0, 2_000, n_de).astype(str))
    de_vals = rng_de.uniform(0, 5, n_de)

    def de_chunks():
        return [(de_pid[i:i + de_chunk], de_pk[i:i + de_chunk],
                 de_vals[i:i + de_chunk])
                for i in range(0, n_de, de_chunk)]

    def run_encode_mode(mode):
        start = time.perf_counter()
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(
            accountant,
            pdp.TPUBackend(noise_seed=13, encode_threads=2,
                           encode_mode=mode))
        result = engine.aggregate(pdp.ChunkSource(de_chunks()), params,
                                  extractors)
        accountant.compute_budgets()
        n_kept = sum(1 for _ in result)
        return time.perf_counter() - start, n_kept

    run_encode_mode("host")  # compiles for this shape
    host_encode_sec, n_kept_host_enc = run_encode_mode("host")
    run_encode_mode("hash_device")  # warm the hash-route kernels
    misses_before = rt_telemetry.snapshot()
    with rt_trace.scoped():
        with rt_trace.span("e2e_device_encode"):
            device_sec, n_kept_device = run_encode_mode("hash_device")
        device_summary = rt_trace.trace_summary()
    device_second_warm_misses = rt_telemetry.delta(misses_before).get(
        "jit_cache_misses", 0)
    device_breakdown = _phase_breakdown(device_summary, device_sec)
    rt_trace.reset()
    assert n_kept_device == n_kept_host_enc, (
        "device-encode release diverged from the host encode")

    # --- Single-dispatch warm path (PR 14) over the same fine-grained
    # 4K-chunk stream (the shape where per-dispatch overhead is
    # visible). Three warm configurations, identical released bytes
    # (bit-identity asserted in tests/test_aot.py + the dryrun):
    #   legacy    — unfused release, serial drain, per-chunk appends
    #               (the pre-PR14 path; the comparison baseline),
    #   traced    — the default warm path (fused release + overlap +
    #               batched appends) through jit's Python dispatch,
    #   aot       — the default warm path through the AOT executable
    #               cache (.lower().compile(), zero retraces).
    # e2e_dispatch_count counts probed jit:/aot: entry-point calls per
    # warm run; e2e_aot_speedup is traced/aot wall on identical work.
    from pipelinedp_tpu.runtime import pipeline as rt_pipeline_mod

    n_wp = min(n_de, 200_000)
    wp_chunks = [(de_pid[i:i + de_chunk], de_pk[i:i + de_chunk],
                  de_vals[i:i + de_chunk]) for i in range(0, n_wp, de_chunk)]

    def run_warm_path(label, batch_rows, **kw):
        prev_batch = rt_pipeline_mod.APPEND_BATCH_ROWS
        rt_pipeline_mod.APPEND_BATCH_ROWS = batch_rows
        try:
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                                   total_delta=1e-6)
            engine = pdp.DPEngine(
                accountant,
                pdp.TPUBackend(noise_seed=13, encode_threads=2, **kw))
            start = time.perf_counter()
            result = engine.aggregate(pdp.ChunkSource(iter(wp_chunks)),
                                      params, extractors)
            accountant.compute_budgets()
            n_kept = sum(1 for _ in result)
            return time.perf_counter() - start, n_kept
        finally:
            rt_pipeline_mod.APPEND_BATCH_ROWS = prev_batch

    warm_path = {}
    kept_counts = set()
    for label, batch_rows, kw in (
            ("legacy", 0, dict(fused_release=False)),
            ("traced", rt_pipeline_mod.APPEND_BATCH_ROWS,
             dict(overlap_drain=True)),
            ("aot", rt_pipeline_mod.APPEND_BATCH_ROWS,
             dict(aot=True, overlap_drain=True))):
        run_warm_path(label, batch_rows, **kw)  # warm compiles/cache
        with rt_trace.scoped():
            with rt_trace.span("e2e_warm_" + label):
                sec, kept = run_warm_path(label, batch_rows, **kw)
            warm_path[label] = (sec, _probed_dispatches(
                rt_trace.trace_summary()))
        rt_trace.reset()
        kept_counts.add(kept)
    assert len(kept_counts) == 1, (
        f"warm-path configurations diverged: {kept_counts}")
    dispatch_reduction = (warm_path["legacy"][1] /
                          max(warm_path["aot"][1], 1))
    os.unlink(path)
    # Note for cross-round comparisons: rounds <= 4 reported a single
    # compile-inclusive "end_to_end_sec"; that old key corresponds to
    # end_to_end_sec_cold here.
    return {
        "end_to_end_rows": n,
        "end_to_end_sec_cold": round(cold_sec, 3),
        "end_to_end_rows_per_sec_cold": round(n / cold_sec),
        "end_to_end_sec_warm": round(warm_sec, 3),
        "end_to_end_rows_per_sec_warm": round(n / warm_sec),
        "end_to_end_kept_partitions": n_kept_warm,
        "e2e_sec_pipelined": round(pipelined_sec, 3),
        "e2e_sec_pipelined_first_warm": round(pipelined_warm1_sec, 3),
        "e2e_rows_per_sec_pipelined": round(n / pipelined_sec),
        "e2e_overlap_efficiency": _overlap_efficiency(pipelined_summary,
                                                      pipelined_sec),
        "e2e_pipelined_kept_partitions": n_kept_pipelined,
        # 0 == every row shape of the second warm pipelined call hit the
        # persistent compile cache (the bucketed-padding guarantee).
        "e2e_pipelined_second_warm_jit_cache_misses": second_warm_misses,
        # Device-resident ingest (encode_mode="hash_device") vs the
        # host encoder over the SAME heavy-encode stream (composite
        # string keys, 300K-entry vocabulary, 4K-row chunks), both
        # warm; the device-mode phase breakdown shows host
        # encode/factorize is no longer the dominant phase (no host
        # factorization runs at all — "ingest" is hashing + upload,
        # "ingest.device_codes" the in-jit code assignment).
        "e2e_device_encode_rows": n_de,
        "e2e_sec_host_encode": round(host_encode_sec, 3),
        "e2e_rows_per_sec_host_encode": round(n_de / host_encode_sec),
        "e2e_sec_device_encode": round(device_sec, 3),
        "e2e_rows_per_sec_device_encode": round(n_de / device_sec),
        "e2e_device_encode_speedup": round(
            host_encode_sec / device_sec, 2),
        "e2e_device_encode_kept_partitions": n_kept_device,
        "e2e_device_encode_second_warm_jit_cache_misses":
            device_second_warm_misses,
        "e2e_device_encode_phase_breakdown": device_breakdown,
        # Single-dispatch warm path: probed jit:/aot: entry-point calls
        # per warm run over the 4K-chunk stream (legacy = pre-PR14
        # unfused/serial/per-chunk-append path), and the warm wall-clock
        # ratio of the traced vs AOT-executable dispatch of the SAME
        # fused path. Identical released bytes in all three modes.
        "e2e_dispatch_count": {
            "legacy": warm_path["legacy"][1],
            "fused": warm_path["traced"][1],
            "fused_aot": warm_path["aot"][1],
            "reduction": round(dispatch_reduction, 2),
        },
        "e2e_sec_warm_legacy": round(warm_path["legacy"][0], 3),
        "e2e_sec_warm_fused": round(warm_path["traced"][0], 3),
        "e2e_sec_warm_aot": round(warm_path["aot"][0], 3),
        "e2e_aot_speedup": round(
            warm_path["traced"][0] / max(warm_path["aot"][0], 1e-9), 3),
        "e2e_warm_path_speedup": round(
            warm_path["legacy"][0] / max(warm_path["aot"][0], 1e-9), 3),
        "e2e_phase_breakdown": breakdown,
        "trace_summary": {
            "spans": dict(list(summary["spans"].items())[:12]),
            "instants": summary["instants"],
            "n_events": summary["n_events"],
            "dropped_events": summary["dropped_events"],
        },
        "trace_file": trace_path,
    }


def _bench_ingest():
    """Host ingest throughput: raw key columns -> vocab-encoded int arrays
    (columnar.encode_columns, the 1B-row bottleneck flagged in round 2)."""
    from pipelinedp_tpu import columnar
    n = 4_000_000
    rng = np.random.default_rng(3)
    pids = rng.integers(0, 1_000_000, n)
    pks = np.char.add("movie_", rng.integers(0, 100_000, n).astype(str))
    vals = rng.random(n)
    start = time.perf_counter()
    encoded = columnar.encode_columns(pids, pks, vals)
    elapsed = time.perf_counter() - start

    # Fallback path (pandas masked): the vectorized searchsorted remap in
    # ChunkedVocabEncoder, measured host-side on the same columns.
    from pipelinedp_tpu import ingest as ingest_mod
    saved = ingest_mod._pd, columnar._pd
    ingest_mod._pd = columnar._pd = None
    try:
        start = time.perf_counter()
        enc_pid = ingest_mod.ChunkedVocabEncoder()
        enc_pk = ingest_mod.ChunkedVocabEncoder()
        chunk = 1 << 19
        for i in range(0, n, chunk):
            enc_pid.encode(pids[i:i + chunk])
            enc_pk.encode(pks[i:i + chunk])
        fb_elapsed = time.perf_counter() - start
    finally:
        ingest_mod._pd, columnar._pd = saved

    # Device-resident encode: the same columns through the hash-device
    # route (host work = hashing only; factorization runs inside jit).
    # Warm once so the factorize-kernel compile does not bill the
    # steady-state number, then time a full encode to device arrays.
    import jax

    chunk = 1 << 19

    def dev_chunks():
        return [(pids[i:i + chunk], pks[i:i + chunk], vals[i:i + chunk])
                for i in range(0, n, chunk)]

    ingest_mod.stream_encode_columns(dev_chunks(),
                                     encode_mode="hash_device",
                                     encode_threads=2)
    start = time.perf_counter()
    dev_encoded = ingest_mod.stream_encode_columns(
        dev_chunks(), encode_mode="hash_device", encode_threads=2)
    jax.block_until_ready((dev_encoded.pid, dev_encoded.pk))
    dev_elapsed = time.perf_counter() - start
    return {
        "ingest_rows": n,
        "ingest_rows_per_sec": round(n / elapsed),
        "ingest_fallback_rows_per_sec": round(n / fb_elapsed),
        "ingest_device_rows_per_sec": round(n / dev_elapsed),
        "ingest_partitions": encoded.n_partitions,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=200_000_000,
                        help="total synthetic rows to push through")
    parser.add_argument("--chunk", type=int, default=0,
                        help="rows per device chunk (0 = auto)")
    parser.add_argument("--partitions", type=int, default=4096)
    parser.add_argument("--users", type=int, default=1_000_000)
    parser.add_argument("--cpu", action="store_true",
                        help="force CPU (debug)")
    parser.add_argument("--max-wait", type=float, default=480.0,
                        help="max seconds to wait for TPU backend init")
    args = parser.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    # Persistent compilation cache: over a remote-tunneled chip, first
    # compiles cost 30s-minutes per distinct shape; caching them makes
    # retries (and the CPU-failover rerun) start warm. One cache dir
    # shared with the benchmarks/ scripts.
    from benchmarks import _common
    _common.enable_compile_cache()

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import combiners, executor
    from pipelinedp_tpu.aggregate_params import MechanismType
    from pipelinedp_tpu.ops import selection_ops

    if args.cpu:
        device, fallback = jax.devices()[0], None
    else:
        device, fallback = acquire_device(max_wait_sec=args.max_wait)
    on_tpu = device.platform != "cpu"
    if not on_tpu and not args.cpu:
        # CPU fallback: shrink the workload so the diagnostic line appears
        # in seconds, not hours.
        args.rows = min(args.rows, 4_000_000)
    # 16.8M rows/chunk on TPU: the measured optimum of the round-5 sweep
    # (134M rows: 2^23 53.3M, 2^24 60.4M, 2^25 58.3-59.6M, 2^26 55.7M
    # rec/s) — the bounding sort's O(n log n) comparator passes beat
    # per-chunk dispatch overhead above 2^24.
    chunk = args.chunk or (2**24 if on_tpu else 2**20)
    chunk = min(chunk, args.rows)

    # --- Aggregation spec: SUM+COUNT, eps=1, private partition selection. ---
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=4,
                                 max_contributions_per_partition=8,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    selection_budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, selection_budget.eps,
        selection_budget.delta, params.max_partitions_contributed, None)
    cfg = executor.make_kernel_config(params, compound, args.partitions,
                                      private_selection=True,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    min_v, max_v, min_s, max_s, mid = executor.kernel_scalars(params)

    # --- Synthetic data: zipf-ish partition popularity, uniform users. ---
    key = jax.random.PRNGKey(0)
    make_chunk = _device_zipfish(jax, jnp, chunk, args.partitions,
                                 args.users)

    def step(k):
        pid, pk, values, valid = make_chunk(jax.random.fold_in(k, 1))
        return executor.aggregate_kernel(pid, pk, values, valid, min_v, max_v,
                                         min_s, max_s, mid, jnp.asarray(stds),
                                         jax.random.fold_in(k, 2), cfg)

    # Warmup / compile. Synchronization is a host fetch of one output
    # scalar, NOT block_until_ready: under remote-tunneled devices the
    # latter can return at dispatch time and overstate throughput.
    outputs, keep, _ = step(key)
    _ = float(outputs["count"][0])

    n_chunks = max(1, args.rows // chunk)
    start = time.perf_counter()
    results = []
    for i in range(n_chunks):
        results.append(step(jax.random.fold_in(key, i)))
    for outputs, keep, _ in results:
        _ = float(outputs["count"][0])  # forces each chunk's execution
    elapsed = time.perf_counter() - start

    total_rows = n_chunks * chunk
    records_per_sec = total_rows / elapsed

    # --- BASELINE config 5: 64-config ε-sweep as ONE compiled program. ---
    sweep_detail = _bench_eps_sweep(jax, jnp, on_tpu)

    # --- Host ingest: vectorized vocab factorization (columnar.encode). ---
    ingest_detail = _bench_ingest()

    # --- End-to-end: Netflix-format file -> DP result, overlapped ingest. ---
    e2e_detail = _bench_end_to_end(on_tpu)

    # --- 10^7-partition blocked aggregation (bounded memory). ---
    large_p_detail = _bench_large_p(jax, on_tpu)

    # --- 10^7-partition standalone selection, O(kept) transfers. ---
    select_detail = _bench_select_partitions(jax, on_tpu)

    # --- Meshed reshard: host-staged vs collective on the CPU mesh. ---
    reshard_detail = _bench_meshed_reshard(on_tpu)

    # --- Multi-host topology: process count, per-process ingest overlap,
    # cross-host exchange volume (0 on a single-controller run). ---
    multihost_detail = _bench_multihost()

    # --- Resident multi-tenant service: jobs/sec, latency percentiles,
    # compile reuse across tenants, ledger reconciliation. ---
    service_detail = _bench_service(on_tpu)

    # --- Megabatched serving: saturated open-loop micro-job load,
    # per-job path vs the coalescing tier (jobs/sec, p50/p99, batch
    # occupancy, launches per N jobs, the single-row-job floor). ---
    megabatch_detail = _bench_megabatch(on_tpu)

    # --- Fleet operations: mini scale-UP, drain-and-migrate, and the
    # 2-wave rolling-restart drill (wall time + counter deltas). ---
    fleet_detail = _bench_fleet(on_tpu)

    # --- Chaos campaign: composed-fault trials with the full invariant
    # check (wall time per trial, what fired, storage-seam counters). ---
    chaos_detail = _bench_chaos(on_tpu)

    # --- Numeric armor: safe-vs-fast release cost, compensated-vs-naive
    # accumulation error in ULPs, snapped/geometric noise draw rates. ---
    numeric_detail = _bench_numeric(on_tpu)

    # --- PLD fast composition: batched-vs-sequential compositions/sec,
    # epsilon saved at k=100, cache hit rate, admission capacity. ---
    pld_detail = _bench_pld(on_tpu)

    # --- BASELINE configs 1-3 (LocalBackend ref, Gaussian+public,
    # compound combiner). ---
    baseline_detail = _bench_baseline_configs(jax, jnp, on_tpu)

    # Noise-distribution fidelity: KS statistic of 1M device noise draws
    # vs the CPU reference distribution at the same calibrated stddev
    # (BASELINE.json metric "noise-dist KS-stat vs CPU ref").
    from scipy import stats as scipy_stats
    from pipelinedp_tpu.ops import noise as noise_ops
    sum_std = float(stds[1])
    draws = np.asarray(
        noise_ops.laplace_noise(jax.random.PRNGKey(7), (1_000_000,),
                                jnp.float32(sum_std)))
    ks = float(
        scipy_stats.kstest(draws,
                           scipy_stats.laplace(scale=sum_std /
                                               np.sqrt(2.0)).cdf).statistic)
    # Fault-tolerance counters accumulated across every benchmark above:
    # a healthy run records zeros; nonzero retries/fallbacks/degradations
    # in a receipt flag the run as having survived adversity (and explain
    # any throughput dip) instead of silently hiding it.
    from pipelinedp_tpu.runtime import health as rt_health
    from pipelinedp_tpu.runtime import telemetry as rt_telemetry
    # Every declared counter (telemetry.REGISTRY is the single source of
    # truth), not a hand-maintained list that drifts as counters grow.
    fault_counters = {
        name: rt_telemetry.counters.get(name, 0)
        for name in rt_telemetry.counter_names()
    }
    # Per-phase wall-time stats (telemetry.record_duration) and the
    # health state machine's per-job verdicts: a receipt that stalled,
    # degraded or quarantined says so — and says where the time went.
    # Timings are scoped by job (the same job_scope discipline counter
    # forwarding uses), so a receipt covering several jobs run in this
    # process never mixes their phases; "_process" is the unscoped
    # aggregate for phases recorded outside any job.
    def _rounded(stats_by_name):
        return {
            name: {k: round(v, 4) for k, v in stats.items()}
            for name, stats in stats_by_name.items()
        }

    phase_timings = {
        job: _rounded(stats)
        for job, stats in rt_telemetry.job_timing_snapshot().items()
    }
    phase_timings["_process"] = _rounded(rt_telemetry.timing_snapshot())
    job_health = {
        job: {
            "state": snap["state"],
            "counters": snap["counters"],
            "journal_quarantined": snap["journal_quarantined"],
            **({"planned_devices": snap["planned_devices"],
                "live_devices": snap["live_devices"]}
               if snap.get("planned_devices") is not None else {}),
        }
        for job, snap in rt_health.snapshot_all().items()
    }
    # Fleet observability keys: the device-memory watermark the run
    # peaked at (platform memory stats on TPU, the byte-accounted
    # fallback on CPU), and the privacy-budget odometer reconciled
    # against the headline accountant's ledger — a receipt whose
    # odometer does not reconcile is flagging a registration that
    # bypassed the audit trail.
    from pipelinedp_tpu.runtime import observability as rt_obs
    memory_watermarks = rt_obs.memory_watermark()
    odo = rt_obs.odometer_report(accountant=accountant)
    odometer_detail = {
        "mechanisms": odo["mechanisms"],
        "spent_epsilon": round(odo["spent_epsilon"], 8),
        "total_epsilon": odo["total_epsilon"],
        "remaining_epsilon": round(odo["remaining_epsilon"], 8),
        "reconciled": odo["reconciled"],
        "by_metric": {
            metric: sum(1 for r in odo["records"]
                        if (r["metric"] or "?") == metric)
            for metric in sorted({r["metric"] or "?"
                                  for r in odo["records"]})
        },
    }
    # Static-analysis gate state rides along with the perf numbers: the
    # finding count + rule version in every receipt means a lint
    # regression (or a rule-set change that re-opens triage) shows up
    # next to the throughput it ships with.
    try:
        from pipelinedp_tpu import staticcheck as sc
        from pipelinedp_tpu.staticcheck import cli as sc_cli
        from pipelinedp_tpu.staticcheck import rules as sc_rules
        from pipelinedp_tpu.staticcheck import threads as sc_threads
        sc_started = time.perf_counter()
        sc_analysis, sc_active, sc_baselined, sc_stale, sc_mods = \
            sc.run_tree()
        sc_seconds = time.perf_counter() - sc_started
        staticcheck_detail = {
            "findings": len(sc_active),
            "baselined": len(sc_baselined),
            "stale_baseline_entries": len(sc_stale),
            "rules_version": sc.RULES_VERSION,
            # Full-tree analysis wall time + per-rule finding counts:
            # analyzer runtime regressions (the dataflow fixpoints are
            # the dominant cost; budget: <= 10s on the tier-1 runner)
            # and per-family triage drift are both visible in the perf
            # trajectory.
            "analysis_seconds": round(sc_seconds, 3),
            "per_rule": sc_cli.per_rule_counts(sc_analysis, sc_active,
                                               sc_baselined),
            # Structurally discovered thread roots (thread-escape's
            # quantifier domain): a new threaded subsystem that does
            # NOT grow this count escaped the race analysis.
            "thread_roots": len(sc_threads.discover_roots(
                sc_rules._call_graph(sc_mods))),
        }
    except Exception as e:  # noqa: BLE001 - the receipt must survive analyzer breakage; tests/test_staticcheck.py owns failing on it
        staticcheck_detail = {"error": f"{type(e).__name__}: {e}"}
    builder_receipt = _builder_receipt_summary() if fallback else None
    print(
        json.dumps({
            "metric": "DP SUM+COUNT records/sec/chip (eps=1, private "
                      "partition selection, fused kernel)",
            "value": round(records_per_sec),
            "unit": "records/sec/chip",
            "vs_baseline": round(records_per_sec / NORTH_STAR_RECORDS_PER_SEC,
                                 4),
            "detail": {
                "rows": total_rows,
                "chunk": chunk,
                "partitions": args.partitions,
                "users": args.users,
                "elapsed_sec": round(elapsed, 3),
                "device": str(device),
                "kept_partitions": int(np.asarray(keep).sum()),
                "noise_ks_stat_vs_cpu_ref": round(ks, 5),
                **sweep_detail,
                **ingest_detail,
                **e2e_detail,
                **large_p_detail,
                **select_detail,
                **reshard_detail,
                **multihost_detail,
                **service_detail,
                **megabatch_detail,
                **fleet_detail,
                **chaos_detail,
                **numeric_detail,
                **pld_detail,
                **baseline_detail,
                "runtime_fault_counters": fault_counters,
                "runtime_phase_timings": phase_timings,
                "runtime_job_health": job_health,
                "memory_watermarks": memory_watermarks,
                "odometer": odometer_detail,
                "staticcheck": staticcheck_detail,
                **({"device_fallback": fallback} if fallback else {}),
                # CPU-fallback runs carry the newest committed device
                # evidence so a tunnel-dropped driver round still shows it.
                **({"builder_receipt": builder_receipt}
                   if builder_receipt else {}),
            },
        }))


def _main_with_device_failover():
    """Runs main(); if the device dies MID-RUN (e.g. a remote-compile tunnel
    drops after successful init — observed failure mode), re-runs the whole
    benchmark CPU-only in a fresh subprocess so the driver still records a
    parseable (clearly-flagged) line instead of rc=1."""
    import subprocess
    argv = sys.argv[1:]
    try:
        main()
        return 0
    except Exception as e:  # noqa: BLE001 - any device/runtime failure
        if "--cpu" in argv:
            raise
        msg = (str(e).splitlines() or [""])[0][:200]
        _log(f"benchmark failed mid-run ({type(e).__name__}: {msg}); "
             "re-running CPU-only")
        passthrough, skip, requested_rows = [], False, None
        for i, a in enumerate(argv):
            if skip:
                skip = False
                requested_rows = int(a)
            elif a == "--rows":
                skip = True  # drop the flag AND its value token
            elif a.startswith("--rows="):
                requested_rows = int(a.split("=", 1)[1])
            else:
                passthrough.append(a)
        rerun_rows = min(requested_rows or 4_000_000, 4_000_000)
        r = subprocess.run(
            [sys.executable, __file__, "--cpu", "--rows", str(rerun_rows)] +
            passthrough,
            capture_output=True, text=True)
        if r.returncode == 0 and r.stdout.strip():
            line = r.stdout.strip().splitlines()[-1]
            try:
                payload = json.loads(line)
                payload.setdefault("detail", {})["device_fallback"] = (
                    f"device died mid-run: {type(e).__name__}; CPU rerun")
                receipt = _builder_receipt_summary()
                if receipt:
                    payload["detail"].setdefault("builder_receipt", receipt)
                print(json.dumps(payload))
                return 0
            except json.JSONDecodeError:
                pass
        _log(f"CPU rerun also failed: rc={r.returncode}")
        raise


if __name__ == "__main__":
    sys.exit(_main_with_device_failover())
