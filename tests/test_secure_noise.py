"""Secure discrete noise: table sampler exactness, grid release, e2e parity.

The device sampler (ops/secure_noise.py) must (a) reproduce the discrete
Laplace / discrete Gaussian PMFs exactly (to table precision), (b) release
values on the snapping grid only, and (c) agree distributionally with the
native C++ host samplers (native/dp_primitives.cc) where available.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as scipy_stats

import pipelinedp_tpu as pdp
from pipelinedp_tpu.aggregate_params import NoiseKind
from pipelinedp_tpu.ops import secure_noise

N_DRAWS = 200_000


def _draws(std, kind, max_atoms=256, n=N_DRAWS, seed=0):
    thr_hi, thr_lo, gran = secure_noise.build_table(std, kind, max_atoms)
    atoms = secure_noise.sample_discrete(jax.random.PRNGKey(seed), (n,),
                                         jnp.asarray(thr_hi),
                                         jnp.asarray(thr_lo))
    return np.asarray(atoms), gran


class TestTableSampler:

    def test_laplace_pmf_matches_analytic(self):
        std = 10.0
        atoms, gran = _draws(std, NoiseKind.LAPLACE)
        b = std / math.sqrt(2.0)
        t = b / gran
        alpha = math.exp(-1.0 / t)
        ks = np.arange(-40, 41)
        expected = (1 - alpha) / (1 + alpha) * alpha**np.abs(ks)
        counts = np.array([(atoms == k).sum() for k in ks]) / len(atoms)
        # Multinomial sampling error ~ sqrt(p/n) ~ 6e-4 at the mode.
        np.testing.assert_allclose(counts, expected, atol=5e-3)

    def test_gaussian_pmf_matches_analytic(self):
        std = 4.0
        atoms, gran = _draws(std, NoiseKind.GAUSSIAN)
        t = std / gran
        ks = np.arange(-int(4 * t), int(4 * t) + 1)
        w = np.exp(-ks.astype(float)**2 / (2 * t * t))
        expected = w / w.sum()
        counts = np.array([(atoms == k).sum() for k in ks]) / len(atoms)
        np.testing.assert_allclose(counts, expected, atol=5e-3)

    @pytest.mark.parametrize("kind", [NoiseKind.LAPLACE, NoiseKind.GAUSSIAN])
    @pytest.mark.parametrize("std", [0.5, 3.0, 100.0])
    def test_moments(self, kind, std):
        atoms, gran = _draws(std, kind, max_atoms=2048)
        noise = atoms * gran
        se = std / math.sqrt(N_DRAWS)
        assert abs(noise.mean()) < 5 * se
        assert noise.std() == pytest.approx(std, rel=0.02)

    def test_symmetric(self):
        atoms, _ = _draws(5.0, NoiseKind.LAPLACE)
        assert abs((atoms > 0).mean() - (atoms < 0).mean()) < 0.01

    def test_degenerate_zero_std(self):
        thr_hi, thr_lo, gran = secure_noise.build_table(
            0.0, NoiseKind.LAPLACE, 64)
        atoms = secure_noise.sample_discrete(jax.random.PRNGKey(0), (1000,),
                                             jnp.asarray(thr_hi),
                                             jnp.asarray(thr_lo))
        assert np.all(np.asarray(atoms) == 0)

    def test_native_parity_two_sample(self):
        # Two-sample KS between the device table sampler and the native C++
        # discrete Laplace at an integer scale.
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        t = 4  # discrete Laplace scale (grid units)
        std = math.sqrt(2.0) * t  # b = t, gran = 1 by construction:
        thr_hi, thr_lo, gran = secure_noise.build_table(
            std, NoiseKind.LAPLACE, max_atoms=256)
        assert gran == pytest.approx(1.0)
        device = np.asarray(
            secure_noise.sample_discrete(jax.random.PRNGKey(3), (50_000,),
                                         jnp.asarray(thr_hi),
                                         jnp.asarray(thr_lo)))
        native_lib = native.discrete_laplace(t, 1, 50_000)
        ks = scipy_stats.ks_2samp(device, native_lib)
        assert ks.pvalue > 1e-4


class TestSnappingCalibration:
    """Snapping maps neighbors at distance Delta up to floor(Delta/g)+1 grid
    steps apart; the table must widen the grid-unit noise scale accordingly
    or the release overspends epsilon."""

    @pytest.mark.parametrize("std,sens", [(1.41421356, 1.0), (70.0, 1.0),
                                          (500.0, 720.0), (4.0, 24.0)])
    def test_laplace_actual_eps_within_granted(self, std, sens):
        b = std / math.sqrt(2.0)
        granted_eps = sens / b
        thr_hi, thr_lo, gran = secure_noise.build_table(
            std, NoiseKind.LAPLACE, sensitivity=sens)
        # Recover the realized grid-unit scale t from the table PMF ratio.
        thr = (thr_hi.astype(np.uint64) << np.uint64(32)) | thr_lo.astype(
            np.uint64)
        pmf = np.diff(thr.astype(np.float64))
        K = (len(thr) - 1) // 2
        t = 1.0 / np.log(pmf[K] / pmf[K + 1])  # p(0)/p(1) = e^(1/t)
        delta_grid = math.floor(sens / gran) + 1
        actual_eps = delta_grid / t
        assert actual_eps <= granted_eps * 1.001, (gran, t)

    def test_compensation_cost_is_small(self):
        # At eps=1-ish budgets the widening costs only a few percent of std.
        std, sens = 34.0, 24.0  # b = 24/1 -> eps 1
        atoms, gran = None, None
        thr_hi, thr_lo, gran = secure_noise.build_table(
            std, NoiseKind.LAPLACE, sensitivity=sens)
        atoms = np.asarray(
            secure_noise.sample_discrete(jax.random.PRNGKey(0), (100_000,),
                                         jnp.asarray(thr_hi),
                                         jnp.asarray(thr_lo)))
        realized_std = (atoms * gran).std()
        assert std <= realized_std < std * 1.1

    def test_gaussian_scale_widened(self):
        std, sens = 10.0, 5.0
        thr_hi, thr_lo, gran = secure_noise.build_table(
            std, NoiseKind.GAUSSIAN, sensitivity=sens)
        atoms = np.asarray(
            secure_noise.sample_discrete(jax.random.PRNGKey(1), (200_000,),
                                         jnp.asarray(thr_hi),
                                         jnp.asarray(thr_lo)))
        realized = (atoms * gran).std()
        delta_grid = math.floor(sens / gran) + 1
        required = delta_grid * gran * std / sens
        assert realized == pytest.approx(required, rel=0.02)
        assert realized >= std


class TestGranularity:

    def test_gran_is_power_of_two(self):
        for std in (0.3, 1.0, 7.7, 1e4):
            _, _, gran = secure_noise.build_table(std, NoiseKind.LAPLACE)
            assert math.log2(gran) == round(math.log2(gran))

    def test_gran_scales_with_std(self):
        _, _, g1 = secure_noise.build_table(1.0, NoiseKind.LAPLACE)
        _, _, g2 = secure_noise.build_table(1024.0, NoiseKind.LAPLACE)
        assert g2 / g1 == pytest.approx(1024.0)


class TestSecureEngineEndToEnd:

    ROWS = [("u%d" % (i % 40), "pk%d" % (i % 5), float(i % 7))
            for i in range(600)]
    EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])

    def _run(self, backend, eps=1e6):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=5,
            max_contributions_per_partition=30,
            min_value=0.0,
            max_value=7.0)
        result = engine.aggregate(self.ROWS, params, self.EXTRACTORS,
                                  ["pk%d" % i for i in range(5)])
        accountant.compute_budgets()
        return dict(result)

    def test_matches_local_at_huge_eps(self):
        expected = self._run(pdp.LocalBackend(seed=0))
        got = self._run(pdp.TPUBackend(noise_seed=0, secure_noise=True))
        for pk in expected:
            # Secure snapping quantizes: tolerance = a few grid steps.
            assert got[pk].count == pytest.approx(expected[pk].count,
                                                  abs=0.05)
            assert got[pk].sum == pytest.approx(expected[pk].sum, abs=0.05)

    def test_outputs_live_on_grid(self):
        backend = pdp.TPUBackend(noise_seed=1, secure_noise=True)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=5,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=7.0)
        result = engine.aggregate(self.ROWS, params, self.EXTRACTORS,
                                  ["pk%d" % i for i in range(5)])
        accountant.compute_budgets()
        result = dict(result)
        # Recover the grid from the calibrated noise std.
        from pipelinedp_tpu import combiners as comb, executor
        acc2 = pdp.NaiveBudgetAccountant(total_epsilon=2.0, total_delta=1e-6)
        compound = comb.create_compound_combiner(params, acc2)
        acc2.compute_budgets()
        std = executor.compute_noise_stds(compound, params)[0]
        _, _, gran = secure_noise.build_table(float(std), NoiseKind.LAPLACE)
        for pk, metrics in result.items():
            ratio = metrics.sum / gran
            assert ratio == pytest.approx(round(ratio), abs=1e-3), (pk, gran)

    def test_sharded_secure(self):
        from pipelinedp_tpu.parallel import make_mesh
        mesh = make_mesh(n_devices=4)
        expected = self._run(pdp.LocalBackend(seed=0))
        got = self._run(
            pdp.TPUBackend(mesh=mesh, noise_seed=2, secure_noise=True))
        for pk in expected:
            assert got[pk].count == pytest.approx(expected[pk].count,
                                                  abs=0.05)

    def test_noised_distribution_secure(self):
        # At eps=1 the secure path's released noise must match the target
        # std and stay integer on the count grid.
        backend = pdp.TPUBackend(noise_seed=3, secure_noise=True)
        counts = []
        for seed in range(150):
            backend.noise_seed = seed
            got = self._run(backend, eps=1.0)
            counts.append(got["pk0"].count)
        counts = np.asarray(counts)
        true_count = 120.0
        resid = counts - true_count
        assert abs(resid.mean()) < 3 * resid.std() / math.sqrt(len(resid))

    def _run_percentile(self, backend, eps=1e6, seed=None):
        if seed is not None:
            backend.noise_seed = seed
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50),
                     pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=5,
            max_contributions_per_partition=30,
            min_value=0.0,
            max_value=7.0)
        result = engine.aggregate(self.ROWS, params, self.EXTRACTORS,
                                  ["pk%d" % i for i in range(5)])
        accountant.compute_budgets()
        return dict(result)

    def test_secure_percentile_matches_local_at_huge_eps(self):
        # The secure-release guarantee is now metric-complete: PERCENTILE
        # runs through the same snapped table-sampled discrete noise as
        # COUNT/SUM (quantile-tree node counts are integers; executor
        # quantile_outputs secure branch).
        expected = self._run_percentile(pdp.LocalBackend(seed=0))
        got = self._run_percentile(
            pdp.TPUBackend(noise_seed=0, secure_noise=True))
        for pk in expected:
            assert got[pk].percentile_50 == pytest.approx(
                expected[pk].percentile_50, abs=0.2)
            assert got[pk].percentile_90 == pytest.approx(
                expected[pk].percentile_90, abs=0.2)

    def test_secure_percentile_sharded(self):
        from pipelinedp_tpu.parallel import make_mesh
        mesh = make_mesh(n_devices=4)
        expected = self._run_percentile(pdp.LocalBackend(seed=0))
        got = self._run_percentile(
            pdp.TPUBackend(mesh=mesh, noise_seed=1, secure_noise=True))
        for pk in expected:
            assert got[pk].percentile_50 == pytest.approx(
                expected[pk].percentile_50, abs=0.2)

    def test_secure_percentile_blocked_routes(self):
        # Secure snapped PERCENTILE through the blocked large-P route,
        # single-device and meshed (per-block quantile trees + secure
        # tables through _block_trace).
        from pipelinedp_tpu.parallel import make_mesh
        expected = self._run_percentile(pdp.LocalBackend(seed=0))
        for backend in (
                pdp.TPUBackend(noise_seed=0, secure_noise=True,
                               large_partition_threshold=2),
                pdp.TPUBackend(mesh=make_mesh(n_devices=4), noise_seed=0,
                               secure_noise=True,
                               large_partition_threshold=2),
        ):
            got = self._run_percentile(backend)
            for pk in expected:
                assert got[pk].percentile_50 == pytest.approx(
                    expected[pk].percentile_50, abs=0.2)

    def test_secure_percentile_noise_is_calibrated(self):
        # At a real budget the released median must be unbiased around the
        # non-secure release (same per-level std; only the sampler differs).
        backend = pdp.TPUBackend(secure_noise=True)
        released = np.asarray([
            self._run_percentile(backend, eps=5.0, seed=s)["pk0"].
            percentile_50 for s in range(60)
        ])
        truth = self._run_percentile(pdp.LocalBackend(seed=0))[
            "pk0"].percentile_50
        assert abs(released.mean() - truth) < max(
            4 * released.std() / math.sqrt(len(released)), 0.05)

    def test_quantile_slot_secure_table_ks(self):
        # KS receipt on the actual quantile-slot noise: build the kernel's
        # secure tables from the SAME std/sensitivity plumbing the percentile
        # path uses, sample its discrete atoms, and KS against the ideal
        # discrete Laplace at the compensated scale.
        from pipelinedp_tpu import combiners as comb, executor
        params = pdp.AggregateParams(metrics=[pdp.Metrics.PERCENTILE(50)],
                                     max_partitions_contributed=5,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=7.0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=2.0, total_delta=1e-6)
        compound = comb.create_compound_combiner(params, acc)
        acc.compute_budgets()
        stds = executor.compute_noise_stds(compound, params)
        sens = executor.compute_noise_sensitivities(compound, params)
        assert sens[0] == pytest.approx(5 * 30)  # l1 = l0 * linf (Laplace)
        thr_hi, thr_lo, gran = secure_noise.build_tables(
            stds, NoiseKind.LAPLACE, sensitivities=sens)
        atoms = np.asarray(
            secure_noise.sample_discrete(jax.random.PRNGKey(11), (200_000,),
                                         jnp.asarray(thr_hi[0]),
                                         jnp.asarray(thr_lo[0])))
        # Ideal discrete-Laplace CDF at the snapping-compensated grid scale.
        b = (math.floor(sens[0] / gran[0]) + 1) * (
            stds[0] / math.sqrt(2.0)) / sens[0] / gran[0] * gran[0]
        t = (math.floor(sens[0] / gran[0]) + 1) * (
            stds[0] / math.sqrt(2.0)) / sens[0]
        xs = np.arange(atoms.min(), atoms.max() + 1)
        pmf = np.exp(-np.abs(xs) / t)
        pmf /= pmf.sum()
        cdf = np.cumsum(pmf)
        emp = np.searchsorted(np.sort(atoms), xs, side="right") / len(atoms)
        ks = np.max(np.abs(emp - cdf))
        assert ks < 0.01, f"KS={ks}, b={b}"
